"""Enums shared across domains.

Parity: reference ``src/torchmetrics/utilities/enums.py:56-155``.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional


class EnumStr(str, Enum):
    """Case-insensitive string enum with a friendly ``from_str`` constructor."""

    @staticmethod
    def _name() -> str:
        return "Task"

    @classmethod
    def from_str(cls, value: str, source: str = "Key") -> "EnumStr":
        try:
            return cls(value.lower().replace("-", "_"))
        except ValueError as err:
            valid = [m.value for m in cls]
            raise ValueError(
                f"Invalid {cls._name()}: expected one of {valid}, but got {value}."
            ) from err

    def __str__(self) -> str:
        return self.value


class DataType(EnumStr):
    """Legacy input-mode inference for classification inputs."""

    @staticmethod
    def _name() -> str:
        return "Data type"

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    """Averaging strategy for multi-class/multi-label reductions."""

    @staticmethod
    def _name() -> str:
        return "Average method"

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    """Multi-dim multi-class averaging."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"


class ClassificationTask(EnumStr):
    """Task selector for the task-dispatch wrapper classes."""

    @staticmethod
    def _name() -> str:
        return "Classification"

    BINARY = "binary"
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoBinary(EnumStr):
    @staticmethod
    def _name() -> str:
        return "Classification"

    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoMultilabel(EnumStr):
    @staticmethod
    def _name() -> str:
        return "Classification"

    BINARY = "binary"
    MULTICLASS = "multiclass"


def _check_average_arg(average: Optional[str], allowed=("micro", "macro", "weighted", "none", None)) -> Optional[str]:
    if average not in allowed:
        raise ValueError(f"Argument `average` must be one of {allowed}, got {average}.")
    return average
