"""Flat-npz (de)serialization for parameter pytrees.

The converted-weights artifacts produced by ``python -m torchmetrics_tpu.convert``
are plain ``.npz`` archives whose keys are ``/``-joined pytree paths — loadable with
nothing but numpy, inspectable with ``np.load``, and stable across jax versions
(unlike pickled pytrees). Reference counterpart: the reference ships torch ``.pth``
checkpoints (e.g. ``functional/image/lpips_models/*.pth``); npz is the JAX-native
equivalent.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

_SEP = "/"


def flatten_tree(tree: Dict[str, Any], prefix: str = "") -> Dict[str, np.ndarray]:
    """Flatten a nested dict-of-arrays into ``{"a/b/c": ndarray}``."""
    flat: Dict[str, np.ndarray] = {}
    for key, value in tree.items():
        if _SEP in str(key):
            raise ValueError(f"Tree keys must not contain {_SEP!r}, got {key!r}")
        path = f"{prefix}{_SEP}{key}" if prefix else str(key)
        if isinstance(value, dict):
            flat.update(flatten_tree(value, path))
        else:
            flat[path] = np.asarray(value)
    return flat


def unflatten_tree(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Inverse of :func:`flatten_tree`."""
    tree: Dict[str, Any] = {}
    for path, value in flat.items():
        parts = path.split(_SEP)
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return tree


def save_tree_npz(path: str, tree: Dict[str, Any]) -> str:
    """Write a nested param pytree to a flat ``.npz`` archive; returns the real path.

    ``np.savez`` silently appends ``.npz`` to extension-less paths — normalize up
    front so callers (checksum manifests, extension-dispatching loaders) always see
    the filename actually written.
    """
    if not path.endswith(".npz"):
        path = path + ".npz"
    np.savez(path, **flatten_tree(tree))
    return path


def load_tree_npz(path: str) -> Dict[str, Any]:
    """Load a flat ``.npz`` archive back into a nested param pytree."""
    with np.load(path) as data:
        return unflatten_tree({name: data[name] for name in data.files})
