"""Plotting support for ``Metric.plot()``.

Parity: reference ``src/torchmetrics/utilities/plot.py:64-365``. Optional matplotlib
dependency; all data is pulled to host numpy before plotting.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import numpy as np

from torchmetrics_tpu.utils.imports import _MATPLOTLIB_AVAILABLE

if _MATPLOTLIB_AVAILABLE:
    import matplotlib
    import matplotlib.pyplot as plt

    _AX_TYPE = matplotlib.axes.Axes
    _PLOT_OUT_TYPE = Tuple[plt.Figure, matplotlib.axes.Axes]
else:  # pragma: no cover
    _AX_TYPE = object
    _PLOT_OUT_TYPE = tuple

_error_msg = "matplotlib is required for plotting but is not installed."


def _get_col_row_split(n: int) -> Tuple[int, int]:
    """Smallest grid (rows, cols) that fits ``n`` plots."""
    nsq = np.sqrt(n)
    if int(nsq) == nsq:
        return int(nsq), int(nsq)
    if np.floor(nsq) * np.ceil(nsq) >= n:
        return int(np.floor(nsq)), int(np.ceil(nsq))
    return int(np.ceil(nsq)), int(np.ceil(nsq))


def trim_axs(axs, nb: int):
    axs = np.asarray(axs).reshape(-1)
    for ax in axs[nb:]:
        ax.remove()
    return axs[:nb]


def _to_np(x):
    if isinstance(x, dict):
        return {k: _to_np(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_to_np(v) for v in x]
    return np.asarray(x)


def plot_single_or_multi_val(
    val,
    ax: Optional[Any] = None,
    higher_is_better: Optional[bool] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """Plot a single scalar result, a per-class vector, or a sequence over steps."""
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(_error_msg)
    fig, ax = (None, ax) if ax is not None else plt.subplots()
    if fig is None:
        fig = ax.get_figure()

    val = _to_np(val)
    if isinstance(val, dict):
        for i, (k, v) in enumerate(val.items()):
            v = np.atleast_1d(v)
            if v.size == 1:
                ax.plot(i, float(v.reshape(-1)[0]), "o", label=k)
            else:
                ax.plot(v, label=k)
        ax.legend()
    elif isinstance(val, list):
        steps = np.arange(len(val))
        arr = np.stack([np.atleast_1d(v) for v in val])
        for c in range(arr.shape[1]):
            label = f"{legend_name or 'class'} {c}" if arr.shape[1] > 1 else (name or "metric")
            ax.plot(steps, arr[:, c], marker="o", label=label)
        ax.legend()
        ax.set_xlabel("Step")
    else:
        arr = np.atleast_1d(val)
        if arr.size == 1:
            ax.plot([0], [float(arr.reshape(-1)[0])], "o", label=name or "metric")
        elif arr.ndim >= 2:
            # multi-group values (e.g. per-class stat scores [C, 5]): one point
            # cluster per leading index (reference ``utilities/plot.py:98-110``)
            for i, row in enumerate(arr.reshape(arr.shape[0], -1)):
                ax.plot([i] * row.size, row, "o", linestyle="None",
                        label=f"{legend_name or 'group'} {i}")
        else:
            ax.bar(np.arange(arr.size), arr, label=name or "metric")
        ax.legend()
    if lower_bound is not None or upper_bound is not None:
        ax.set_ylim(lower_bound, upper_bound)
    if name:
        ax.set_title(name)
    ax.grid(True, alpha=0.3)
    return fig, ax


def plot_confusion_matrix(
    confmat,
    ax: Optional[Any] = None,
    add_text: bool = True,
    labels: Optional[Sequence[Union[str, int]]] = None,
    cmap: Optional[str] = None,
):
    """Heatmap plot of a ``[C, C]`` (or ``[N, 2, 2]`` multilabel) confusion matrix."""
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(_error_msg)
    confmat = np.asarray(confmat)
    if confmat.ndim == 3:  # multilabel [N, 2, 2]
        nb, n_classes = confmat.shape[0], 2
        rows, cols = _get_col_row_split(nb)
    else:
        nb, n_classes, rows, cols = 1, confmat.shape[0], 1, 1

    if labels is not None and confmat.ndim != 3 and len(labels) != n_classes:
        raise ValueError("Expected number of elements in `labels` to match number of classes.")
    fig, axs = plt.subplots(nrows=rows, ncols=cols) if ax is None else (ax.get_figure(), ax)
    axs = trim_axs(axs, nb) if nb > 1 else [axs]
    for i in range(nb):
        cm = confmat[i] if confmat.ndim == 3 else confmat
        ax_ = axs[i]
        im = ax_.imshow(cm, cmap=cmap or "viridis")
        ticks = labels if (labels is not None and confmat.ndim != 3) else np.arange(cm.shape[0])
        ax_.set_xticks(np.arange(cm.shape[0]))
        ax_.set_yticks(np.arange(cm.shape[0]))
        ax_.set_xticklabels(ticks)
        ax_.set_yticklabels(ticks)
        ax_.set_xlabel("Predicted class")
        ax_.set_ylabel("True class")
        if add_text:
            for ii in range(cm.shape[0]):
                for jj in range(cm.shape[1]):
                    v = cm[ii, jj]
                    txt = f"{v:.2f}" if np.issubdtype(cm.dtype, np.floating) else str(int(v))
                    ax_.text(jj, ii, txt, ha="center", va="center")
    fig.colorbar(im, ax=axs[-1] if nb > 1 else axs[0])
    return fig, axs[0] if nb == 1 else axs


def plot_curve(
    curve,
    score=None,
    ax: Optional[Any] = None,
    label_names: Optional[Tuple[str, str]] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """Plot a (x, y, thresholds) style curve (ROC / PR)."""
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(_error_msg)
    x, y = _to_np(curve[0]), _to_np(curve[1])
    fig, ax = (None, ax) if ax is not None else plt.subplots()
    if fig is None:
        fig = ax.get_figure()
    if isinstance(x, list) or (hasattr(x, "ndim") and np.asarray(x, dtype=object).ndim and isinstance(x, list)):
        for i, (xi, yi) in enumerate(zip(x, y)):
            label = f"{legend_name or 'class'} {i}"
            if score is not None:
                label += f" (score={float(np.asarray(score)[i]):.3f})"
            ax.plot(np.asarray(xi), np.asarray(yi), label=label)
    elif np.asarray(x).ndim == 2:
        for i in range(np.asarray(x).shape[0]):
            label = f"{legend_name or 'class'} {i}"
            if score is not None:
                label += f" (score={float(np.asarray(score)[i]):.3f})"
            ax.plot(x[i], y[i], label=label)
    else:
        label = name or "curve"
        if score is not None:
            label += f" (score={float(score):.3f})"
        ax.plot(x, y, label=label)
    if label_names:
        ax.set_xlabel(label_names[0])
        ax.set_ylabel(label_names[1])
    ax.legend()
    ax.grid(True, alpha=0.3)
    return fig, ax
