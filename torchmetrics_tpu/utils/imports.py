"""Optional-dependency feature flags.

Parity: reference ``src/torchmetrics/utilities/imports.py:22-67``. The reference gates
40+ optional backends; here the heavy metrics run on Flax models in-process, so the flag
set is smaller — external flags remain for test-reference packages and host-callback
metrics (PESQ/STOI-style) that have no TPU-native equivalent.
"""

from __future__ import annotations

import importlib.util
import shutil
import sys


def _package_available(name: str) -> bool:
    try:
        return importlib.util.find_spec(name) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


_JAX_AVAILABLE = _package_available("jax")
_FLAX_AVAILABLE = _package_available("flax")
_MATPLOTLIB_AVAILABLE = _package_available("matplotlib")
_SCIPY_AVAILABLE = _package_available("scipy")
_SKLEARN_AVAILABLE = _package_available("sklearn")
_TRANSFORMERS_AVAILABLE = _package_available("transformers")
_NLTK_AVAILABLE = _package_available("nltk")
_REGEX_AVAILABLE = _package_available("regex")
_TORCH_AVAILABLE = _package_available("torch")  # CPU torch: only for weight conversion
_ORBAX_AVAILABLE = _package_available("orbax.checkpoint")
_PESQ_AVAILABLE = _package_available("pesq")
_PYSTOI_AVAILABLE = _package_available("pystoi")
_GAMMATONE_AVAILABLE = _package_available("gammatone")
_ONNXRUNTIME_AVAILABLE = _package_available("onnxruntime")
_PYCOCOTOOLS_AVAILABLE = _package_available("pycocotools")
_TORCHVISION_AVAILABLE = _package_available("torchvision")
_SENTENCEPIECE_AVAILABLE = _package_available("sentencepiece")
_TQDM_AVAILABLE = _package_available("tqdm")
_MECAB_AVAILABLE = _package_available("MeCab")
_IPADIC_AVAILABLE = _package_available("ipadic")
_MECAB_KO_DIC_AVAILABLE = _package_available("mecab_ko_dic")

_PYTHON_GREATER_EQUAL_3_11 = sys.version_info >= (3, 11)
_LATEX_AVAILABLE = shutil.which("latex") is not None


def snapshot_weight_stamp(model_name_or_path: str):
    """(name, mtime, size) of every weights file in a local snapshot dir, so model
    caches keyed on it reload when the checkpoint on disk is replaced (e.g. the
    convert CLI overwriting the same directory). Cache-by-name (HF hub ids) stamps
    as empty."""
    import glob
    import os

    if not os.path.isdir(model_name_or_path):
        return ()
    stamps = []
    for pattern in ("flax_model*.msgpack", "pytorch_model*.bin", "model*.safetensors"):
        for path in sorted(glob.glob(os.path.join(model_name_or_path, pattern))):
            stat = os.stat(path)
            stamps.append((os.path.basename(path), stat.st_mtime_ns, stat.st_size))
    return tuple(stamps)


def load_flax_with_pt_fallback(model_cls, model_name_or_path: str, **kwargs):
    """``from_pretrained`` a transformers Flax model from a local snapshot, converting
    torch-only snapshots (e.g. a dropped HF download) on the fly via ``from_pt=True``.

    Shared by every HF-backed metric (BERTScore, InfoLM, CLIPScore) and the convert
    CLI so the fallback behavior cannot drift between call sites. When the snapshot
    directory *does* contain flax weights, a load failure is a corrupt file, not a
    torch-only snapshot — re-raised as-is so the true cause is not masked.
    """
    import glob
    import os

    try:
        return model_cls.from_pretrained(model_name_or_path, local_files_only=True, **kwargs)
    except (OSError, ValueError) as first_err:
        if os.path.isdir(model_name_or_path) and glob.glob(
            os.path.join(model_name_or_path, "flax_model*.msgpack")
        ):
            raise
        try:
            return model_cls.from_pretrained(
                model_name_or_path, local_files_only=True, from_pt=True, **kwargs
            )
        except Exception as second_err:
            raise second_err from first_err
