"""Framework exceptions.

Parity: reference ``src/torchmetrics/utilities/exceptions.py``.
"""


class TorchMetricsUserError(Exception):
    """Error raised on wrong usage of the metric API (lifecycle violations, bad kwargs)."""


class TorchMetricsUserWarning(UserWarning):
    """Warning raised on suspicious-but-legal usage of the metric API."""
