"""Array utilities: dim-zero reductions, one-hot, top-k selection, bincount.

Parity: reference ``src/torchmetrics/utilities/data.py:28-245``. TPU-first notes:

- The reference's XLA-safe one-hot ``_bincount`` fallback (``data.py:203-205``) is the
  *default* here — a compare-against-iota matmul-friendly formulation that compiles to
  static shapes and runs on the VPU/MXU, instead of a data-dependent scatter.
- ``dim_zero_cat`` accepts either an array or a Python list of arrays (list states).
- Everything is jit-compatible with static shapes unless documented otherwise.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def dim_zero_cat(x: Union[Array, List[Array], tuple]) -> Array:
    """Concatenate a (list of) array(s) along dim 0."""
    if isinstance(x, (jnp.ndarray, jax.Array)):
        return x
    if not isinstance(x, (list, tuple)):
        raise ValueError("`dim_zero_cat` expects an array or a list of arrays")
    if not x:
        raise ValueError("No samples to concatenate")
    x = [jnp.atleast_1d(jnp.asarray(v)) for v in x]
    return jnp.concatenate(x, axis=0)


def _halving_reduce(x: Array, op) -> Array:
    """Reduce a power-of-two minor axis by repeated halving.

    XLA:CPU lowers a minor-axis reduce to a scalar per-row loop (~13x slower than its
    major-axis reduce on [4096, 100] inputs); log2(n) elementwise ops on contiguous
    half-rows vectorise instead. Shapes are static, so this traces fine under jit.
    """
    while x.shape[-1] > 1:
        half = x.shape[-1] // 2
        x = op(x[..., :half], x[..., half:])
    return x[..., 0]


def first_argmax(x: Array, axis: int = -1) -> Array:
    """``jnp.argmax`` (first-max-wins ties) with a fast CPU path for 2D minor-axis.

    On TPU the native argmax reduce runs fine on the VPU; on CPU (including the
    virtual-device test/fallback mesh) the minor-axis tuple-reduce is pathologically
    slow, so pad the class axis to a power of two and run two halving trees: max, then
    min-index-of-max. Tie semantics match ``jnp.argmax`` exactly.
    """
    if jax.default_backend() != "cpu" or x.ndim != 2 or axis not in (1, -1) or x.shape[-1] < 2:
        return jnp.argmax(x, axis=axis)
    n = x.shape[-1]
    padded_n = 1
    while padded_n < n:
        padded_n *= 2
    if padded_n != n:
        fill = jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        x = jnp.pad(x, ((0, 0), (0, padded_n - n)), constant_values=fill)
    row_max = _halving_reduce(x, jnp.maximum)
    candidates = jnp.where(x == row_max[:, None], jnp.arange(padded_n, dtype=jnp.int32), padded_n)
    # clamp keeps the result a valid index even for degenerate rows (all-NaN rows have
    # no self-equal maximum); which in-range index a NaN row maps to is unspecified
    return jnp.minimum(_halving_reduce(candidates, jnp.minimum), n - 1)


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    """Flatten one level of nesting."""
    return [item for sublist in x for item in sublist]


def _flatten_dict(x: dict) -> tuple[dict, bool]:
    """Flatten dict-of-dicts one level; returns (flat, whether duplicates were found)."""
    new_dict = {}
    duplicates = False
    for key, value in x.items():
        if isinstance(value, dict):
            for k, v in value.items():
                if k in new_dict:
                    duplicates = True
                new_dict[k] = v
        else:
            if key in new_dict:
                duplicates = True
            new_dict[key] = value
    return new_dict, duplicates


def to_onehot(label_tensor: Array, num_classes: Optional[int] = None) -> Array:
    """Convert dense label array ``[N, ...]`` to one-hot ``[N, C, ...]``.

    Parity: reference ``utilities/data.py:79-120``; implemented as a broadcast compare
    against an iota (static shapes, VPU-friendly) rather than scatter.
    """
    if num_classes is None:
        raise ValueError("`num_classes` must be provided (static shape requirement under jit)")
    onehot = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int32, axis=1)
    return onehot


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary mask of the ``topk`` highest entries along ``dim``.

    Parity: reference ``utilities/data.py:123-160``.
    """
    if topk == 1:  # cheap argmax path
        idx = jnp.argmax(prob_tensor, axis=dim, keepdims=True)
        mask = jnp.zeros_like(prob_tensor, dtype=jnp.int32)
        return jnp.put_along_axis(mask, idx, 1, axis=dim, inplace=False)
    _, idx = jax.lax.top_k(jnp.moveaxis(prob_tensor, dim, -1), topk)
    num = prob_tensor.shape[dim]
    mask = (jax.nn.one_hot(idx, num, dtype=jnp.int32).sum(axis=-2) > 0).astype(jnp.int32)
    return jnp.moveaxis(mask, -1, dim)


def _bincount(x: Array, minlength: Optional[int] = None) -> Array:
    """Count occurrences of each value in ``x`` of non-negative ints.

    TPU-native: scatter-free. Small ranges use a broadcast compare (VPU); larger ranges
    use a one-hot matmul against a ones-vector (MXU), chunked over the data so the
    ``[chunk, minlength]`` one-hot stays in VMEM. Scatter-based ``segment_sum`` is
    ~1000x slower on TPU (serialized scatter-adds) — the reference's XLA fallback
    (``utilities/data.py:203-205``) had the right idea; here it is the only path.
    """
    if minlength is None:
        raise ValueError("`minlength` must be static under jit")
    x = x.reshape(-1)
    n = x.size
    if n == 0:
        return jnp.zeros(minlength, dtype=jnp.int32)
    # Pallas gate matches the kernel's contract exactly: only the regime where the
    # XLA path below is the f32 chunked scan (same 2^24-per-bin exactness), and only
    # bin ranges whose one-hot tile fits VMEM (the kernel shrinks its sample tile
    # with c_pad; past 8192 bins no tile size keeps it in budget).
    if 64 < minlength <= 8192 and n * minlength > (1 << 22):
        from torchmetrics_tpu.ops.pallas_kernels import pallas_enabled

        if pallas_enabled():
            # valid=None selects the unweighted kernel (only the [N] indices stream in)
            from torchmetrics_tpu.ops.pallas_kernels import bincount_pallas

            return bincount_pallas(x, None, minlength)
    if minlength <= 64 or n * minlength <= (1 << 22):
        iota = jnp.arange(minlength, dtype=x.dtype)
        return (x[:, None] == iota[None, :]).astype(jnp.int32).sum(axis=0)
    # chunked one-hot accumulation: pad to a multiple of chunk, mask the padding
    chunk = max(1, (1 << 22) // minlength)
    pad = (-n) % chunk
    xp = jnp.pad(x, (0, pad), constant_values=0)
    validp = jnp.pad(jnp.ones((n,), dtype=jnp.float32), (0, pad), constant_values=0.0)
    xp = xp.reshape(-1, chunk)
    validp = validp.reshape(-1, chunk)

    def body(acc, args):
        xc, vc = args
        oh = jax.nn.one_hot(xc, minlength, dtype=jnp.float32)
        return acc + jnp.einsum("nc,n->c", oh, vc), None

    acc0 = jnp.zeros((minlength,), dtype=jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (xp, validp))
    return acc.astype(jnp.int32)


def _flexible_bincount(x: Array) -> Array:
    """Bincount over the *observed* unique values — EAGER ONLY, raises under jit.

    Both the output shape (number of uniques) and the inner ``minlength`` are
    data-dependent, so no XLA formulation exists; ``int(jnp.max(x))`` forces a host
    sync by design. Callers (retrieval's per-query grouping) run at host-side
    compute time. Parity: reference ``utilities/data.py:210-228``.
    """
    if isinstance(x, jax.core.Tracer):
        raise ValueError(
            "`_flexible_bincount` has data-dependent output shapes and cannot run"
            " under jit; call it from host-side (eager) compute only."
        )
    x = x - jnp.min(x)
    unique_ids = jnp.unique(x)
    return _bincount(x, minlength=int(jnp.max(x)) + 1)[unique_ids]


def _cumsum(x: Array, axis: int = 0, dtype=None) -> Array:
    return jnp.cumsum(x, axis=axis, dtype=dtype)


def allclose(a: Array, b: Array, rtol: float = 1e-5, atol: float = 1e-8) -> bool:
    """Host-side allclose that tolerates dtype/shape mismatch (returns False)."""
    if a.shape != b.shape:
        return False
    return bool(jnp.allclose(a, b, rtol=rtol, atol=atol))


def safe_divide(num: Array, denom: Array, zero_division: float = 0.0) -> Array:
    """Elementwise division returning ``zero_division`` where ``denom == 0``.

    Parity: reference ``utilities/compute.py:_safe_divide``.
    """
    num = jnp.asarray(num)
    denom = jnp.asarray(denom)
    dtype = num.dtype if jnp.issubdtype(num.dtype, jnp.floating) else jnp.result_type(num, jnp.float32)
    num = num.astype(dtype)
    denom = denom.astype(dtype)
    zero_mask = denom == 0
    out = num / jnp.where(zero_mask, 1, denom)
    return jnp.where(zero_mask, jnp.asarray(zero_division, dtype=dtype), out)
