"""Orbax checkpoint/resume for metrics and collections.

The reference piggybacks on ``torch.save``/Lightning checkpoints (its ``state_dict``
hooks, reference ``metric.py:858-924``); the TPU-native analog is an orbax pytree
checkpoint: every state — including non-persistent ones, mid-epoch — is written as a
host pytree and restored into a freshly constructed metric of the same spec.

Preemption-safe layout (since the fault-tolerance PR): a checkpoint directory holds
``data/`` (the orbax pytree) plus ``INTEGRITY.json`` (a SHA-256 digest over every
leaf). Saves build the whole directory under a temp name and swap it into place with
directory renames, so a host preempted mid-checkpoint can never leave a truncated
tree masquerading as a valid resume point; loads verify the digest and raise
:class:`CheckpointIntegrityError` on mismatch. Checkpoints written by older
versions (the orbax tree directly at ``<path>``, no integrity record) still load.

Layout written to ``<path>/data``: one subtree per metric (collections nest by
metric name) holding ``states`` plus ``update_count`` so a restored metric resumes
exactly where the checkpoint was taken (no compute-before-update warning, same
results).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import uuid
from typing import Any, Dict, Optional, Union

import numpy as np

from torchmetrics_tpu.core.metric import _ROBUST_STATE_KEY, Metric

__all__ = [
    "CheckpointIntegrityError",
    "atomic_install_dir",
    "file_tree_digest",
    "load_checkpoint",
    "save_checkpoint",
]

_DATA_SUBDIR = "data"
_INTEGRITY_NAME = "INTEGRITY.json"
# displaced .old./.tmp. siblings younger than this may belong to a live
# concurrent save and are never swept (see save_checkpoint)
_STALE_SIBLING_AGE_S = 3600.0


class CheckpointIntegrityError(RuntimeError):
    """The checkpoint on disk is truncated, tampered, or half-written."""


def _require_orbax():
    from torchmetrics_tpu.utils.imports import _ORBAX_AVAILABLE

    if not _ORBAX_AVAILABLE:
        raise ModuleNotFoundError(
            "Metric checkpointing requires that `orbax-checkpoint` is installed."
            " Install it with `pip install orbax-checkpoint`."
        )
    import orbax.checkpoint as ocp

    return ocp


def _host_states(metric: Metric) -> Dict[str, Any]:
    """All states (not just persistent ones) as an orbax-friendly host pytree."""
    out: Dict[str, Any] = {}
    for key, value in metric.state_dict(persistent_only=False).items():
        if isinstance(value, list):
            # orbax drops empty containers; index dicts keep ordering explicit
            out[key] = {"__list__": {str(i): v for i, v in enumerate(value)}}
        elif isinstance(value, dict):  # state_dict's MaskedBuffer wire format
            out[key] = {"__masked_buffer__": value}
        else:
            out[key] = value
    return {"states": out, "update_count": np.asarray(metric.update_count)}


def _restore_states(metric: Metric, tree: Dict[str, Any]) -> None:
    if not isinstance(tree, dict) or "states" not in tree:
        raise ValueError(
            "Checkpoint tree is not a single-metric checkpoint (no 'states' entry) —"
            " was this saved from a MetricCollection? Load it into a collection instead."
        )
    states = tree.get("states", {}) or {}
    payload: Dict[str, Any] = {}
    if _ROBUST_STATE_KEY in states:  # update-guard counters ride along
        payload[_ROBUST_STATE_KEY] = states[_ROBUST_STATE_KEY]
    for key in metric._defaults:
        if key not in states:
            # empty containers are dropped by orbax on save — restore as empty
            if isinstance(metric._defaults[key], list):
                payload[key] = []
            continue
        value = states[key]
        if isinstance(value, dict) and "__list__" in value:
            items = value["__list__"] or {}
            payload[key] = [items[k] for k in sorted(items, key=int)]
        elif isinstance(value, dict) and "__masked_buffer__" in value:
            payload[key] = value["__masked_buffer__"]
        else:
            payload[key] = value
    metric.load_state_dict(payload)  # also drops any stale compute cache
    count = tree.get("update_count")
    if count is not None:
        metric._update_count = int(count)


def _tree_of(target: Union[Metric, Any]) -> Dict[str, Any]:
    if isinstance(target, Metric):
        return _host_states(target)
    # MetricCollection (or any name->Metric mapping)
    return {name: _host_states(m) for name, m in target.items()}


def _tree_digest(tree: Any) -> str:
    """Deterministic SHA-256 over every leaf (path, dtype, shape, bytes)."""
    digest = hashlib.sha256()

    def _walk(prefix: str, node: Any) -> None:
        if isinstance(node, dict):
            for key in sorted(node):
                _walk(f"{prefix}/{key}", node[key])
            return
        leaf = np.asarray(node)
        digest.update(prefix.encode())
        digest.update(str(leaf.dtype).encode())
        digest.update(str(leaf.shape).encode())
        digest.update(np.ascontiguousarray(leaf).tobytes())

    _walk("", tree)
    return digest.hexdigest()


def atomic_install_dir(tmp: str, path: str, tag: str) -> str:
    """Swap a fully-materialized temp directory into place at ``path``.

    The hardened half of the temp-dir+rename writer, shared by metric
    checkpoints and live-session bundles (:mod:`torchmetrics_tpu.engine.migrate`):
    a displace-then-rename loop (a concurrent saver can install a new dir at
    ``path`` between our displace and rename — displace again and retry rather
    than stranding the fully-written tmp), then a sweep of stale ``.old.*`` /
    ``.tmp.*`` siblings old enough that no live save owns them. ``tmp`` must be
    fully written (integrity record included) before this is called.
    """
    displaced = []
    for attempt in range(3):
        old = f"{path}.old.{tag}.{attempt}"
        try:
            if os.path.exists(path):
                os.rename(path, old)
                displaced.append(old)
            os.rename(tmp, path)
            break
        except OSError:
            if attempt == 2:
                raise
    for old in displaced:
        shutil.rmtree(old, ignore_errors=True)
    # a successful swap supersedes siblings leaked by earlier preempted saves
    # under other pids — but another process may be mid-save to the same path
    # right now, so only sweep dirs old enough that no live save owns them
    import glob
    import time

    cutoff = time.time() - _STALE_SIBLING_AGE_S
    for stale in glob.glob(f"{path}.old.*") + glob.glob(f"{path}.tmp.*"):
        try:
            if os.path.getmtime(stale) < cutoff:
                shutil.rmtree(stale, ignore_errors=True)
        except OSError:
            pass  # vanished under us (another sweeper won the race)
    return path


def file_tree_digest(root: str, exclude: tuple = ()) -> str:
    """Deterministic SHA-256 over every file under ``root`` (relpath + bytes).

    The integrity digest for directory bundles whose contents are opaque files
    (the session-bundle layout) rather than a restorable pytree: files are
    walked in sorted relative-path order and hashed as (path, content), so a
    truncated, tampered, renamed or missing file flips the digest. ``exclude``
    names relative paths to skip — the integrity record itself.

    Path-traversal hardening: a bundle is a closed set of regular files its
    writer materialized under one root, so any entry that could make a reader
    touch bytes *outside* that root — a symlink (file or directory, wherever it
    points) or a relative path escaping the root — raises
    :class:`CheckpointIntegrityError` instead of being silently followed. A
    crafted bundle must fail loudly at verification, before any restore reads
    through it.
    """
    digest = hashlib.sha256()
    excluded = {str(e).replace(os.sep, "/") for e in exclude}
    real_root = os.path.realpath(root)
    entries = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for dname in dirnames:
            if os.path.islink(os.path.join(dirpath, dname)):
                rel = os.path.relpath(os.path.join(dirpath, dname), root).replace(os.sep, "/")
                raise CheckpointIntegrityError(
                    f"Bundle at {root} contains a symlinked directory {rel!r} — bundles"
                    " hold only regular files; a link could point a restore outside the"
                    " bundle root, so this tree is rejected."
                )
        for fname in filenames:
            full = os.path.join(dirpath, fname)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            if rel in excluded:
                continue
            if os.path.islink(full):
                raise CheckpointIntegrityError(
                    f"Bundle at {root} contains a symlink {rel!r} — bundles hold only"
                    " regular files; a link could point a restore outside the bundle"
                    " root, so this tree is rejected."
                )
            if rel.startswith("..") or not os.path.realpath(full).startswith(
                real_root + os.sep
            ):
                raise CheckpointIntegrityError(
                    f"Bundle at {root} contains an entry {rel!r} that escapes the"
                    " bundle root — rejected."
                )
            entries.append((rel, full))
    for rel, full in sorted(entries):
        digest.update(rel.encode())
        with open(full, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                digest.update(chunk)
    return digest.hexdigest()


def save_checkpoint(target: Union[Metric, Any], path: str) -> str:
    """Write ``target``'s full state (mid-epoch included) to ``path`` via orbax.

    ``target`` is a :class:`Metric` or a ``MetricCollection``. Returns the absolute
    checkpoint path. Overwrites an existing checkpoint at the same path — atomically:
    the new checkpoint is fully materialized (tree + integrity record) under a temp
    directory first, then swapped in with renames, so preemption mid-save leaves
    either the old checkpoint or the new one, never a hybrid.
    """
    ocp = _require_orbax()

    path = os.path.abspath(path)
    tree = _tree_of(target)
    # tag beyond the pid: containerized pod hosts commonly share pid 1, and two
    # hosts saving to the same shared-storage path must never collide on tmp
    tag = f"{os.getpid()}.{uuid.uuid4().hex[:8]}"
    tmp = f"{path}.tmp.{tag}"
    try:
        ocp.PyTreeCheckpointer().save(os.path.join(tmp, _DATA_SUBDIR), tree, force=True)
        with open(os.path.join(tmp, _INTEGRITY_NAME), "w") as fh:
            json.dump({"version": 1, "sha256": _tree_digest(tree)}, fh)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return atomic_install_dir(tmp, path, tag)


def _recover_displaced(path: str) -> Optional[str]:
    """Newest ``<path>.old.<pid>``/``<path>.tmp.<pid>`` sibling that verifies.

    A preemption between ``save_checkpoint``'s two directory renames leaves no
    checkpoint at ``path`` but a complete one displaced under a pid-suffixed
    name (``.old.*`` = the previous good checkpoint; ``.tmp.*`` = the new one,
    already fully written since INTEGRITY.json lands before any rename).
    """
    import glob

    stamped = []
    for candidate in glob.glob(f"{path}.old.*") + glob.glob(f"{path}.tmp.*"):
        try:
            stamped.append((os.path.getmtime(candidate), candidate))
        except OSError:
            pass  # vanished under us (a concurrent save's stale-sibling sweep)
    for _, candidate in sorted(stamped, reverse=True):
        if os.path.isfile(os.path.join(candidate, _INTEGRITY_NAME)):
            return candidate
    return None


def _restore_verified(ocp, path: str) -> Dict[str, Any]:
    """Restore the pytree at ``path``, verifying the integrity record when present.

    Layout discrimination is on ``INTEGRITY.json``, not on a ``data/`` subdir —
    a *legacy* MetricCollection checkpoint holding a metric literally named
    "data" has a ``<path>/data/`` subtree but no integrity record, and must
    restore as the legacy layout. The atomic save guarantees every new-layout
    checkpoint reaching ``path`` carries its integrity record.
    """
    if not os.path.exists(path):
        displaced = _recover_displaced(path)
        if displaced is None:
            raise FileNotFoundError(f"No checkpoint at {path} (and no displaced sibling to recover)")
        from torchmetrics_tpu.utils.prints import rank_zero_warn

        rank_zero_warn(
            f"No checkpoint at {path}, but a save interrupted mid-swap left a complete"
            f" one at {displaced}; recovering from it. Re-save to normalize the path.",
            RuntimeWarning,
        )
        path = displaced
    integrity_path = os.path.join(path, _INTEGRITY_NAME)
    if not os.path.isfile(integrity_path):
        # pre-fault-tolerance layout: the orbax tree sits at `path` directly
        return ocp.PyTreeCheckpointer().restore(path)
    data_dir = os.path.join(path, _DATA_SUBDIR)
    try:
        restored = ocp.PyTreeCheckpointer().restore(data_dir)
    except Exception as err:
        raise CheckpointIntegrityError(
            f"Checkpoint at {path} is unreadable (truncated or half-written?): {err}"
        ) from err
    try:
        with open(integrity_path) as fh:
            recorded = json.load(fh)
    except (OSError, ValueError) as err:
        raise CheckpointIntegrityError(
            f"Checkpoint at {path} has an unreadable {_INTEGRITY_NAME} ({err}) —"
            " the record itself is truncated or tampered; restore from an older checkpoint."
        ) from err
    digest = _tree_digest(restored)
    if digest != recorded.get("sha256"):
        raise CheckpointIntegrityError(
            f"Checkpoint at {path} failed its integrity check (recorded"
            f" {str(recorded.get('sha256'))[:12]}…, recomputed {digest[:12]}…) —"
            " the data was corrupted after the save; restore from an older checkpoint."
        )
    return restored


def load_checkpoint(target: Union[Metric, Any], path: str) -> Union[Metric, Any]:
    """Restore states saved by :func:`save_checkpoint` into ``target`` (in place).

    ``target`` must be constructed with the same spec (same metric classes and
    arguments) as the checkpointed one — exactly the reference's ``load_state_dict``
    contract. Verifies the checkpoint's integrity record (when present) and raises
    :class:`CheckpointIntegrityError` on corruption. Returns ``target``.
    """
    ocp = _require_orbax()

    restored = _restore_verified(ocp, os.path.abspath(path))
    if isinstance(target, Metric):
        _restore_states(target, restored)
        return target
    for name, metric in target.items():
        if name not in restored:
            raise KeyError(f"Checkpoint at {path} has no entry for metric {name!r}")
        _restore_states(metric, restored[name])
    return target
