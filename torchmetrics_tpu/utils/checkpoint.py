"""Orbax checkpoint/resume for metrics and collections.

The reference piggybacks on ``torch.save``/Lightning checkpoints (its ``state_dict``
hooks, reference ``metric.py:858-924``); the TPU-native analog is an orbax pytree
checkpoint: every state — including non-persistent ones, mid-epoch — is written as a
host pytree and restored into a freshly constructed metric of the same spec.

Layout written to ``<path>/``: one subtree per metric (collections nest by metric
name) holding ``states`` plus ``update_count`` so a restored metric resumes exactly
where the checkpoint was taken (no compute-before-update warning, same results).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Union

import numpy as np

from torchmetrics_tpu.core.metric import Metric

__all__ = ["save_checkpoint", "load_checkpoint"]


def _require_orbax():
    from torchmetrics_tpu.utils.imports import _ORBAX_AVAILABLE

    if not _ORBAX_AVAILABLE:
        raise ModuleNotFoundError(
            "Metric checkpointing requires that `orbax-checkpoint` is installed."
            " Install it with `pip install orbax-checkpoint`."
        )
    import orbax.checkpoint as ocp

    return ocp


def _host_states(metric: Metric) -> Dict[str, Any]:
    """All states (not just persistent ones) as an orbax-friendly host pytree."""
    out: Dict[str, Any] = {}
    for key, value in metric.state_dict(persistent_only=False).items():
        if isinstance(value, list):
            # orbax drops empty containers; index dicts keep ordering explicit
            out[key] = {"__list__": {str(i): v for i, v in enumerate(value)}}
        elif isinstance(value, dict):  # state_dict's MaskedBuffer wire format
            out[key] = {"__masked_buffer__": value}
        else:
            out[key] = value
    return {"states": out, "update_count": np.asarray(metric.update_count)}


def _restore_states(metric: Metric, tree: Dict[str, Any]) -> None:
    if not isinstance(tree, dict) or "states" not in tree:
        raise ValueError(
            "Checkpoint tree is not a single-metric checkpoint (no 'states' entry) —"
            " was this saved from a MetricCollection? Load it into a collection instead."
        )
    states = tree.get("states", {}) or {}
    payload: Dict[str, Any] = {}
    for key in metric._defaults:
        if key not in states:
            # empty containers are dropped by orbax on save — restore as empty
            if isinstance(metric._defaults[key], list):
                payload[key] = []
            continue
        value = states[key]
        if isinstance(value, dict) and "__list__" in value:
            items = value["__list__"] or {}
            payload[key] = [items[k] for k in sorted(items, key=int)]
        elif isinstance(value, dict) and "__masked_buffer__" in value:
            payload[key] = value["__masked_buffer__"]
        else:
            payload[key] = value
    metric.load_state_dict(payload)  # also drops any stale compute cache
    count = tree.get("update_count")
    if count is not None:
        metric._update_count = int(count)


def _tree_of(target: Union[Metric, Any]) -> Dict[str, Any]:
    if isinstance(target, Metric):
        return _host_states(target)
    # MetricCollection (or any name->Metric mapping)
    return {name: _host_states(m) for name, m in target.items()}


def save_checkpoint(target: Union[Metric, Any], path: str) -> str:
    """Write ``target``'s full state (mid-epoch included) to ``path`` via orbax.

    ``target`` is a :class:`Metric` or a ``MetricCollection``. Returns the absolute
    checkpoint path. Overwrites an existing checkpoint at the same path.
    """
    ocp = _require_orbax()

    path = os.path.abspath(path)
    ocp.PyTreeCheckpointer().save(path, _tree_of(target), force=True)
    return path


def load_checkpoint(target: Union[Metric, Any], path: str) -> Union[Metric, Any]:
    """Restore states saved by :func:`save_checkpoint` into ``target`` (in place).

    ``target`` must be constructed with the same spec (same metric classes and
    arguments) as the checkpointed one — exactly the reference's ``load_state_dict``
    contract. Returns ``target``.
    """
    ocp = _require_orbax()

    restored = ocp.PyTreeCheckpointer().restore(os.path.abspath(path))
    if isinstance(target, Metric):
        _restore_states(target, restored)
        return target
    for name, metric in target.items():
        if name not in restored:
            raise KeyError(f"Checkpoint at {path} has no entry for metric {name!r}")
        _restore_states(metric, restored[name])
    return target
