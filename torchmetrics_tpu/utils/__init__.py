"""Utility layer: reductions, validation, enums, flags, plotting, logging."""

from torchmetrics_tpu.utils.checks import _check_same_shape, check_forward_full_state_property
from torchmetrics_tpu.utils.data import (
    _bincount,
    _cumsum,
    _flexible_bincount,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
    safe_divide,
    select_topk,
    to_onehot,
)
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError, TorchMetricsUserWarning
from torchmetrics_tpu.utils.prints import rank_zero_debug, rank_zero_info, rank_zero_warn

# reference exports these from torchmetrics.utilities (utilities/__init__.py)
from torchmetrics_tpu.parallel.reductions import class_reduce, reduce

__all__ = [
    "check_forward_full_state_property",
    "class_reduce",
    "reduce",
    "dim_zero_cat",
    "dim_zero_max",
    "dim_zero_mean",
    "dim_zero_min",
    "dim_zero_sum",
    "safe_divide",
    "select_topk",
    "to_onehot",
    "rank_zero_debug",
    "rank_zero_info",
    "rank_zero_warn",
    "TorchMetricsUserError",
    "TorchMetricsUserWarning",
]
