"""Input validation helpers shared across domains.

Parity: reference ``src/torchmetrics/utilities/checks.py`` (796 LoC). Host-side (not
jittable) checks that run once per ``update`` call on shapes/dtypes — static properties
under jit, so they never trigger recompilation or device sync.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def measure_runtime(fn: Callable[[], object], reps: int = 5, warmup: int = 0) -> float:
    """Median wall-clock seconds of ``fn()`` over ``reps`` timed repetitions.

    The shared perf timer behind :func:`check_forward_full_state_property` and
    the obs disabled-path overhead smoke test: median (not mean) so one noisy
    repetition on a shared host cannot dominate the measurement.
    """
    for _ in range(max(0, warmup)):
        fn()
    times = []
    for _ in range(max(1, reps)):
        start = time.perf_counter()
        fn()
        times.append(time.perf_counter() - start)
    return float(np.median(times))


def _check_same_shape(preds, target) -> None:
    """Raise if ``preds`` and ``target`` have different shapes."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {preds.shape} and {target.shape}."
        )


def _is_floating(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def _is_integral(x) -> bool:
    dt = jnp.asarray(x).dtype
    return jnp.issubdtype(dt, jnp.integer) or jnp.issubdtype(dt, jnp.bool_)


def _check_valid_prob_dtype(preds) -> None:
    if not _is_floating(preds):
        raise ValueError(f"Expected floating point predictions, got dtype {preds.dtype}.")


def _host_value(x):
    """Pull a (small) array to host. Explicit device sync point — use sparingly."""
    return np.asarray(x)


def check_forward_full_state_property(
    metric_class,
    init_args: Optional[dict] = None,
    input_args: Optional[dict] = None,
    num_update_to_compare: int = 10,
    reps: int = 5,
) -> None:
    """Empirically check if ``full_state_update=False`` gives the same result as ``True``.

    Parity: reference ``utilities/checks.py:636``. Prints timing for both paths and
    asserts result equality, so metric authors can set the class attribute safely.
    """
    init_args = init_args or {}
    input_args = input_args or {}

    class FullState(metric_class):
        full_state_update = True

    class PartialState(metric_class):
        full_state_update = False

    m_full = FullState(**init_args)
    m_part = PartialState(**init_args)

    res_full, res_part = None, None
    for _ in range(num_update_to_compare):
        res_full = m_full(**input_args)
        res_part = m_part(**input_args)

    equal = jax.tree_util.tree_all(
        jax.tree_util.tree_map(lambda a, b: bool(jnp.allclose(a, b)), res_full, res_part)
    )
    if not equal:
        raise RuntimeError(
            "The metric gives different results with `full_state_update=True` vs `False`;"
            " it must keep `full_state_update=True`."
        )

    def _time(m):
        def _one_rep():
            for _ in range(num_update_to_compare):
                m(**input_args)
            m.reset()

        return measure_runtime(_one_rep, reps=reps)

    t_full, t_part = _time(FullState(**init_args)), _time(PartialState(**init_args))
    print(f"Full state for {num_update_to_compare} steps took: {t_full}")  # noqa: T201
    print(f"Partial state for {num_update_to_compare} steps took: {t_part}")  # noqa: T201
    print("Recommended setting `full_state_update=False`")  # noqa: T201


def _try_proceed_with_timeout(fn, timeout: int = 25) -> bool:
    """Run ``fn`` in a daemon thread with a timeout; True on success.

    Parity: reference ``utilities/checks.py:766`` — guards slow model downloads in
    doctests/CI.
    """
    import threading

    result = {"ok": False}

    def _target():
        try:
            fn()
            result["ok"] = True
        except Exception:
            result["ok"] = False

    thread = threading.Thread(target=_target, daemon=True)
    thread.start()
    thread.join(timeout)
    return result["ok"] and not thread.is_alive()
