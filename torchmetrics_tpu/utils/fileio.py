"""Crash-safe file writes: temp-file-in-place + atomic rename.

The fault-tolerance PR established the invariant that nothing in this runtime
may leave a truncated file masquerading as a real one — checkpoints swap whole
directories (``utils/checkpoint.py``), downloaded resources go through a temp
file and ``os.replace`` (``robust/retry.py``). This module is that pattern as a
reusable helper, shared by every telemetry writer (``obs/export.write_jsonl``,
``obs/perfetto.write_trace``, ``obs/regress`` bench history) and the resource
fetcher: the payload is fully written (and optionally validated) under a temp
name in the destination's directory, then renamed into place. A crash at any
point leaves either the old file or the new one — never a hybrid — and the
temp file is removed on failure.

Pure stdlib; importable everywhere (no jax/numpy).
"""

from __future__ import annotations

import os
import tempfile
from contextlib import contextmanager
from typing import Callable, IO, Iterator, Optional

__all__ = ["atomic_open", "atomic_write_bytes", "atomic_write_text", "exclusive_create_text"]


@contextmanager
def atomic_open(
    path: str,
    mode: str = "w",
    encoding: Optional[str] = None,
    validate: Optional[Callable[[str], None]] = None,
) -> Iterator[IO]:
    """Open a temp file that is atomically renamed to ``path`` on clean exit.

    The single implementation of the temp-file protocol (both ``atomic_write_*``
    helpers delegate here). ``mode`` must be a write mode (``"w"`` / ``"wb"``);
    append modes make no sense under replace-on-commit semantics. The temp file
    lives in ``path``'s directory so the final ``os.replace`` never crosses a
    filesystem boundary (a cross-device rename is a copy, which reintroduces
    the torn-write window). ``validate``, when given, is called with the
    fully-written-and-synced temp path *before* the rename — a payload that
    fails validation (raises) never reaches ``path``. On any exception the
    temp file is removed and ``path`` is left untouched.
    """
    if "a" in mode or "r" in mode or "+" in mode:
        raise ValueError(f"atomic_open requires a plain write mode ('w'/'wb'), got {mode!r}")
    path = os.path.abspath(path)
    directory = os.path.dirname(path) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".", suffix=".tmp", dir=directory)
    try:
        with os.fdopen(fd, mode, encoding=encoding) as fh:
            yield fh
            fh.flush()
            os.fsync(fh.fileno())
        if validate is not None:
            validate(tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str, text: str, encoding: str = "utf-8") -> str:
    """Atomically materialize ``text`` at ``path``; returns the absolute path."""
    path = os.path.abspath(path)
    with atomic_open(path, "w", encoding=encoding) as fh:
        fh.write(text)
    return path


def exclusive_create_text(path: str, text: str, encoding: str = "utf-8") -> bool:
    """Create ``path`` with ``text`` iff it does not already exist; win/lose.

    The durable claim primitive (``O_CREAT | O_EXCL``): exactly one of N
    concurrent callers — threads OR processes sharing the filesystem — gets
    ``True``; everyone else gets ``False`` with the file untouched. Used by
    the fence watchdog's failover leader election (``FAILOVER_CLAIM.json``):
    a shared-disk fleet where several survivors detect the same stale lease
    must elect exactly one to run the failover, and a lock that does not
    survive the electing process's own crash is no lock at all. Unlike
    :func:`atomic_open` the content lands after creation (creation IS the
    atomic event here; the payload is advisory detail for operators), so the
    file is fsynced before close. Any error other than "already exists"
    propagates — a claim that silently failed to persist would elect two
    leaders on the next crash.
    """
    path = os.path.abspath(path)
    directory = os.path.dirname(path) or "."
    os.makedirs(directory, exist_ok=True)
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL)
    except FileExistsError:
        return False
    try:
        with os.fdopen(fd, "w", encoding=encoding) as fh:
            fh.write(text)
            fh.flush()
            os.fsync(fh.fileno())
    except BaseException:
        try:
            os.remove(path)  # a torn claim must not permanently block election
        except OSError:
            pass
        raise
    return True


def atomic_write_bytes(
    path: str, data: bytes, validate: Optional[Callable[[str], None]] = None
) -> str:
    """Atomically materialize ``data`` at ``path``; returns the absolute path.

    ``validate``, when given, runs against the fully-written temp path before
    the rename (see :func:`atomic_open`).
    """
    path = os.path.abspath(path)
    with atomic_open(path, "wb", validate=validate) as fh:
        fh.write(data)
    return path
