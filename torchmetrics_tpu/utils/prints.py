"""Rank-zero logging helpers.

Parity: reference ``src/torchmetrics/utilities/prints.py:22-73``. In JAX's
single-controller model "rank" maps to :func:`jax.process_index`; on a single host every
call site is rank zero.
"""

from __future__ import annotations

import logging
import os
import warnings
from functools import partial, wraps
from typing import Any, Callable

import torchmetrics_tpu.obs.trace as _trace

log = logging.getLogger("torchmetrics_tpu")


def _get_rank() -> int:
    # Cheap probe that works before/without jax.distributed being initialised.
    for env in ("JAX_PROCESS_INDEX", "RANK", "LOCAL_RANK"):
        if env in os.environ:
            try:
                return int(os.environ[env])
            except ValueError:
                pass
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def rank_zero_only(fn: Callable) -> Callable:
    """Run ``fn`` only on process index 0."""

    @wraps(fn)
    def wrapped(*args: Any, **kwargs: Any) -> Any:
        if _get_rank() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped


@rank_zero_only
def rank_zero_warn(message: str, *args: Any, **kwargs: Any) -> None:
    kwargs.setdefault("stacklevel", 5)
    # With obs tracing enabled, warnings also land in the telemetry event log
    # (so degraded-sync/quarantine warnings reach exported JSONL/Prometheus,
    # not only stderr) and repeated identical messages are deduplicated: the
    # repeat bumps the `warnings.deduplicated` counter instead of re-warning.
    if _trace.ENABLED and not _trace.record_warning(str(message)):
        return
    warnings.warn(message, *args, **kwargs)


@rank_zero_only
def rank_zero_info(message: str, *args: Any, **kwargs: Any) -> None:
    log.info(message, *args, **kwargs)


@rank_zero_only
def rank_zero_debug(message: str, *args: Any, **kwargs: Any) -> None:
    log.debug(message, *args, **kwargs)


def _deprecated_root_import_class(name: str, domain: str) -> None:
    rank_zero_warn(
        f"Importing `{name}` from `torchmetrics_tpu` was deprecated; import it from"
        f" `torchmetrics_tpu.{domain}` instead.",
        DeprecationWarning,
    )


def _deprecated_root_import_func(name: str, domain: str) -> None:
    rank_zero_warn(
        f"Importing `{name}` from `torchmetrics_tpu.functional` was deprecated; import it from"
        f" `torchmetrics_tpu.functional.{domain}` instead.",
        DeprecationWarning,
    )


rank_zero_warn_once = partial(rank_zero_warn)
