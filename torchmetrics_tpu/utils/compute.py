"""Shared numeric helpers (AUC integration, interpolation).

Parity: reference ``src/torchmetrics/utilities/compute.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array

_trapezoid = getattr(jnp, "trapezoid", None) or jnp.trapz


def _auc_compute_without_check(x: Array, y: Array, direction: float = 1.0, axis: int = -1) -> Array:
    """Area under the curve via trapezoidal rule (inputs assumed sorted along x)."""
    return (_trapezoid(y, x, axis=axis) * direction).astype(jnp.float32)


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    """AUC with monotonicity handling: auto-detects decreasing x (direction = -1).

    Non-monotonic ``x`` with ``reorder=False`` raises eagerly (like the reference,
    ``utilities/compute.py``); under jit tracing the check is skipped and ascending
    order is assumed.
    """
    if reorder:
        order = jnp.argsort(x)
        x, y = x[order], y[order]
    dx = jnp.diff(x)
    if not reorder and not isinstance(dx, jax.core.Tracer) and dx.size:
        if not (bool(jnp.all(dx <= 0)) or bool(jnp.all(dx >= 0))):
            raise ValueError(
                "The `x` array is neither increasing or decreasing. Try setting the reorder argument to `True`."
            )
    direction = jnp.where(jnp.all(dx <= 0), -1.0, 1.0)
    return _auc_compute_without_check(x, y, direction)


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Area under the curve y = f(x).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.utils.compute import auc
        >>> x = jnp.array([0.0, 0.5, 1.0])
        >>> y = jnp.array([0.0, 0.8, 1.0])
        >>> auc(x, y)
        Array(0.65, dtype=float32)
    """
    if x.ndim != 1 or y.ndim != 1:
        raise ValueError(f"Expected both `x` and `y` to be 1d tensors, got {x.ndim}d and {y.ndim}d")
    return _auc_compute(x, y, reorder=reorder)


def interp(x: Array, xp: Array, fp: Array) -> Array:
    """Linear interpolation (ascending ``xp``)."""
    return jnp.interp(x, xp, fp)
