"""Mean average precision (COCO-style) with a native matcher.

Parity: reference ``src/torchmetrics/detection/mean_ap.py`` (the pycocotools-backed
API incl. ``iou_type="segm"``, ``extended_summary``, ``average``) with the matching
semantics of the reference's own pure-torch evaluator
``src/torchmetrics/detection/_mean_ap.py`` (greedy per-detection best-GT matching
``:623-650``, per-image evaluation ``:522-620``, PR accumulation ``:791-860``,
COCO summarization ``:652-695,755-789``).

TPU design notes:

- The greedy COCO matcher is sequential per detection with dynamic per-image box
  counts — host logic by nature (the reference runs it in C via pycocotools). Here it
  runs in vectorized numpy at ``compute`` time.
- **Distributed sync** works in both state layouts:
  * list mode (default): per-image ragged numpy arrays; eager multihost sync ships
    them through the pad-to-max ragged gather (:func:`allgather_ragged_arrays`) —
    the tensor-native analog of the reference's object gather (``mean_ap.py:442-450``).
  * buffered mode (``buffer_capacity``/``image_capacity`` set): static-shape
    :class:`MaskedBuffer` row + per-image-size states that ``all_gather`` inside
    ``shard_map`` like every other metric — the mesh-native layout. ``segm`` rides
    it too: masks of a declared static ``mask_shape`` are bit-packed to uint8 rows
    on device (8x smaller than bool) and unpacked at compute.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.core.buffer import MaskedBuffer
from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.detection.helpers import _fix_empty_tensors, _input_validator
from torchmetrics_tpu.functional.detection.box_ops import box_convert

Array = jax.Array

_BBOX_AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}


def _np_box_area(boxes: np.ndarray) -> np.ndarray:
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


def _np_box_iou(det: np.ndarray, gt: np.ndarray) -> np.ndarray:
    area_det = _np_box_area(det)
    area_gt = _np_box_area(gt)
    lt = np.maximum(det[:, None, :2], gt[None, :, :2])
    rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    return inter / (area_det[:, None] + area_gt[None, :] - inter)


def _pack_mask_bits(masks: Array, packed_len: int) -> Array:
    """Bit-pack boolean masks: (n, H, W) -> (n, packed_len) uint8, big-endian bit
    order (np.unpackbits-compatible). Keeps the mesh-synced mask buffer 8x smaller
    than a bool layout; traceable, so it runs inside ``pure_update`` under jit."""
    if masks.shape[0] == 0:
        return jnp.zeros((0, packed_len), dtype=jnp.uint8)
    flat = masks.reshape(masks.shape[0], -1).astype(jnp.int32)
    flat = jnp.pad(flat, ((0, 0), (0, packed_len * 8 - flat.shape[1])))
    groups = flat.reshape(flat.shape[0], packed_len, 8)
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], dtype=jnp.int32)
    return jnp.sum(groups * weights, axis=-1).astype(jnp.uint8)


def _unpack_mask_bits(rows: np.ndarray, mask_shape: Tuple[int, int]) -> np.ndarray:
    """Inverse of :func:`_pack_mask_bits` on host: (n, packed_len) -> (n, H, W) bool."""
    n = rows.shape[0]
    if n == 0:
        return np.zeros((0,) + tuple(mask_shape), dtype=bool)
    bits = np.unpackbits(rows.astype(np.uint8), axis=1)[:, : mask_shape[0] * mask_shape[1]]
    return bits.reshape(n, *mask_shape).astype(bool)


def _np_mask_iou(det: np.ndarray, gt: np.ndarray) -> np.ndarray:
    """Bitmap IoU: [n, H, W] x [m, H, W] -> [n, m] via flattened boolean matmuls."""
    d = det.reshape(det.shape[0], -1).astype(np.float32)
    g = gt.reshape(gt.shape[0], -1).astype(np.float32)
    inter = d @ g.T
    union = d.sum(axis=1)[:, None] + g.sum(axis=1)[None, :] - inter
    with np.errstate(divide="ignore", invalid="ignore"):
        iou = np.where(union > 0, inter / union, 0.0)
    return iou


class _Samples:
    """Materialized per-image evaluation inputs (layout-independent)."""

    def __init__(
        self,
        det_boxes: List[np.ndarray],
        det_scores: List[np.ndarray],
        det_labels: List[np.ndarray],
        gt_boxes: List[np.ndarray],
        gt_labels: List[np.ndarray],
        det_masks: Optional[List[np.ndarray]] = None,
        gt_masks: Optional[List[np.ndarray]] = None,
    ) -> None:
        self.det_boxes = det_boxes
        self.det_scores = det_scores
        self.det_labels = det_labels
        self.gt_boxes = gt_boxes
        self.gt_labels = gt_labels
        self.det_masks = det_masks
        self.gt_masks = gt_masks

    @property
    def num_images(self) -> int:
        return len(self.gt_boxes)

    def classes(self) -> List[int]:
        labels = [lab for lab in self.det_labels + self.gt_labels if lab.size]
        if labels:
            return sorted({int(v) for v in np.concatenate(labels)})
        return []

    def relabeled_to_single_class(self) -> "_Samples":
        """Micro averaging pools every class (reference ``mean_ap.py:552-555``)."""
        return _Samples(
            self.det_boxes,
            self.det_scores,
            [np.zeros_like(lab) for lab in self.det_labels],
            self.gt_boxes,
            [np.zeros_like(lab) for lab in self.gt_labels],
            self.det_masks,
            self.gt_masks,
        )


class MeanAveragePrecision(Metric):
    r"""COCO mean average precision / mean average recall for object detection.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.detection import MeanAveragePrecision
        >>> preds = [{"boxes": jnp.array([[258.0, 41.0, 606.0, 285.0]]),
        ...           "scores": jnp.array([0.536]),
        ...           "labels": jnp.array([0])}]
        >>> target = [{"boxes": jnp.array([[214.0, 41.0, 562.0, 285.0]]),
        ...            "labels": jnp.array([0])}]
        >>> metric = MeanAveragePrecision()
        >>> metric.update(preds, target)
        >>> result = metric.compute()
        >>> result["map_50"].round(4)
        Array(1., dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: str = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        extended_summary: bool = False,
        average: str = "macro",
        buffer_capacity: Optional[int] = None,
        image_capacity: Optional[int] = None,
        mask_shape: Optional[Tuple[int, int]] = None,
        **kwargs: Any,
    ) -> None:
        kwargs.setdefault("jit_update", False)
        super().__init__(**kwargs)

        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        if iou_type not in ("bbox", "segm"):
            raise ValueError(f"Expected argument `iou_type` to be one of ('bbox', 'segm') but got {iou_type}")
        self.iou_type = iou_type
        self.iou_thresholds = iou_thresholds or np.linspace(0.5, 0.95, 10).round(2).tolist()
        self.rec_thresholds = rec_thresholds or np.linspace(0.0, 1.0, 101).round(2).tolist()
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics
        if not isinstance(extended_summary, bool):
            raise ValueError("Expected argument `extended_summary` to be a boolean")
        self.extended_summary = extended_summary
        if average not in ("macro", "micro"):
            raise ValueError(f"Expected argument `average` to be one of ('macro', 'micro') but got {average}")
        self.average = average

        if mask_shape is not None and (iou_type != "segm" or buffer_capacity is None):
            raise ValueError(
                "Argument `mask_shape` is only used by the buffered segm layout"
                " (`iou_type='segm'` together with `buffer_capacity`)"
            )
        self._buffered = buffer_capacity is not None
        if self._buffered:
            image_capacity = image_capacity or 256
            # static-shape mesh layout: flat row buffers + per-image size buffers;
            # rows are [x1, y1, x2, y2, score, label] / [x1, y1, x2, y2, label]
            self.add_state("det_rows", MaskedBuffer.create(buffer_capacity, (6,)), dist_reduce_fx="cat")
            self.add_state("det_sizes", MaskedBuffer.create(image_capacity, (), dtype=jnp.int32), dist_reduce_fx="cat")
            self.add_state("gt_rows", MaskedBuffer.create(buffer_capacity, (5,)), dist_reduce_fx="cat")
            self.add_state("gt_sizes", MaskedBuffer.create(image_capacity, (), dtype=jnp.int32), dist_reduce_fx="cat")
            if iou_type == "segm":
                # fixed-capacity bit-packed bitmap rows: masks must share one static
                # (H, W) so segm states stay mesh-syncable inside shard_map
                if mask_shape is None:
                    raise ValueError(
                        "Buffered (mesh-syncable) segm states need a static `mask_shape=(H, W)`;"
                        " pass it, or use the default list-mode states (no `buffer_capacity`)"
                        " whose ragged masks sync via the eager multihost gather."
                    )
                self.mask_shape = (int(mask_shape[0]), int(mask_shape[1]))
                self._packed_len = -(-(self.mask_shape[0] * self.mask_shape[1]) // 8)
                self.add_state(
                    "det_mask_rows",
                    MaskedBuffer.create(buffer_capacity, (self._packed_len,), dtype=jnp.uint8),
                    dist_reduce_fx="cat",
                )
                self.add_state(
                    "gt_mask_rows",
                    MaskedBuffer.create(buffer_capacity, (self._packed_len,), dtype=jnp.uint8),
                    dist_reduce_fx="cat",
                )
        else:
            # per-image ragged lists; synced across hosts via the pad-to-max ragged
            # gather in _sync_dist (boundaries preserved by gathering aligned lists)
            self.add_state("detections", [], dist_reduce_fx=None)
            self.add_state("detection_scores", [], dist_reduce_fx=None)
            self.add_state("detection_labels", [], dist_reduce_fx=None)
            self.add_state("groundtruths", [], dist_reduce_fx=None)
            self.add_state("groundtruth_labels", [], dist_reduce_fx=None)
            if iou_type == "segm":
                self.add_state("detection_masks", [], dist_reduce_fx=None)
                self.add_state("groundtruth_masks", [], dist_reduce_fx=None)

    # ------------------------------------------------------------------ state update

    @staticmethod
    def _canonical_masks(masks: Any) -> np.ndarray:
        """Canonicalize masks to rank 3: a 1-D empty array becomes (0, 0, 0).

        Mirrors ``_fix_empty_tensors`` for boxes — without this, the multihost
        ragged gather's rank-3 shape table would reject inputs that evaluate fine
        on a single host.
        """
        arr = np.asarray(masks).astype(bool)
        if arr.size == 0 and arr.ndim != 3:
            return arr.reshape(0, 0, 0)
        return arr

    def _convert_boxes(self, boxes: Array) -> Array:
        boxes = _fix_empty_tensors(jnp.asarray(boxes, dtype=jnp.float32))
        if boxes.ndim != 2 or boxes.shape[-1] != 4:
            boxes = boxes.reshape(-1, 4)
        if boxes.size:
            boxes = box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
        return boxes

    def update(self, preds: Sequence[Dict[str, Array]], target: Sequence[Dict[str, Array]]) -> None:
        """Store per-image detections and ground truths."""
        _input_validator(preds, target, iou_type=self.iou_type)
        if self._buffered:
            self._update_buffered(preds, target)
            return

        for item in preds:
            n = np.asarray(item["labels"]).reshape(-1).shape[0]
            self.detections.append(
                np.asarray(self._convert_boxes(item["boxes"])) if "boxes" in item
                else np.zeros((n, 4), dtype=np.float32)
            )
            self.detection_labels.append(np.asarray(item["labels"]))
            self.detection_scores.append(np.asarray(item["scores"]))
            if self.iou_type == "segm":
                self.detection_masks.append(self._canonical_masks(item["masks"]))

        for item in target:
            n = np.asarray(item["labels"]).reshape(-1).shape[0]
            self.groundtruths.append(
                np.asarray(self._convert_boxes(item["boxes"])) if "boxes" in item
                else np.zeros((n, 4), dtype=np.float32)
            )
            self.groundtruth_labels.append(np.asarray(item["labels"]))
            if self.iou_type == "segm":
                self.groundtruth_masks.append(self._canonical_masks(item["masks"]))

    def _checked_masks(self, item: Dict[str, Array], n_rows: int) -> Array:
        masks = jnp.asarray(item["masks"]).astype(bool)
        if masks.size == 0 and n_rows == 0:
            return jnp.zeros((0,) + self.mask_shape, dtype=bool)
        if masks.ndim != 3 or tuple(masks.shape[-2:]) != self.mask_shape or masks.shape[0] != n_rows:
            raise ValueError(
                f"Buffered segm states hold per-image masks of static shape"
                f" ({n_rows}, {self.mask_shape[0]}, {self.mask_shape[1]}) for this item,"
                f" but got an array of shape {tuple(masks.shape)}."
            )
        return masks

    def _update_buffered(self, preds: Sequence[Dict[str, Array]], target: Sequence[Dict[str, Array]]) -> None:
        # one append per state per call (not per image): concatenating the whole
        # batch first keeps the eager path at a constant number of device dispatches
        segm = self.iou_type == "segm"
        det_rows, det_sizes, det_mask_rows = [], [], []
        for item in preds:
            n = np.prod(jnp.asarray(item["labels"]).shape, dtype=int)
            boxes = (
                self._convert_boxes(item["boxes"]) if "boxes" in item
                else jnp.zeros((n, 4), dtype=jnp.float32)
            )
            scores = jnp.asarray(item["scores"], dtype=jnp.float32).reshape(-1, 1)
            labels = jnp.asarray(item["labels"]).astype(jnp.float32).reshape(-1, 1)
            rows = jnp.concatenate([boxes.reshape(-1, 4), scores, labels], axis=1)
            det_rows.append(rows)
            det_sizes.append(rows.shape[0])
            if segm:
                det_mask_rows.append(_pack_mask_bits(self._checked_masks(item, rows.shape[0]), self._packed_len))
        if det_rows:
            self.det_rows = self.det_rows.append(jnp.concatenate(det_rows, axis=0))
            self.det_sizes = self.det_sizes.append(jnp.asarray(det_sizes, dtype=jnp.int32))
            if segm:
                self.det_mask_rows = self.det_mask_rows.append(jnp.concatenate(det_mask_rows, axis=0))
        gt_rows, gt_sizes, gt_mask_rows = [], [], []
        for item in target:
            n = np.prod(jnp.asarray(item["labels"]).shape, dtype=int)
            boxes = (
                self._convert_boxes(item["boxes"]) if "boxes" in item
                else jnp.zeros((n, 4), dtype=jnp.float32)
            )
            labels = jnp.asarray(item["labels"]).astype(jnp.float32).reshape(-1, 1)
            rows = jnp.concatenate([boxes.reshape(-1, 4), labels], axis=1)
            gt_rows.append(rows)
            gt_sizes.append(rows.shape[0])
            if segm:
                gt_mask_rows.append(_pack_mask_bits(self._checked_masks(item, rows.shape[0]), self._packed_len))
        if gt_rows:
            self.gt_rows = self.gt_rows.append(jnp.concatenate(gt_rows, axis=0))
            self.gt_sizes = self.gt_sizes.append(jnp.asarray(gt_sizes, dtype=jnp.int32))
            if segm:
                self.gt_mask_rows = self.gt_mask_rows.append(jnp.concatenate(gt_mask_rows, axis=0))

    # ---------------------------------------------------------------- distributed sync

    def _sync_dist(self, dist_sync_fn=None) -> None:
        if self._buffered or dist_sync_fn is not None or self.dist_sync_fn is not None:
            # MaskedBuffer states ride the generic all_gather+compaction path
            super()._sync_dist(dist_sync_fn)
            return
        from torchmetrics_tpu.parallel.sync import allgather_ragged_arrays

        sv = self._state_values
        names_2d = ["detections", "groundtruths"]
        names_1d = ["detection_scores", "detection_labels", "groundtruth_labels"]
        for name in names_2d:
            sv[name] = allgather_ragged_arrays([np.asarray(a).reshape(-1, 4) for a in sv[name]], ndim=2)
        for name in names_1d:
            dtype = np.float32 if name == "detection_scores" else np.int64
            sv[name] = [
                a.astype(dtype)
                for a in allgather_ragged_arrays([np.asarray(a).reshape(-1) for a in sv[name]], ndim=1, dtype=dtype)
            ]
        if self.iou_type == "segm":
            for name in ("detection_masks", "groundtruth_masks"):
                gathered = allgather_ragged_arrays([np.asarray(a) for a in sv[name]], ndim=3, dtype=np.uint8)
                sv[name] = [a.astype(bool) for a in gathered]

    # --------------------------------------------------------------- materialization

    def _materialize(self) -> _Samples:
        if not self._buffered:
            return _Samples(
                [np.asarray(a).reshape(-1, 4) for a in self.detections],
                [np.asarray(a).reshape(-1) for a in self.detection_scores],
                [np.asarray(a).reshape(-1) for a in self.detection_labels],
                [np.asarray(a).reshape(-1, 4) for a in self.groundtruths],
                [np.asarray(a).reshape(-1) for a in self.groundtruth_labels],
                self.detection_masks if self.iou_type == "segm" else None,
                self.groundtruth_masks if self.iou_type == "segm" else None,
            )
        det_rows = np.asarray(self.det_rows.values())
        det_sizes = np.asarray(self.det_sizes.values()).astype(np.int64)
        gt_rows = np.asarray(self.gt_rows.values())
        gt_sizes = np.asarray(self.gt_sizes.values()).astype(np.int64)
        det_split = np.split(det_rows, np.cumsum(det_sizes)[:-1]) if det_sizes.size else []
        gt_split = np.split(gt_rows, np.cumsum(gt_sizes)[:-1]) if gt_sizes.size else []
        det_masks = gt_masks = None
        if self.iou_type == "segm":
            det_mask_rows = _unpack_mask_bits(np.asarray(self.det_mask_rows.values()), self.mask_shape)
            gt_mask_rows = _unpack_mask_bits(np.asarray(self.gt_mask_rows.values()), self.mask_shape)
            det_masks = np.split(det_mask_rows, np.cumsum(det_sizes)[:-1]) if det_sizes.size else []
            gt_masks = np.split(gt_mask_rows, np.cumsum(gt_sizes)[:-1]) if gt_sizes.size else []
        return _Samples(
            [r[:, :4] for r in det_split],
            [r[:, 4] for r in det_split],
            [r[:, 5].astype(np.int64) for r in det_split],
            [r[:, :4] for r in gt_split],
            [r[:, 4].astype(np.int64) for r in gt_split],
            det_masks,
            gt_masks,
        )

    # --------------------------------------------------------------------- evaluation

    def _prepare_image(self, samples: _Samples, idx: int, class_id: int, max_det: int) -> Optional[Dict[str, np.ndarray]]:
        """Per-(image, class) setup shared across area ranges: filtered + score-sorted
        detections, filtered GTs, areas, and the IoU matrix (computed once)."""
        gt_mask = samples.gt_labels[idx] == class_id
        det_mask = samples.det_labels[idx] == class_id
        if not gt_mask.any() and not det_mask.any():
            return None

        scores = samples.det_scores[idx][det_mask]
        dtind = np.argsort(-scores, kind="mergesort")[:max_det]
        scores_sorted = scores[dtind]

        if self.iou_type == "segm":
            gt = samples.gt_masks[idx][gt_mask]
            det = samples.det_masks[idx][det_mask][dtind]
            gt_areas = gt.reshape(gt.shape[0], -1).sum(axis=1).astype(np.float64) if len(gt) else np.zeros(0)
            det_areas = det.reshape(det.shape[0], -1).sum(axis=1).astype(np.float64) if len(det) else np.zeros(0)
            ious = _np_mask_iou(det, gt) if len(det) and len(gt) else np.zeros((len(det), len(gt)))
        else:
            gt = samples.gt_boxes[idx][gt_mask]
            det = samples.det_boxes[idx][det_mask][dtind]
            gt_areas = _np_box_area(gt) if len(gt) else np.zeros(0)
            det_areas = _np_box_area(det) if len(det) else np.zeros(0)
            ious = _np_box_iou(det, gt) if len(det) and len(gt) else np.zeros((len(det), len(gt)))

        return {
            "gt_areas": gt_areas,
            "det_areas": det_areas,
            "scores_sorted": scores_sorted,
            "ious": ious,
        }

    def _evaluate_image(
        self, prep: Optional[Dict[str, np.ndarray]], area_range: Tuple[float, float]
    ) -> Optional[Dict[str, np.ndarray]]:
        """Greedy best-match evaluation at all IoU thresholds for one area range."""
        if prep is None:
            return None

        # sort gts so ignored (out-of-area) come last
        gt_out_of_area = (prep["gt_areas"] < area_range[0]) | (prep["gt_areas"] > area_range[1])
        gtind = np.argsort(gt_out_of_area, kind="stable")
        gt_ignore = gt_out_of_area[gtind]

        num_thrs = len(self.iou_thresholds)
        num_gt = len(gt_ignore)
        num_det = len(prep["scores_sorted"])
        gt_matches = np.zeros((num_thrs, num_gt), dtype=bool)
        det_matches = np.zeros((num_thrs, num_det), dtype=bool)
        det_ignore = np.zeros((num_thrs, num_det), dtype=bool)

        if num_gt and num_det:
            ious = prep["ious"][:, gtind]
            for t_idx, threshold in enumerate(self.iou_thresholds):
                for d_idx in range(num_det):
                    candidates = ious[d_idx] * ~(gt_matches[t_idx] | gt_ignore)
                    m = int(candidates.argmax())
                    if candidates[m] <= threshold:
                        continue
                    det_ignore[t_idx, d_idx] = gt_ignore[m]
                    det_matches[t_idx, d_idx] = True
                    gt_matches[t_idx, m] = True

        # unmatched detections outside the area range are ignored
        det_out_of_area = (prep["det_areas"] < area_range[0]) | (prep["det_areas"] > area_range[1])
        det_ignore |= ~det_matches & det_out_of_area[None, :]

        return {
            "dtMatches": det_matches,
            "dtScores": prep["scores_sorted"],
            "gtIgnore": gt_ignore,
            "dtIgnore": det_ignore,
        }

    def _accumulate(self, samples: _Samples, classes: List[int]) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Dict]:
        """PR accumulation → precision[T,R,K,A,M], recall[T,K,A,M], scores[T,R,K,A,M]."""
        num_thrs = len(self.iou_thresholds)
        num_rec = len(self.rec_thresholds)
        num_cls = len(classes)
        num_areas = len(_BBOX_AREA_RANGES)
        num_maxdet = len(self.max_detection_thresholds)

        precision = -np.ones((num_thrs, num_rec, num_cls, num_areas, num_maxdet))
        recall = -np.ones((num_thrs, num_cls, num_areas, num_maxdet))
        score_surface = -np.ones((num_thrs, num_rec, num_cls, num_areas, num_maxdet))
        ious_out: Dict = {}
        rec_thrs = np.asarray(self.rec_thresholds)
        max_det_cap = self.max_detection_thresholds[-1]

        for k_idx, class_id in enumerate(classes):
            preps = [self._prepare_image(samples, i, class_id, max_det_cap) for i in range(samples.num_images)]
            if self.extended_summary:
                for i, prep in enumerate(preps):
                    ious_out[(i, class_id)] = (
                        jnp.asarray(prep["ious"], dtype=jnp.float32) if prep is not None else jnp.zeros((0, 0))
                    )
            for a_idx, area_range in enumerate(_BBOX_AREA_RANGES.values()):
                evals = [self._evaluate_image(prep, area_range) for prep in preps]
                evals = [e for e in evals if e is not None]
                if not evals:
                    continue
                for m_idx, max_det in enumerate(self.max_detection_thresholds):
                    det_scores = np.concatenate([e["dtScores"][:max_det] for e in evals])
                    inds = np.argsort(-det_scores, kind="mergesort")
                    det_scores_sorted = det_scores[inds]
                    det_matches = np.concatenate(
                        [e["dtMatches"][:, :max_det] for e in evals], axis=1
                    )[:, inds]
                    det_ignore = np.concatenate(
                        [e["dtIgnore"][:, :max_det] for e in evals], axis=1
                    )[:, inds]
                    gt_ignore = np.concatenate([e["gtIgnore"] for e in evals])
                    npig = int((~gt_ignore).sum())
                    if npig == 0:
                        continue
                    tps = det_matches & ~det_ignore
                    fps = ~det_matches & ~det_ignore
                    tp_sum = np.cumsum(tps, axis=1, dtype=np.float64)
                    fp_sum = np.cumsum(fps, axis=1, dtype=np.float64)

                    for t_idx in range(num_thrs):
                        tp = tp_sum[t_idx]
                        fp = fp_sum[t_idx]
                        rc = tp / npig
                        pr = tp / (fp + tp + np.finfo(np.float64).eps)
                        recall[t_idx, k_idx, a_idx, m_idx] = rc[-1] if len(tp) else 0

                        # monotone non-increasing precision envelope (right-to-left max)
                        pr = np.maximum.accumulate(pr[::-1])[::-1]

                        inds_r = np.searchsorted(rc, rec_thrs, side="left")
                        prec = np.zeros(num_rec)
                        score_at = np.zeros(num_rec)
                        valid = inds_r < len(pr)
                        prec[valid] = pr[inds_r[valid]]
                        score_at[valid] = det_scores_sorted[inds_r[valid]]
                        precision[t_idx, :, k_idx, a_idx, m_idx] = prec
                        score_surface[t_idx, :, k_idx, a_idx, m_idx] = score_at

        return precision, recall, score_surface, ious_out

    @staticmethod
    def _mean_over_valid(values: np.ndarray) -> Array:
        valid = values > -1
        if not valid.any():
            return jnp.asarray(-1.0)
        return jnp.asarray(values[valid].mean(), dtype=jnp.float32)

    def _summarize(
        self,
        precision: np.ndarray,
        recall: np.ndarray,
        avg_prec: bool = True,
        iou_threshold: Optional[float] = None,
        area_range: str = "all",
        max_dets: int = 100,
    ) -> Array:
        """COCO summarization: mean over valid entries of the selected PR slab."""
        a_idx = list(_BBOX_AREA_RANGES).index(area_range)
        m_idx = self.max_detection_thresholds.index(max_dets)
        if avg_prec:
            vals = precision[..., a_idx, m_idx]
            if iou_threshold is not None:
                vals = vals[self.iou_thresholds.index(iou_threshold)]
        else:
            vals = recall[..., a_idx, m_idx]
            if iou_threshold is not None:
                vals = vals[self.iou_thresholds.index(iou_threshold)]
        return self._mean_over_valid(vals)

    def compute(self) -> Dict[str, Array]:
        """COCO mAP/mAR metric dictionary over all accumulated images."""
        samples = self._materialize()
        eval_samples = samples.relabeled_to_single_class() if self.average == "micro" else samples
        classes = eval_samples.classes()
        precision, recall, score_surface, ious = self._accumulate(eval_samples, classes)
        last_max_det = self.max_detection_thresholds[-1]

        metrics: Dict[str, Array] = {}
        metrics["map"] = self._summarize(precision, recall, True, max_dets=last_max_det)
        metrics["map_50"] = (
            self._summarize(precision, recall, True, iou_threshold=0.5, max_dets=last_max_det)
            if 0.5 in self.iou_thresholds
            else jnp.asarray(-1.0)
        )
        metrics["map_75"] = (
            self._summarize(precision, recall, True, iou_threshold=0.75, max_dets=last_max_det)
            if 0.75 in self.iou_thresholds
            else jnp.asarray(-1.0)
        )
        for area in ("small", "medium", "large"):
            metrics[f"map_{area}"] = self._summarize(
                precision, recall, True, area_range=area, max_dets=last_max_det
            )
        for max_det in self.max_detection_thresholds:
            metrics[f"mar_{max_det}"] = self._summarize(precision, recall, False, max_dets=max_det)
        for area in ("small", "medium", "large"):
            metrics[f"mar_{area}"] = self._summarize(
                precision, recall, False, area_range=area, max_dets=last_max_det
            )

        if self.extended_summary:
            metrics["ious"] = ious
            metrics["precision"] = jnp.asarray(precision, dtype=jnp.float32)
            metrics["recall"] = jnp.asarray(recall, dtype=jnp.float32)
            metrics["scores"] = jnp.asarray(score_surface, dtype=jnp.float32)

        map_per_class = jnp.asarray([-1.0])
        mar_per_class = jnp.asarray([-1.0])
        if self.class_metrics:
            # micro pooled everything into one class for the headline stats; per-class
            # metrics always evaluate per true class (reference ``mean_ap.py:551-559``)
            cls_samples = samples
            cls_classes = cls_samples.classes()
            if cls_classes:
                if self.average == "micro":
                    cls_precision, cls_recall, _, _ = self._accumulate(cls_samples, cls_classes)
                else:
                    cls_precision, cls_recall = precision, recall
                map_list, mar_list = [], []
                for k_idx in range(len(cls_classes)):
                    cls_prec = cls_precision[:, :, k_idx : k_idx + 1]
                    cls_rec = cls_recall[:, k_idx : k_idx + 1]
                    map_list.append(self._summarize(cls_prec, cls_rec, True, max_dets=last_max_det))
                    mar_list.append(self._summarize(cls_prec, cls_rec, False, max_dets=last_max_det))
                map_per_class = jnp.stack(map_list)
                mar_per_class = jnp.stack(mar_list)
        metrics["map_per_class"] = map_per_class
        metrics[f"mar_{last_max_det}_per_class"] = mar_per_class
        metrics["classes"] = jnp.asarray(
            samples.classes() if self.class_metrics or self.average == "micro" else classes, dtype=jnp.int32
        )
        return metrics
