"""Mean average precision (COCO-style) with a native matcher.

Parity: reference ``src/torchmetrics/detection/mean_ap.py`` (the pycocotools-backed
API) with the matching semantics of the reference's own pure-torch evaluator
``src/torchmetrics/detection/_mean_ap.py`` (greedy per-detection best-GT matching
``:623-650``, per-image evaluation ``:522-620``, PR accumulation ``:791-860``,
COCO summarization ``:652-695,755-789``).

TPU design note: the greedy COCO matcher is sequential per detection with dynamic
per-image box counts — host logic by nature (the reference runs it on CPU torch, COCO
runs it in C). Here it runs in vectorized numpy at ``compute`` time; box IoU matrices
are the only heavy arithmetic and are batched numpy einsum-free ops.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.detection.helpers import _fix_empty_tensors, _input_validator
from torchmetrics_tpu.functional.detection.box_ops import box_convert

Array = jax.Array

_BBOX_AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}


def _np_box_area(boxes: np.ndarray) -> np.ndarray:
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


def _np_box_iou(det: np.ndarray, gt: np.ndarray) -> np.ndarray:
    area_det = _np_box_area(det)
    area_gt = _np_box_area(gt)
    lt = np.maximum(det[:, None, :2], gt[None, :, :2])
    rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    return inter / (area_det[:, None] + area_gt[None, :] - inter)


class MeanAveragePrecision(Metric):
    r"""COCO mean average precision / mean average recall for object detection.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.detection import MeanAveragePrecision
        >>> preds = [{"boxes": jnp.array([[258.0, 41.0, 606.0, 285.0]]),
        ...           "scores": jnp.array([0.536]),
        ...           "labels": jnp.array([0])}]
        >>> target = [{"boxes": jnp.array([[214.0, 41.0, 562.0, 285.0]]),
        ...            "labels": jnp.array([0])}]
        >>> metric = MeanAveragePrecision()
        >>> metric.update(preds, target)
        >>> result = metric.compute()
        >>> result["map_50"].round(4)
        Array(1., dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: str = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        **kwargs: Any,
    ) -> None:
        kwargs.setdefault("jit_update", False)
        super().__init__(**kwargs)

        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        if iou_type != "bbox":
            raise ValueError(f"Expected argument `iou_type` to be `bbox` (native matcher) but got {iou_type}")
        self.iou_type = iou_type
        self.iou_thresholds = iou_thresholds or np.linspace(0.5, 0.95, 10).round(2).tolist()
        self.rec_thresholds = rec_thresholds or np.linspace(0.0, 1.0, 101).round(2).tolist()
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics

        # per-image ragged lists: a concat-gather would lose image boundaries, so
        # multi-process sync is explicitly unsupported (see _sync_dist)
        self.add_state("detections", [], dist_reduce_fx=None)
        self.add_state("detection_scores", [], dist_reduce_fx=None)
        self.add_state("detection_labels", [], dist_reduce_fx=None)
        self.add_state("groundtruths", [], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", [], dist_reduce_fx=None)

    def _sync_dist(self, dist_sync_fn=None) -> None:
        if dist_sync_fn is None and self.dist_sync_fn is None:
            raise NotImplementedError(
                "MeanAveragePrecision holds per-image ragged states that the built-in sync"
                " cannot gather without corrupting image boundaries. Provide a custom"
                " `dist_sync_fn` that gathers the per-image lists, or compute per process."
            )
        super()._sync_dist(dist_sync_fn)

    def update(self, preds: Sequence[Dict[str, Array]], target: Sequence[Dict[str, Array]]) -> None:
        """Store per-image detections and ground truths."""
        _input_validator(preds, target)

        for item in preds:
            boxes = _fix_empty_tensors(jnp.asarray(item["boxes"], dtype=jnp.float32))
            if boxes.size:
                boxes = box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
            self.detections.append(np.asarray(boxes))
            self.detection_labels.append(np.asarray(item["labels"]))
            self.detection_scores.append(np.asarray(item["scores"]))

        for item in target:
            boxes = _fix_empty_tensors(jnp.asarray(item["boxes"], dtype=jnp.float32))
            if boxes.size:
                boxes = box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
            self.groundtruths.append(np.asarray(boxes))
            self.groundtruth_labels.append(np.asarray(item["labels"]))

    # --------------------------------------------------------------- evaluation

    def _get_classes(self) -> List[int]:
        labels = [lab for lab in self.detection_labels + self.groundtruth_labels if lab.size]
        if labels:
            return sorted({int(v) for v in np.concatenate(labels)})
        return []

    def _prepare_image(self, idx: int, class_id: int, max_det: int) -> Optional[Dict[str, np.ndarray]]:
        """Per-(image, class) setup shared across area ranges: filtered + score-sorted
        detections, filtered GTs, areas, and the IoU matrix (computed once)."""
        gt_mask = self.groundtruth_labels[idx] == class_id
        det_mask = self.detection_labels[idx] == class_id
        if not gt_mask.any() and not det_mask.any():
            return None

        gt = self.groundtruths[idx][gt_mask]
        det = self.detections[idx][det_mask]
        scores = self.detection_scores[idx][det_mask]

        dtind = np.argsort(-scores, kind="mergesort")[:max_det]
        det = det[dtind]
        scores_sorted = scores[dtind]

        return {
            "gt": gt,
            "gt_areas": _np_box_area(gt) if len(gt) else np.zeros(0),
            "det_areas": _np_box_area(det) if len(det) else np.zeros(0),
            "scores_sorted": scores_sorted,
            "ious": _np_box_iou(det, gt) if len(det) and len(gt) else np.zeros((len(det), len(gt))),
        }

    def _evaluate_image(
        self, prep: Optional[Dict[str, np.ndarray]], area_range: Tuple[float, float]
    ) -> Optional[Dict[str, np.ndarray]]:
        """Greedy best-match evaluation at all IoU thresholds for one area range."""
        if prep is None:
            return None

        # sort gts so ignored (out-of-area) come last
        gt_out_of_area = (prep["gt_areas"] < area_range[0]) | (prep["gt_areas"] > area_range[1])
        gtind = np.argsort(gt_out_of_area, kind="stable")
        gt_ignore = gt_out_of_area[gtind]

        num_thrs = len(self.iou_thresholds)
        num_gt = len(gt_ignore)
        num_det = len(prep["scores_sorted"])
        gt_matches = np.zeros((num_thrs, num_gt), dtype=bool)
        det_matches = np.zeros((num_thrs, num_det), dtype=bool)
        det_ignore = np.zeros((num_thrs, num_det), dtype=bool)

        if num_gt and num_det:
            ious = prep["ious"][:, gtind]
            for t_idx, threshold in enumerate(self.iou_thresholds):
                for d_idx in range(num_det):
                    candidates = ious[d_idx] * ~(gt_matches[t_idx] | gt_ignore)
                    m = int(candidates.argmax())
                    if candidates[m] <= threshold:
                        continue
                    det_ignore[t_idx, d_idx] = gt_ignore[m]
                    det_matches[t_idx, d_idx] = True
                    gt_matches[t_idx, m] = True

        # unmatched detections outside the area range are ignored
        det_out_of_area = (prep["det_areas"] < area_range[0]) | (prep["det_areas"] > area_range[1])
        det_ignore |= ~det_matches & det_out_of_area[None, :]

        return {
            "dtMatches": det_matches,
            "dtScores": prep["scores_sorted"],
            "gtIgnore": gt_ignore,
            "dtIgnore": det_ignore,
        }

    def _accumulate(
        self, classes: List[int]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """PR-curve accumulation → precision[T,R,K,A,M] and recall[T,K,A,M]."""
        num_thrs = len(self.iou_thresholds)
        num_rec = len(self.rec_thresholds)
        num_cls = len(classes)
        num_areas = len(_BBOX_AREA_RANGES)
        num_maxdet = len(self.max_detection_thresholds)
        num_imgs = len(self.groundtruths)

        precision = -np.ones((num_thrs, num_rec, num_cls, num_areas, num_maxdet))
        recall = -np.ones((num_thrs, num_cls, num_areas, num_maxdet))
        rec_thrs = np.asarray(self.rec_thresholds)
        max_det_cap = self.max_detection_thresholds[-1]

        for k_idx, class_id in enumerate(classes):
            preps = [self._prepare_image(i, class_id, max_det_cap) for i in range(num_imgs)]
            for a_idx, area_range in enumerate(_BBOX_AREA_RANGES.values()):
                evals = [self._evaluate_image(prep, area_range) for prep in preps]
                evals = [e for e in evals if e is not None]
                if not evals:
                    continue
                for m_idx, max_det in enumerate(self.max_detection_thresholds):
                    det_scores = np.concatenate([e["dtScores"][:max_det] for e in evals])
                    inds = np.argsort(-det_scores, kind="mergesort")
                    det_matches = np.concatenate(
                        [e["dtMatches"][:, :max_det] for e in evals], axis=1
                    )[:, inds]
                    det_ignore = np.concatenate(
                        [e["dtIgnore"][:, :max_det] for e in evals], axis=1
                    )[:, inds]
                    gt_ignore = np.concatenate([e["gtIgnore"] for e in evals])
                    npig = int((~gt_ignore).sum())
                    if npig == 0:
                        continue
                    tps = det_matches & ~det_ignore
                    fps = ~det_matches & ~det_ignore
                    tp_sum = np.cumsum(tps, axis=1, dtype=np.float64)
                    fp_sum = np.cumsum(fps, axis=1, dtype=np.float64)

                    for t_idx in range(num_thrs):
                        tp = tp_sum[t_idx]
                        fp = fp_sum[t_idx]
                        rc = tp / npig
                        pr = tp / (fp + tp + np.finfo(np.float64).eps)
                        recall[t_idx, k_idx, a_idx, m_idx] = rc[-1] if len(tp) else 0

                        # monotone non-increasing precision envelope (right-to-left max)
                        pr = np.maximum.accumulate(pr[::-1])[::-1]

                        inds_r = np.searchsorted(rc, rec_thrs, side="left")
                        prec = np.zeros(num_rec)
                        valid = inds_r < len(pr)
                        prec[valid] = pr[inds_r[valid]]
                        precision[t_idx, :, k_idx, a_idx, m_idx] = prec

        return precision, recall

    @staticmethod
    def _mean_over_valid(values: np.ndarray) -> Array:
        valid = values > -1
        if not valid.any():
            return jnp.asarray(-1.0)
        return jnp.asarray(values[valid].mean(), dtype=jnp.float32)

    def _summarize(
        self,
        precision: np.ndarray,
        recall: np.ndarray,
        avg_prec: bool = True,
        iou_threshold: Optional[float] = None,
        area_range: str = "all",
        max_dets: int = 100,
    ) -> Array:
        """COCO summarization: mean over valid entries of the selected PR slab."""
        a_idx = list(_BBOX_AREA_RANGES).index(area_range)
        m_idx = self.max_detection_thresholds.index(max_dets)
        if avg_prec:
            vals = precision[..., a_idx, m_idx]
            if iou_threshold is not None:
                vals = vals[self.iou_thresholds.index(iou_threshold)]
        else:
            vals = recall[..., a_idx, m_idx]
            if iou_threshold is not None:
                vals = vals[self.iou_thresholds.index(iou_threshold)]
        return self._mean_over_valid(vals)

    def compute(self) -> Dict[str, Array]:
        """COCO mAP/mAR metric dictionary over all accumulated images."""
        classes = self._get_classes()
        precision, recall = self._accumulate(classes)
        last_max_det = self.max_detection_thresholds[-1]

        metrics: Dict[str, Array] = {}
        metrics["map"] = self._summarize(precision, recall, True, max_dets=last_max_det)
        metrics["map_50"] = (
            self._summarize(precision, recall, True, iou_threshold=0.5, max_dets=last_max_det)
            if 0.5 in self.iou_thresholds
            else jnp.asarray(-1.0)
        )
        metrics["map_75"] = (
            self._summarize(precision, recall, True, iou_threshold=0.75, max_dets=last_max_det)
            if 0.75 in self.iou_thresholds
            else jnp.asarray(-1.0)
        )
        for area in ("small", "medium", "large"):
            metrics[f"map_{area}"] = self._summarize(
                precision, recall, True, area_range=area, max_dets=last_max_det
            )
        for max_det in self.max_detection_thresholds:
            metrics[f"mar_{max_det}"] = self._summarize(precision, recall, False, max_dets=max_det)
        for area in ("small", "medium", "large"):
            metrics[f"mar_{area}"] = self._summarize(
                precision, recall, False, area_range=area, max_dets=last_max_det
            )

        map_per_class = jnp.asarray([-1.0])
        mar_per_class = jnp.asarray([-1.0])
        if self.class_metrics and classes:
            map_list, mar_list = [], []
            for k_idx in range(len(classes)):
                cls_prec = precision[:, :, k_idx : k_idx + 1]
                cls_rec = recall[:, k_idx : k_idx + 1]
                map_list.append(self._summarize(cls_prec, cls_rec, True, max_dets=last_max_det))
                mar_list.append(self._summarize(cls_prec, cls_rec, False, max_dets=last_max_det))
            map_per_class = jnp.stack(map_list)
            mar_per_class = jnp.stack(mar_list)
        metrics["map_per_class"] = map_per_class
        metrics[f"mar_{last_max_det}_per_class"] = mar_per_class
        metrics["classes"] = jnp.asarray(classes, dtype=jnp.int32)
        return metrics
