"""Detection metrics (stateful modules).

Parity: reference ``src/torchmetrics/detection/__init__.py`` (7 classes).
"""

from torchmetrics_tpu.detection.iou_modules import (
    CompleteIntersectionOverUnion,
    DistanceIntersectionOverUnion,
    GeneralizedIntersectionOverUnion,
    IntersectionOverUnion,
)
from torchmetrics_tpu.detection.mean_ap import MeanAveragePrecision
from torchmetrics_tpu.detection.panoptic import ModifiedPanopticQuality, PanopticQuality

__all__ = [
    "CompleteIntersectionOverUnion",
    "DistanceIntersectionOverUnion",
    "GeneralizedIntersectionOverUnion",
    "IntersectionOverUnion",
    "MeanAveragePrecision",
    "ModifiedPanopticQuality",
    "PanopticQuality",
]
