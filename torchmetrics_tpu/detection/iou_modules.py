"""IoU-family detection metric modules.

Parity: reference ``src/torchmetrics/detection/{iou,giou,diou,ciou}.py``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.detection.helpers import _fix_empty_tensors, _input_validator
from torchmetrics_tpu.functional.detection.box_ops import (
    box_convert,
    box_iou,
    complete_box_iou,
    distance_box_iou,
    generalized_box_iou,
)
from torchmetrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class IntersectionOverUnion(Metric):
    r"""Intersection over union of detection boxes against ground-truth boxes.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.detection import IntersectionOverUnion
        >>> preds = [{"boxes": jnp.array([[296.55, 93.96, 314.97, 152.79]]),
        ...           "labels": jnp.array([0])}]
        >>> target = [{"boxes": jnp.array([[300.00, 100.00, 315.00, 150.00]]),
        ...            "labels": jnp.array([0])}]
        >>> metric = IntersectionOverUnion()
        >>> metric(preds, target)["iou"].round(4)
        Array(0.68979996, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    _iou_type: str = "iou"
    _invalid_val: float = -1.0
    _pairwise_fn = staticmethod(box_iou)

    groundtruth_labels: List[Array]
    iou_matrix: List[Array]

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_threshold: Optional[float] = None,
        class_metrics: bool = False,
        respect_labels: bool = True,
        **kwargs: Any,
    ) -> None:
        kwargs.setdefault("jit_update", False)
        super().__init__(**kwargs)
        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        self.iou_threshold = iou_threshold
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics
        if not isinstance(respect_labels, bool):
            raise ValueError("Expected argument `respect_labels` to be a boolean")
        self.respect_labels = respect_labels

        # per-image NxM matrices, ragged in both dims: synced across hosts via the
        # pad-to-max ragged gather (shape table + flat buffer), which keeps the
        # per-image boundaries that a plain concat-gather would destroy
        self.add_state("groundtruth_labels", [], dist_reduce_fx=None)
        self.add_state("iou_matrix", [], dist_reduce_fx=None)

    def _sync_dist(self, dist_sync_fn=None) -> None:
        if dist_sync_fn is not None or self.dist_sync_fn is not None:
            super()._sync_dist(dist_sync_fn)
            return
        import numpy as np

        from torchmetrics_tpu.parallel.sync import allgather_ragged_arrays

        sv = self._state_values
        sv["iou_matrix"] = [
            jnp.asarray(m) for m in allgather_ragged_arrays([np.asarray(m) for m in sv["iou_matrix"]], ndim=2)
        ]
        sv["groundtruth_labels"] = [
            jnp.asarray(lab)
            for lab in allgather_ragged_arrays(
                [np.asarray(lab).reshape(-1) for lab in sv["groundtruth_labels"]], ndim=1, dtype=np.int64
            )
        ]

    def update(self, preds: List[Dict[str, Array]], target: List[Dict[str, Array]]) -> None:
        """Compute and store the per-image (thresholded) IoU matrix."""
        _input_validator(preds, target, ignore_score=True)

        for p, t in zip(preds, target):
            det_boxes = self._get_safe_item_values(p["boxes"])
            gt_boxes = self._get_safe_item_values(t["boxes"])
            self.groundtruth_labels.append(jnp.asarray(t["labels"]))

            iou_matrix = self._pairwise_fn(det_boxes, gt_boxes)
            if self.iou_threshold is not None:
                iou_matrix = jnp.where(iou_matrix < self.iou_threshold, self._invalid_val, iou_matrix)
            if self.respect_labels:
                label_eq = jnp.asarray(p["labels"])[:, None] == jnp.asarray(t["labels"])[None, :]
                iou_matrix = jnp.where(label_eq, iou_matrix, self._invalid_val)
            self.iou_matrix.append(iou_matrix)

    def _get_safe_item_values(self, boxes: Array) -> Array:
        boxes = _fix_empty_tensors(jnp.asarray(boxes, dtype=jnp.float32))
        if boxes.size > 0:
            boxes = box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
        return boxes

    def compute(self) -> Dict[str, Array]:
        """Mean (valid) IoU, optionally per class."""
        import numpy as np

        valid_vals = [
            np.asarray(mat)[np.asarray(mat) != self._invalid_val] for mat in self.iou_matrix
        ]
        flat = np.concatenate(valid_vals) if valid_vals else np.zeros(0)
        score = jnp.asarray(flat.mean() if flat.size else 0.0, dtype=jnp.float32)
        results: Dict[str, Array] = {f"{self._iou_type}": score}

        if self.class_metrics:
            gt_labels = dim_zero_cat(self.groundtruth_labels)
            classes = sorted({int(v) for v in np.asarray(gt_labels)}) if gt_labels.size else []
            for cl in classes:
                masked_iou, observed = 0.0, 0
                for mat, gt_lab in zip(self.iou_matrix, self.groundtruth_labels):
                    sub = np.asarray(mat)[:, np.asarray(gt_lab) == cl]
                    sub = sub[sub != self._invalid_val]
                    masked_iou += sub.sum()
                    observed += sub.size
                results[f"{self._iou_type}/cl_{cl}"] = jnp.asarray(
                    masked_iou / observed if observed else 0.0, dtype=jnp.float32
                )
        return results


class GeneralizedIntersectionOverUnion(IntersectionOverUnion):
    r"""Generalized IoU of detection boxes against ground-truth boxes.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.detection import GeneralizedIntersectionOverUnion
        >>> preds = [{"boxes": jnp.array([[296.55, 93.96, 314.97, 152.79]]),
        ...           "labels": jnp.array([0])}]
        >>> target = [{"boxes": jnp.array([[300.00, 100.00, 315.00, 150.00]]),
        ...            "labels": jnp.array([0])}]
        >>> metric = GeneralizedIntersectionOverUnion()
        >>> metric(preds, target)["giou"].round(4)
        Array(0.6895, dtype=float32)
    """

    _iou_type: str = "giou"
    _invalid_val: float = -1.0
    _pairwise_fn = staticmethod(generalized_box_iou)


class DistanceIntersectionOverUnion(IntersectionOverUnion):
    r"""Distance IoU of detection boxes against ground-truth boxes.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.detection import DistanceIntersectionOverUnion
        >>> preds = [{"boxes": jnp.array([[296.55, 93.96, 314.97, 152.79]]),
        ...           "labels": jnp.array([0])}]
        >>> target = [{"boxes": jnp.array([[300.00, 100.00, 315.00, 150.00]]),
        ...            "labels": jnp.array([0])}]
        >>> metric = DistanceIntersectionOverUnion()
        >>> metric(preds, target)["diou"].round(4)
        Array(0.68829995, dtype=float32)
    """

    _iou_type: str = "diou"
    _invalid_val: float = -1.0
    _pairwise_fn = staticmethod(distance_box_iou)


class CompleteIntersectionOverUnion(IntersectionOverUnion):
    r"""Complete IoU of detection boxes against ground-truth boxes.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.detection import CompleteIntersectionOverUnion
        >>> preds = [{"boxes": jnp.array([[296.55, 93.96, 314.97, 152.79]]),
        ...           "labels": jnp.array([0])}]
        >>> target = [{"boxes": jnp.array([[300.00, 100.00, 315.00, 150.00]]),
        ...            "labels": jnp.array([0])}]
        >>> metric = CompleteIntersectionOverUnion()
        >>> metric(preds, target)["ciou"].round(4)
        Array(0.68829995, dtype=float32)
    """

    _iou_type: str = "ciou"
    _invalid_val: float = -2.0
    _pairwise_fn = staticmethod(complete_box_iou)
