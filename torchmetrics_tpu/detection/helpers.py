"""Input validation helpers for detection metrics.

Parity: reference ``src/torchmetrics/detection/helpers.py``.
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp

Array = jax.Array


def _fix_empty_tensors(boxes: Array) -> Array:
    """Give empty box tensors the canonical (0, 4) shape."""
    boxes = jnp.asarray(boxes)
    if boxes.size == 0 and boxes.ndim == 1:
        return boxes.reshape(0, 4)
    return boxes


def _input_validator(
    preds: Sequence[Dict[str, Array]],
    targets: Sequence[Dict[str, Array]],
    ignore_score: bool = False,
    iou_type: str = "bbox",
) -> None:
    """Validate the list-of-dicts detection input format."""
    if not isinstance(preds, Sequence):
        raise ValueError(f"Expected argument `preds` to be of type Sequence, but got {preds}")
    if not isinstance(targets, Sequence):
        raise ValueError(f"Expected argument `target` to be of type Sequence, but got {targets}")
    if len(preds) != len(targets):
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same length, but got {len(preds)} and {len(targets)}"
        )

    item_key = "masks" if iou_type == "segm" else "boxes"
    for k in [item_key, "labels"] + ([] if ignore_score else ["scores"]):
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in [item_key, "labels"]:
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")

    def _n_items(item: Dict[str, Array]) -> int:
        arr = jnp.asarray(item[item_key])
        return arr.shape[0] if arr.size else 0

    for i, item in enumerate(targets):
        n_boxes = _n_items(item)
        n_labels = jnp.asarray(item["labels"]).shape[0] if jnp.asarray(item["labels"]).size else 0
        if n_boxes != n_labels:
            raise ValueError(
                f"Input '{i}' of `target` has a different length of {item_key} ({n_boxes}) and labels ({n_labels})"
            )
    if not ignore_score:
        for i, item in enumerate(preds):
            n_boxes = _n_items(item)
            n_labels = jnp.asarray(item["labels"]).shape[0] if jnp.asarray(item["labels"]).size else 0
            n_scores = jnp.asarray(item["scores"]).shape[0] if jnp.asarray(item["scores"]).size else 0
            if n_boxes != n_labels or n_boxes != n_scores:
                raise ValueError(
                    f"Input '{i}' of `preds` has a different length of {item_key} ({n_boxes}), labels ({n_labels})"
                    f" and scores ({n_scores})"
                )
