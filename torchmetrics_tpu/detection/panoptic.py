"""Panoptic quality metric modules.

Parity: reference ``src/torchmetrics/detection/panoptic_qualities.py``.
"""

from __future__ import annotations

from typing import Any, Collection

import jax
import jax.numpy as jnp

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.functional.detection.panoptic import (
    _get_category_id_to_continuous_id,
    _get_void_color,
    _panoptic_quality_compute,
    _panoptic_quality_update,
    _parse_categories,
    _prepocess_inputs,
    _validate_inputs,
)

Array = jax.Array


class PanopticQuality(Metric):
    r"""Panoptic quality of (category, instance) panoptic maps.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.detection import PanopticQuality
        >>> preds = jnp.array([[[[6, 0], [0, 0], [6, 0], [6, 0]],
        ...                     [[0, 0], [0, 0], [6, 0], [0, 1]],
        ...                     [[0, 0], [0, 0], [6, 0], [0, 1]],
        ...                     [[0, 0], [7, 0], [6, 0], [1, 0]],
        ...                     [[0, 0], [7, 0], [7, 0], [7, 0]]]])
        >>> target = jnp.array([[[[6, 0], [0, 1], [6, 0], [0, 1]],
        ...                      [[0, 1], [0, 1], [6, 0], [0, 1]],
        ...                      [[0, 1], [0, 1], [6, 0], [1, 0]],
        ...                      [[0, 1], [7, 0], [1, 0], [1, 0]],
        ...                      [[0, 1], [7, 0], [7, 0], [7, 0]]]])
        >>> panoptic_quality = PanopticQuality(things={0, 1}, stuffs={6, 7})
        >>> panoptic_quality(preds, target).round(4)
        Array(0.5463, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    iou_sum: Array
    true_positives: Array
    false_positives: Array
    false_negatives: Array

    def __init__(
        self,
        things: Collection[int],
        stuffs: Collection[int],
        allow_unknown_preds_category: bool = False,
        return_sq_and_rq: bool = False,
        return_per_class: bool = False,
        **kwargs: Any,
    ) -> None:
        kwargs.setdefault("jit_update", False)
        super().__init__(**kwargs)
        things_set, stuffs_set = _parse_categories(things, stuffs)
        self.things = things_set
        self.stuffs = stuffs_set
        self.void_color = _get_void_color(things_set, stuffs_set)
        self.cat_id_to_continuous_id = _get_category_id_to_continuous_id(things_set, stuffs_set)
        self.allow_unknown_preds_category = allow_unknown_preds_category
        self.return_sq_and_rq = return_sq_and_rq
        self.return_per_class = return_per_class

        num_categories = len(things_set) + len(stuffs_set)
        self.add_state("iou_sum", jnp.zeros(num_categories), dist_reduce_fx="sum")
        self.add_state("true_positives", jnp.zeros(num_categories, dtype=jnp.int32), dist_reduce_fx="sum")
        self.add_state("false_positives", jnp.zeros(num_categories, dtype=jnp.int32), dist_reduce_fx="sum")
        self.add_state("false_negatives", jnp.zeros(num_categories, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate per-category PQ statistics for the batch."""
        _validate_inputs(preds, target)
        flatten_preds = _prepocess_inputs(
            self.things, self.stuffs, preds, self.void_color, self.allow_unknown_preds_category
        )
        flatten_target = _prepocess_inputs(self.things, self.stuffs, target, self.void_color, True)
        iou_sum, true_positives, false_positives, false_negatives = self._update_fn(
            flatten_preds, flatten_target
        )
        self.iou_sum = self.iou_sum + iou_sum
        self.true_positives = self.true_positives + true_positives
        self.false_positives = self.false_positives + false_positives
        self.false_negatives = self.false_negatives + false_negatives

    def _update_fn(self, flatten_preds, flatten_target):
        return _panoptic_quality_update(
            flatten_preds, flatten_target, self.cat_id_to_continuous_id, self.void_color
        )

    def compute(self) -> Array:
        """Panoptic quality over accumulated statistics."""
        pq, sq, rq, pq_avg, sq_avg, rq_avg = _panoptic_quality_compute(
            self.iou_sum, self.true_positives, self.false_positives, self.false_negatives
        )
        if self.return_per_class:
            if self.return_sq_and_rq:
                return jnp.stack((pq, sq, rq), axis=-1)
            return pq.reshape(1, -1)
        if self.return_sq_and_rq:
            return jnp.stack((pq_avg, sq_avg, rq_avg), axis=0)
        return pq_avg


class ModifiedPanopticQuality(PanopticQuality):
    r"""Modified panoptic quality (stuff classes scored without segment matching).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.detection import ModifiedPanopticQuality
        >>> preds = jnp.array([[[0, 0], [0, 1], [6, 0], [7, 0], [0, 2], [1, 0]]])
        >>> target = jnp.array([[[0, 1], [0, 0], [6, 0], [7, 0], [6, 0], [255, 0]]])
        >>> pq_modified = ModifiedPanopticQuality(
        ...     things={0, 1}, stuffs={6, 7}, allow_unknown_preds_category=True)
        >>> pq_modified(preds, target).round(4)
        Array(0.76669997, dtype=float32)
    """

    def __init__(
        self,
        things: Collection[int],
        stuffs: Collection[int],
        allow_unknown_preds_category: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            things=things,
            stuffs=stuffs,
            allow_unknown_preds_category=allow_unknown_preds_category,
            **kwargs,
        )

    def _update_fn(self, flatten_preds, flatten_target):
        return _panoptic_quality_update(
            flatten_preds,
            flatten_target,
            self.cat_id_to_continuous_id,
            self.void_color,
            modified_metric_stuffs=self.stuffs,
        )
