"""Clustering metric modules.

Parity: reference ``src/torchmetrics/clustering/*.py`` — every class stores label (or
data) "cat" states and evaluates its functional at compute time, exactly like the
reference (contingency matrices need the full epoch's label sets).
"""

from __future__ import annotations

from typing import Any

import jax

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.functional.clustering import (
    adjusted_mutual_info_score,
    adjusted_rand_score,
    calinski_harabasz_score,
    completeness_score,
    davies_bouldin_score,
    dunn_index,
    fowlkes_mallows_index,
    homogeneity_score,
    mutual_info_score,
    normalized_mutual_info_score,
    rand_score,
    v_measure_score,
)
from torchmetrics_tpu.functional.clustering.utils import _validate_average_method_arg, check_cluster_labels
from torchmetrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class _LabelPairClusteringMetric(Metric):
    """Base for metrics over (predicted labels, target labels) pairs."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Store the batch's cluster labels."""
        check_cluster_labels(preds, target)
        self.preds.append(preds)
        self.target.append(target)


class _IntrinsicClusteringMetric(Metric):
    """Base for metrics over (embedded data, cluster labels) pairs."""

    is_differentiable = True
    full_state_update = True
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("data", [], dist_reduce_fx="cat")
        self.add_state("labels", [], dist_reduce_fx="cat")

    def update(self, data: Array, labels: Array) -> None:
        """Store the batch's embeddings and labels."""
        self.data.append(data)
        self.labels.append(labels)


class MutualInfoScore(_LabelPairClusteringMetric):
    r"""Mutual information between two clusterings.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.clustering import MutualInfoScore
        >>> mi = MutualInfoScore()
        >>> mi(jnp.array([1, 3, 2, 0, 1]), jnp.array([0, 3, 2, 2, 1])).round(4)
        Array(1.0548999, dtype=float32)
    """

    def compute(self) -> Array:
        """MI over all accumulated labels."""
        return mutual_info_score(dim_zero_cat(self.preds), dim_zero_cat(self.target))


class AdjustedMutualInfoScore(_LabelPairClusteringMetric):
    r"""Adjusted mutual information between two clusterings.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.clustering import AdjustedMutualInfoScore
        >>> ami = AdjustedMutualInfoScore(average_method="arithmetic")
        >>> ami(jnp.array([2, 1, 0, 1, 0]), jnp.array([0, 2, 1, 1, 0])).round(4)
        Array(-0.25, dtype=float32)
    """

    plot_lower_bound: float = -1.0

    def __init__(self, average_method: str = "arithmetic", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_average_method_arg(average_method)
        self.average_method = average_method

    def compute(self) -> Array:
        """AMI over all accumulated labels."""
        return adjusted_mutual_info_score(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.average_method
        )


class NormalizedMutualInfoScore(_LabelPairClusteringMetric):
    r"""Normalized mutual information between two clusterings.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.clustering import NormalizedMutualInfoScore
        >>> nmi = NormalizedMutualInfoScore("arithmetic")
        >>> nmi(jnp.array([1, 3, 2, 0, 1]), jnp.array([0, 3, 2, 2, 1])).round(4)
        Array(0.7919, dtype=float32)
    """

    def __init__(self, average_method: str = "arithmetic", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_average_method_arg(average_method)
        self.average_method = average_method

    def compute(self) -> Array:
        """NMI over all accumulated labels."""
        return normalized_mutual_info_score(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.average_method
        )


class RandScore(_LabelPairClusteringMetric):
    r"""Rand score between two clusterings.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.clustering import RandScore
        >>> metric = RandScore()
        >>> metric(jnp.array([0, 0, 1, 2]), jnp.array([0, 0, 1, 1])).round(4)
        Array(0.8333, dtype=float32)
    """

    def compute(self) -> Array:
        """Rand score over all accumulated labels."""
        return rand_score(dim_zero_cat(self.preds), dim_zero_cat(self.target))


class AdjustedRandScore(_LabelPairClusteringMetric):
    r"""Adjusted Rand score between two clusterings.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.clustering import AdjustedRandScore
        >>> metric = AdjustedRandScore()
        >>> metric(jnp.array([0, 0, 1, 2]), jnp.array([0, 0, 1, 1])).round(4)
        Array(0.5714, dtype=float32)
    """

    plot_lower_bound: float = -1.0

    def compute(self) -> Array:
        """ARI over all accumulated labels."""
        return adjusted_rand_score(dim_zero_cat(self.preds), dim_zero_cat(self.target))


class FowlkesMallowsIndex(_LabelPairClusteringMetric):
    r"""Fowlkes-Mallows index between two clusterings.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.clustering import FowlkesMallowsIndex
        >>> fmi = FowlkesMallowsIndex()
        >>> fmi(jnp.array([2, 2, 0, 1, 0]), jnp.array([2, 2, 1, 1, 0])).round(4)
        Array(0.5, dtype=float32)
    """

    def compute(self) -> Array:
        """FMI over all accumulated labels."""
        return fowlkes_mallows_index(dim_zero_cat(self.preds), dim_zero_cat(self.target))


class HomogeneityScore(_LabelPairClusteringMetric):
    r"""Homogeneity score between two clusterings.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.clustering import HomogeneityScore
        >>> metric = HomogeneityScore()
        >>> metric(jnp.array([0, 0, 1, 2]), jnp.array([0, 0, 1, 1]))
        Array(1., dtype=float32)
    """

    def compute(self) -> Array:
        """Homogeneity over all accumulated labels."""
        return homogeneity_score(dim_zero_cat(self.preds), dim_zero_cat(self.target))


class CompletenessScore(_LabelPairClusteringMetric):
    r"""Completeness score between two clusterings.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.clustering import CompletenessScore
        >>> metric = CompletenessScore()
        >>> metric(jnp.array([0, 0, 1, 1]), jnp.array([1, 1, 0, 0]))
        Array(1., dtype=float32)
    """

    def compute(self) -> Array:
        """Completeness over all accumulated labels."""
        return completeness_score(dim_zero_cat(self.preds), dim_zero_cat(self.target))


class VMeasureScore(_LabelPairClusteringMetric):
    r"""V-measure score between two clusterings.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.clustering import VMeasureScore
        >>> metric = VMeasureScore(beta=1.0)
        >>> metric(jnp.array([0, 0, 1, 2]), jnp.array([0, 0, 1, 1])).round(4)
        Array(0.79999995, dtype=float32)
    """

    def __init__(self, beta: float = 1.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(beta, float) and beta > 0):
            raise ValueError(f"Argument `beta` should be a positive float. Got {beta}.")
        self.beta = beta

    def compute(self) -> Array:
        """V-measure over all accumulated labels."""
        return v_measure_score(dim_zero_cat(self.preds), dim_zero_cat(self.target), self.beta)


class CalinskiHarabaszScore(_IntrinsicClusteringMetric):
    r"""Calinski-Harabasz score for intrinsic cluster evaluation.

    Example:
        >>> import jax
        >>> from torchmetrics_tpu.clustering import CalinskiHarabaszScore
        >>> data = jax.random.normal(jax.random.PRNGKey(42), (10, 3))
        >>> labels = jax.random.randint(jax.random.PRNGKey(0), (10,), 0, 2)
        >>> chs = CalinskiHarabaszScore()
        >>> float(chs(data, labels)) > 0
        True
    """

    higher_is_better = True

    def compute(self) -> Array:
        """CH score over all accumulated data."""
        return calinski_harabasz_score(dim_zero_cat(self.data), dim_zero_cat(self.labels))


class DaviesBouldinScore(_IntrinsicClusteringMetric):
    r"""Davies-Bouldin score for intrinsic cluster evaluation.

    Example:
        >>> import jax
        >>> from torchmetrics_tpu.clustering import DaviesBouldinScore
        >>> data = jax.random.normal(jax.random.PRNGKey(42), (10, 3))
        >>> labels = jax.random.randint(jax.random.PRNGKey(0), (10,), 0, 2)
        >>> dbs = DaviesBouldinScore()
        >>> float(dbs(data, labels)) > 0
        True
    """

    higher_is_better = False

    def compute(self) -> Array:
        """DB score over all accumulated data."""
        return davies_bouldin_score(dim_zero_cat(self.data), dim_zero_cat(self.labels))


class DunnIndex(_IntrinsicClusteringMetric):
    r"""Dunn index for intrinsic cluster evaluation.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.clustering import DunnIndex
        >>> data = jnp.array([[0., 0.], [0.5, 0.], [1., 0.], [0.5, 1.]])
        >>> labels = jnp.array([0, 0, 0, 1])
        >>> dunn = DunnIndex(p=2)
        >>> dunn(data, labels)
        Array(2., dtype=float32)
    """

    higher_is_better = True

    def __init__(self, p: float = 2, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.p = p

    def compute(self) -> Array:
        """Dunn index over all accumulated data."""
        return dunn_index(dim_zero_cat(self.data), dim_zero_cat(self.labels), self.p)
