"""Clustering metrics (stateful modules).

Parity: reference ``src/torchmetrics/clustering/__init__.py`` (12 classes).
"""

from torchmetrics_tpu.clustering.modules import (
    AdjustedMutualInfoScore,
    AdjustedRandScore,
    CalinskiHarabaszScore,
    CompletenessScore,
    DaviesBouldinScore,
    DunnIndex,
    FowlkesMallowsIndex,
    HomogeneityScore,
    MutualInfoScore,
    NormalizedMutualInfoScore,
    RandScore,
    VMeasureScore,
)

__all__ = [
    "AdjustedMutualInfoScore",
    "AdjustedRandScore",
    "CalinskiHarabaszScore",
    "CompletenessScore",
    "DaviesBouldinScore",
    "DunnIndex",
    "FowlkesMallowsIndex",
    "HomogeneityScore",
    "MutualInfoScore",
    "NormalizedMutualInfoScore",
    "RandScore",
    "VMeasureScore",
]
