"""Wrapper metrics: composition utilities around any ``Metric``.

Parity: reference ``src/torchmetrics/wrappers/__init__.py`` (11 exported classes).
"""

from torchmetrics_tpu.wrappers.abstract import WrapperMetric
from torchmetrics_tpu.wrappers.bootstrapping import BootStrapper
from torchmetrics_tpu.wrappers.classwise import ClasswiseWrapper
from torchmetrics_tpu.wrappers.feature_share import FeatureShare
from torchmetrics_tpu.wrappers.minmax import MinMaxMetric
from torchmetrics_tpu.wrappers.multioutput import MultioutputWrapper
from torchmetrics_tpu.wrappers.multitask import MultitaskWrapper
from torchmetrics_tpu.wrappers.running import Running, RunningMean, RunningSum
from torchmetrics_tpu.wrappers.tracker import MetricTracker
from torchmetrics_tpu.wrappers.transformations import (
    BinaryTargetTransformer,
    LambdaInputTransformer,
    MetricInputTransformer,
)

__all__ = [
    "WrapperMetric",
    "BootStrapper",
    "ClasswiseWrapper",
    "FeatureShare",
    "MinMaxMetric",
    "MultioutputWrapper",
    "MultitaskWrapper",
    "MetricTracker",
    "Running",
    "RunningMean",
    "RunningSum",
    "MetricInputTransformer",
    "LambdaInputTransformer",
    "BinaryTargetTransformer",
]
