"""Multioutput wrapper: one metric copy per output dimension.

Parity: reference ``src/torchmetrics/wrappers/multioutput.py``.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric, apply_to_arrays

Array = jax.Array


def _get_nan_indices(*arrays: Array) -> Array:
    """Boolean mask of rows containing any NaN in any of the given arrays."""
    if len(arrays) == 0:
        raise ValueError("Must pass at least one tensor as argument")
    sentinel = arrays[0]
    nan_idxs = jnp.zeros(len(sentinel), dtype=bool)
    for a in arrays:
        flat = a.reshape(len(a), -1)
        nan_idxs = nan_idxs | jnp.any(jnp.isnan(flat), axis=1)
    return nan_idxs


class MultioutputWrapper(WrapperMetric):
    """Compute one metric per output dimension for metrics lacking multioutput support.

    ``compute`` stacks the per-output results into shape ``(num_outputs, ...)``.
    ``remove_nans`` drops rows that contain NaN in any input (per output, host-side —
    dynamic shapes keep this wrapper on the eager path).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import MultioutputWrapper
        >>> from torchmetrics_tpu.regression import R2Score
        >>> target = jnp.array([[0.5, 1.0], [-1.0, 1.0], [7.0, -6.0]])
        >>> preds = jnp.array([[0.0, 2.0], [-1.0, 2.0], [8.0, -5.0]])
        >>> r2score = MultioutputWrapper(R2Score(), 2)
        >>> r2score(preds, target).round(4)
        Array([0.9654    , 0.90819997], dtype=float32)
    """

    is_differentiable = False

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
    ) -> None:
        super().__init__()
        self.metrics = [deepcopy(base_metric) for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs

    def _get_args_kwargs_by_output(self, *args: Array, **kwargs: Array) -> List[Tuple[list, dict]]:
        """Slice args/kwargs per output (and optionally strip NaN rows)."""
        args_kwargs_by_output = []
        for i in range(len(self.metrics)):
            def pick(a, i=i):
                return jnp.take(a, jnp.asarray([i]), axis=self.output_dim)

            selected_args = list(apply_to_arrays(args, pick))
            selected_kwargs = apply_to_arrays(kwargs, pick)
            if self.remove_nans:
                all_arrays = [a for a in selected_args if isinstance(a, jax.Array)] + [
                    v for v in selected_kwargs.values() if isinstance(v, jax.Array)
                ]
                nan_idxs = np.asarray(_get_nan_indices(*all_arrays))
                keep = ~nan_idxs
                selected_args = [a[keep] if isinstance(a, jax.Array) else a for a in selected_args]
                selected_kwargs = {
                    k: (v[keep] if isinstance(v, jax.Array) else v) for k, v in selected_kwargs.items()
                }
            if self.squeeze_outputs:
                dim = self.output_dim

                def squeeze(a, dim=dim):
                    return jnp.squeeze(a, axis=dim)

                selected_args = [squeeze(a) if isinstance(a, jax.Array) else a for a in selected_args]
                selected_kwargs = {
                    k: (squeeze(v) if isinstance(v, jax.Array) else v) for k, v in selected_kwargs.items()
                }
            args_kwargs_by_output.append((selected_args, selected_kwargs))
        return args_kwargs_by_output

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update each per-output metric with its slice."""
        reshaped = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped):
            metric.update(*selected_args, **selected_kwargs)

    def compute(self) -> Array:
        """Stack per-output results: shape ``(num_outputs, ...)``."""
        return jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], axis=0)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Per-output forward values, stacked."""
        reshaped = self._get_args_kwargs_by_output(*args, **kwargs)
        results = [
            metric(*selected_args, **selected_kwargs)
            for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped)
        ]
        if any(r is None for r in results):
            return None
        return jnp.stack([jnp.asarray(r) for r in results], 0)

    def reset(self) -> None:
        """Reset all per-output metrics."""
        for m in self.metrics:
            m.reset()
        super().reset()
