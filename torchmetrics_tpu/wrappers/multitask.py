"""Multitask wrapper: different metrics for different tasks, one call.

Parity: reference ``src/torchmetrics/wrappers/multitask.py``.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, Iterable, Optional, Tuple, Union

import jax

from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric

Array = jax.Array


class MultitaskWrapper(WrapperMetric):
    """Route per-task preds/targets dicts to per-task metrics.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import MultitaskWrapper
        >>> from torchmetrics_tpu.regression import MeanSquaredError
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> metrics = MultitaskWrapper({
        ...     "Classification": BinaryAccuracy(),
        ...     "Regression": MeanSquaredError(),
        ... })
        >>> metrics.update(
        ...     {"Classification": jnp.array([0, 0, 1]), "Regression": jnp.array([3.0, 5.0, 2.5])},
        ...     {"Classification": jnp.array([0, 1, 0]), "Regression": jnp.array([2.5, 5.0, 4.0])},
        ... )
        >>> sorted(metrics.compute())
        ['Classification', 'Regression']
    """

    is_differentiable = False

    def __init__(
        self,
        task_metrics: Dict[str, Union[Metric, MetricCollection]],
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
    ) -> None:
        self._check_task_metrics_type(task_metrics)
        super().__init__()
        self.task_metrics = dict(task_metrics)
        self._prefix = prefix or ""
        self._postfix = postfix or ""

    @staticmethod
    def _check_task_metrics_type(task_metrics: Dict[str, Any]) -> None:
        if not isinstance(task_metrics, dict):
            raise TypeError(f"Expected argument `task_metrics` to be a dict. Found task_metrics = {task_metrics}")
        for metric in task_metrics.values():
            if not isinstance(metric, (Metric, MetricCollection)):
                raise TypeError(
                    "Expected each task's metric to be a Metric or a MetricCollection. "
                    f"Found a metric of type {type(metric)}"
                )

    def items(self, flatten: bool = True) -> Iterable[Tuple[str, Any]]:
        """(task_name, metric) pairs; collections are flattened when ``flatten``."""
        for task_name, metric in self.task_metrics.items():
            if flatten and isinstance(metric, MetricCollection):
                for sub_name, sub_metric in metric.items():
                    yield f"{task_name}_{sub_name}", sub_metric
            else:
                yield task_name, metric

    def keys(self, flatten: bool = True) -> Iterable[str]:
        """Task (or flattened sub-metric) names."""
        for name, _ in self.items(flatten=flatten):
            yield name

    def values(self, flatten: bool = True) -> Iterable[Any]:
        """Metrics (flattened out of collections when ``flatten``)."""
        for _, metric in self.items(flatten=flatten):
            yield metric

    def _check_keys(self, task_preds: Dict[str, Any], task_targets: Dict[str, Any]) -> None:
        if not (self.task_metrics.keys() == task_preds.keys() == task_targets.keys()):
            raise ValueError(
                "Expected arguments `task_preds` and `task_targets` to have the same keys as the wrapped"
                f" `task_metrics`. Found task_preds.keys() = {task_preds.keys()},"
                f" task_targets.keys() = {task_targets.keys()}"
                f" and self.task_metrics.keys() = {self.task_metrics.keys()}"
            )

    def update(self, task_preds: Dict[str, Any], task_targets: Dict[str, Any]) -> None:
        """Update each task's metric with its pred/target."""
        self._check_keys(task_preds, task_targets)
        for task_name, metric in self.task_metrics.items():
            metric.update(task_preds[task_name], task_targets[task_name])

    def compute(self) -> Dict[str, Any]:
        """Per-task results dict."""
        return {self._set_name(name): metric.compute() for name, metric in self.task_metrics.items()}

    def forward(self, task_preds: Dict[str, Any], task_targets: Dict[str, Any]) -> Dict[str, Any]:
        """Per-task batch values, accumulating global state."""
        self._check_keys(task_preds, task_targets)
        return {
            self._set_name(name): metric(task_preds[name], task_targets[name])
            for name, metric in self.task_metrics.items()
        }

    def _set_name(self, base: str) -> str:
        return f"{self._prefix}{base}{self._postfix}"

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MultitaskWrapper":
        """Deep copy, optionally overriding prefix/postfix."""
        mt = deepcopy(self)
        if prefix is not None:
            mt._prefix = prefix
        if postfix is not None:
            mt._postfix = postfix
        return mt

    def reset(self) -> None:
        """Reset all task metrics."""
        for metric in self.task_metrics.values():
            metric.reset()
        super().reset()
