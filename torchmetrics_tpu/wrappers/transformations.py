"""Input-transforming wrappers.

Parity: reference ``src/torchmetrics/wrappers/transformations.py``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric

Array = jax.Array


class MetricInputTransformer(WrapperMetric):
    """Base class: transform inputs, then forward everything to the wrapped metric."""

    def __init__(self, wrapped_metric: Union[Metric, MetricCollection], **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(wrapped_metric, (Metric, MetricCollection)):
            raise TypeError(
                f"Expected wrapped metric to be an instance of `Metric` or `MetricCollection`"
                f" but received {wrapped_metric}"
            )
        self.wrapped_metric = wrapped_metric

    def transform_pred(self, pred: Array) -> Array:
        """Transformation applied to predictions (identity by default)."""
        return pred

    def transform_target(self, target: Array) -> Array:
        """Transformation applied to targets (identity by default)."""
        return target

    def _wrap_transform(self, *args: Array) -> Tuple[Array, ...]:
        if len(args) == 1:
            return (self.transform_pred(args[0]),)
        if len(args) == 2:
            return self.transform_pred(args[0]), self.transform_target(args[1])
        return self.transform_pred(args[0]), self.transform_target(args[1]), *args[2:]

    def update(self, *args: Array, **kwargs: Any) -> None:
        """Transform, then update the wrapped metric."""
        args = self._wrap_transform(*args)
        self.wrapped_metric.update(*args, **kwargs)

    def compute(self) -> Any:
        """Compute the wrapped metric."""
        return self.wrapped_metric.compute()

    def forward(self, *args: Array, **kwargs: Any) -> Any:
        """Transform, then forward the wrapped metric."""
        args = self._wrap_transform(*args)
        return self.wrapped_metric.forward(*args, **kwargs)

    def reset(self) -> None:
        """Reset the wrapped metric (and this wrapper's compute cache)."""
        super().reset()
        self.wrapped_metric.reset()


class LambdaInputTransformer(MetricInputTransformer):
    """Transform inputs with user-provided functions.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import LambdaInputTransformer
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> preds = jnp.array([0.9, 0.2])
        >>> target = jnp.array([0, 1])
        >>> metric = LambdaInputTransformer(BinaryAccuracy(), lambda p: 1 - p)
        >>> metric.update(preds, target)
        >>> float(metric.compute())
        1.0
    """

    def __init__(
        self,
        wrapped_metric: Union[Metric, MetricCollection],
        transform_pred: Optional[Callable[[Array], Array]] = None,
        transform_target: Optional[Callable[[Array], Array]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(wrapped_metric, **kwargs)
        if transform_pred is not None:
            if not callable(transform_pred):
                raise TypeError(f"Expected `transform_pred` to be a Callable but received {transform_pred}")
            self.transform_pred = transform_pred  # type: ignore[method-assign]
        if transform_target is not None:
            if not callable(transform_target):
                raise TypeError(f"Expected `transform_target` to be a Callable but received {transform_target}")
            self.transform_target = transform_target  # type: ignore[method-assign]


class BinaryTargetTransformer(MetricInputTransformer):
    """Binarize continuous targets at ``threshold`` before updating the metric.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import BinaryTargetTransformer
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> metric = BinaryTargetTransformer(BinaryAccuracy(), threshold=0.5)
        >>> metric.update(jnp.array([0.9, 0.2]), jnp.array([0.8, 0.3]))
        >>> float(metric.compute())
        1.0
    """

    def __init__(self, wrapped_metric: Union[Metric, MetricCollection], threshold: float = 0, **kwargs: Any) -> None:
        super().__init__(wrapped_metric, **kwargs)
        if not isinstance(threshold, (int, float)):
            raise TypeError(f"Expected `threshold` to be of type `int` or `float` but received `{threshold}`")
        self.threshold = threshold

    def transform_target(self, target: Array) -> Array:
        """Cast targets to {0, 1} via ``target > threshold`` (dtype preserved)."""
        return (target > self.threshold).astype(target.dtype)
