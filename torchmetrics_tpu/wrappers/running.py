"""Running-window wrapper.

Parity: reference ``src/torchmetrics/wrappers/running.py:83-115`` (window-size ring of
duplicated base-metric states) and the ``RunningMean``/``RunningSum`` aggregators
(reference ``aggregation.py:616-727``).
"""

from __future__ import annotations

from typing import Any, Optional, Union

import jax

from torchmetrics_tpu.aggregation import MeanMetric, SumMetric
from torchmetrics_tpu.core.metric import Metric, _squeeze_if_scalar
from torchmetrics_tpu.wrappers.abstract import WrapperMetric

Array = jax.Array


class Running(WrapperMetric):
    """Compute a metric over a running window of the last ``window`` batches.

    Keeps ``window`` copies of the base metric's state (a ring buffer of state
    pytrees); ``compute`` folds them with the metric's pairwise merge. ``forward``
    still returns the current-batch value; call ``compute`` for the running value.
    Only works with ``full_state_update=False`` metrics.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import Running
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> metric = Running(SumMetric(), window=3)
        >>> for i in range(6):
        ...     _ = metric(jnp.array([float(i)]))
        >>> float(metric.compute())  # 3 + 4 + 5
        12.0
    """

    def __init__(self, base_metric: Metric, window: int = 5) -> None:
        super().__init__()
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected argument `metric` to be an instance of `Metric` but got {base_metric}"
            )
        if not (isinstance(window, int) and window > 0):
            raise ValueError(f"Expected argument `window` to be a positive integer but got {window}")
        if base_metric.full_state_update is not False:
            raise ValueError(
                f"Expected attribute `full_state_update` set to `False` but got {base_metric.full_state_update}"
            )
        self.base_metric = base_metric
        self.window = window
        self._num_vals_seen = 0

        for key in base_metric._defaults:
            for i in range(window):
                self.add_state(
                    name=f"{key}_{i}",
                    default=base_metric._defaults[key],
                    dist_reduce_fx=base_metric._reductions[key],
                )

    def _store_slot(self) -> None:
        slot = self._num_vals_seen % self.window
        for key in self.base_metric._defaults:
            self._state_values[f"{key}_{slot}"] = self.base_metric._state_values[key]
        self.base_metric.reset()
        self._num_vals_seen += 1

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update the base metric, capture its state into the ring, reset it."""
        self.base_metric.update(*args, **kwargs)
        self._store_slot()

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Forward to the base metric (current-batch value), then capture state."""
        res = self.base_metric.forward(*args, **kwargs)
        self._store_slot()
        self._computed = None
        self._update_count += 1
        return res

    def compute(self) -> Any:
        """Fold the window's state ring through the metric's pairwise merge."""
        base = self.base_metric
        state = base._fresh_state()
        count = 0
        for i in range(self.window):
            slot = {key: self._state_values[f"{key}_{i}"] for key in base._defaults}
            state = base._reduce_states(state, slot, count)
            count += 1
        return _squeeze_if_scalar(base.pure_compute(state))

    def reset(self) -> None:
        """Reset the ring and the base metric."""
        super().reset()
        self.base_metric.reset()
        self._num_vals_seen = 0


class RunningMean(Running):
    """Mean over a running window of values.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.aggregation import RunningMean
        >>> metric = RunningMean(window=3)
        >>> for i in range(6):
        ...     _ = metric(jnp.array([float(i)]))
        >>> float(metric.compute())  # mean(3, 4, 5)
        4.0
    """

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(base_metric=MeanMetric(nan_strategy=nan_strategy, **kwargs), window=window)


class RunningSum(Running):
    """Sum over a running window of values.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.aggregation import RunningSum
        >>> metric = RunningSum(window=3)
        >>> for i in range(6):
        ...     _ = metric(jnp.array([float(i)]))
        >>> float(metric.compute())
        12.0
    """

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(base_metric=SumMetric(nan_strategy=nan_strategy, **kwargs), window=window)
