"""Min/max tracking wrapper.

Parity: reference ``src/torchmetrics/wrappers/minmax.py``.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric

Array = jax.Array


class MinMaxMetric(WrapperMetric):
    """Track the min and max of a scalar metric across an experiment.

    ``compute`` returns ``{"raw": current, "min": lowest seen, "max": highest seen}``;
    the extrema update on every ``compute`` call.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import MinMaxMetric
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> metric = MinMaxMetric(BinaryAccuracy())
        >>> _ = metric(jnp.array([1.0, 1.0]), jnp.array([0, 1]))
        >>> sorted(metric.compute())
        ['max', 'min', 'raw']
    """

    full_state_update = True

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        # registered states: survive state_dict round-trips and set_dtype/to_device
        self.add_state("min_val", jnp.asarray(float("inf")), dist_reduce_fx="min")
        self.add_state("max_val", jnp.asarray(float("-inf")), dist_reduce_fx="max")

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update the wrapped metric."""
        self._base_metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        """Current value plus running min/max (extrema update here)."""
        val = self._base_metric.compute()
        if not self._is_suitable_val(val):
            raise RuntimeError(f"Returned value from base metric should be a float or scalar tensor, but got {val}.")
        val = jnp.asarray(val)
        self.max_val = jnp.maximum(self.max_val, val)
        self.min_val = jnp.minimum(self.min_val, val)
        return {"raw": val, "max": self.max_val, "min": self.min_val}

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Array]:
        """Batch-level value dict; extrema track batch values seen through forward.

        The wrapped metric's own ``forward`` runs, so global accumulation is
        preserved (the reference resets the child through the full-state path and
        keeps only the last batch).
        """
        val = jnp.asarray(self._base_metric(*args, **kwargs))
        if not self._is_suitable_val(val):
            raise RuntimeError(f"Returned value from base metric should be a float or scalar tensor, but got {val}.")
        self.max_val = jnp.maximum(self.max_val, val)
        self.min_val = jnp.minimum(self.min_val, val)
        self._computed = None
        self._update_count += 1
        return {"raw": val, "max": self.max_val, "min": self.min_val}

    def reset(self) -> None:
        """Reset extrema (state defaults) and the wrapped metric."""
        super().reset()
        self._base_metric.reset()

    @staticmethod
    def _is_suitable_val(val: Any) -> bool:
        if isinstance(val, (int, float)):
            return True
        if isinstance(val, (jax.Array, np.ndarray)):
            return np.asarray(val).size == 1
        return False
