"""Bootstrap resampling wrapper.

Parity: reference ``src/torchmetrics/wrappers/bootstrapping.py:55-219``.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric, apply_to_arrays

Array = jax.Array


def _bootstrap_sampler(size: int, sampling_strategy: str = "poisson") -> np.ndarray:
    """Index vector that resamples ``size`` rows with replacement.

    Sampling runs on host (numpy) — it only produces gather indices; the actual
    gathers execute on device. ``'poisson'`` draws per-sample inclusion counts from
    Poisson(1) (approximates the bootstrap for large n); ``'multinomial'`` draws
    uniformly with replacement.
    """
    if sampling_strategy == "poisson":
        n = np.random.poisson(1.0, size=size)
        return np.repeat(np.arange(size), n)
    if sampling_strategy == "multinomial":
        return np.random.randint(0, size, size=size)
    raise ValueError("Unknown sampling strategy")


class BootStrapper(WrapperMetric):
    r"""Turn any metric into a bootstrapped estimate with confidence statistics.

    Keeps ``num_bootstraps`` copies of the base metric; every ``update``/``forward``
    resamples the batch (with replacement) along dim 0 independently per copy.

    Args:
        base_metric: the metric to bootstrap.
        num_bootstraps: number of resampled copies.
        mean: include the bootstrap mean in the output dict.
        std: include the bootstrap standard deviation.
        quantile: optionally include this quantile (float or array of floats).
        raw: include all bootstrap values.
        sampling_strategy: ``'poisson'`` or ``'multinomial'``.

    Example:
        >>> import numpy as np, jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import BootStrapper
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy
        >>> np.random.seed(123)
        >>> bootstrap = BootStrapper(MulticlassAccuracy(num_classes=5, average='micro'), num_bootstraps=20)
        >>> bootstrap.update(jnp.asarray(np.random.randint(5, size=20)), jnp.asarray(np.random.randint(5, size=20)))
        >>> sorted(bootstrap.compute())
        ['mean', 'std']
    """

    full_state_update: Optional[bool] = True

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Array]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of Metric but received {base_metric}"
            )
        self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]
        self.num_bootstraps = num_bootstraps
        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw

        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling}"
                f" but received {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy

    def _input_size(self, *args: Any, **kwargs: Any) -> int:
        sizes: list = []
        apply_to_arrays(args, lambda a: sizes.append(a.shape[0]) or a)
        if not sizes:
            apply_to_arrays(kwargs, lambda a: sizes.append(a.shape[0]) or a)
        if not sizes:
            raise ValueError("None of the input contained tensors, so could not determine the sampling size")
        return sizes[0]

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Resample the batch along dim 0 for each bootstrap copy and update it."""
        size = self._input_size(*args, **kwargs)
        for idx in range(self.num_bootstraps):
            sample_idx = _bootstrap_sampler(size, sampling_strategy=self.sampling_strategy)
            if sample_idx.size == 0:
                continue
            idx_dev = jnp.asarray(sample_idx)
            new_args = apply_to_arrays(args, lambda a: jnp.take(a, idx_dev, axis=0))
            new_kwargs = apply_to_arrays(kwargs, lambda a: jnp.take(a, idx_dev, axis=0))
            self.metrics[idx].update(*new_args, **new_kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Array]:
        """Accumulate (resampled) and return the batch-level bootstrap stats.

        Unlike the reference (which routes through the full-state forward and resets
        the copies, keeping only the last batch), each copy's own ``forward`` runs, so
        global accumulation is preserved while batch-level stats are returned.
        """
        size = self._input_size(*args, **kwargs)
        vals = []
        for idx in range(self.num_bootstraps):
            sample_idx = _bootstrap_sampler(size, sampling_strategy=self.sampling_strategy)
            if sample_idx.size == 0:
                continue
            idx_dev = jnp.asarray(sample_idx)
            new_args = apply_to_arrays(args, lambda a: jnp.take(a, idx_dev, axis=0))
            new_kwargs = apply_to_arrays(kwargs, lambda a: jnp.take(a, idx_dev, axis=0))
            vals.append(jnp.asarray(self.metrics[idx](*new_args, **new_kwargs)))
        self._computed = None
        self._update_count += 1
        if not vals:
            # every poisson resample came out empty (likely batch size 1): there is no
            # defined batch-level statistic — report NaNs rather than crashing
            nan = jnp.asarray(float("nan"))
            out = {}
            if self.mean:
                out["mean"] = nan
            if self.std:
                out["std"] = nan
            if self.quantile is not None:
                out["quantile"] = nan
            if self.raw:
                out["raw"] = jnp.zeros((0,))
            return out
        return self._stats_dict(jnp.stack(vals, axis=0))

    def _stats_dict(self, computed_vals: Array) -> Dict[str, Array]:
        output_dict: Dict[str, Array] = {}
        if self.mean:
            output_dict["mean"] = computed_vals.mean(axis=0)
        if self.std:
            output_dict["std"] = computed_vals.std(axis=0, ddof=1)
        if self.quantile is not None:
            output_dict["quantile"] = jnp.quantile(computed_vals, self.quantile, axis=0)
        if self.raw:
            output_dict["raw"] = computed_vals
        return output_dict

    def compute(self) -> Dict[str, Array]:
        """Bootstrap statistics dict with keys among ``mean``/``std``/``quantile``/``raw``."""
        return self._stats_dict(jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], axis=0))

    def reset(self) -> None:
        """Reset all bootstrap copies."""
        for m in self.metrics:
            m.reset()
        super().reset()
