"""Metric history tracker across steps/epochs.

Parity: reference ``src/torchmetrics/wrappers/tracker.py:31-311``.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class MetricTracker:
    """Track a metric (or collection) over multiple increments (e.g. epochs).

    Call :meth:`increment` at the start of each tracked period; ``update``/``forward``/
    ``compute`` hit the latest copy. :meth:`compute_all` stacks every period's result;
    :meth:`best_metric` returns the best value (and optionally which step).

    Example:
        >>> import numpy as np, jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import MetricTracker
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy
        >>> tracker = MetricTracker(MulticlassAccuracy(num_classes=10))
        >>> rng = np.random.RandomState(0)
        >>> for epoch in range(3):
        ...     tracker.increment()
        ...     tracker.update(jnp.asarray(rng.rand(100, 10)), jnp.asarray(rng.randint(10, size=100)))
        >>> tracker.compute_all().shape
        (3,)
    """

    def __init__(self, metric: Union[Metric, MetricCollection], maximize: Union[bool, List[bool], None] = True) -> None:
        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(
                f"Metric arg need to be an instance of a `Metric` or `MetricCollection` but got {metric}"
            )
        self._base_metric = metric
        if maximize is not None:
            if not isinstance(maximize, (bool, list)):
                raise ValueError("Argument `maximize` should either be a single bool or list of bool")
            if isinstance(maximize, list) and isinstance(metric, MetricCollection) and len(maximize) != len(metric):
                raise ValueError("The len of argument `maximize` should match the length of the metric collection")
            if isinstance(metric, Metric) and not isinstance(maximize, bool):
                raise ValueError("Argument `maximize` should be a single bool when `metric` is a single Metric")
        self.maximize = maximize
        self._increments: List[Union[Metric, MetricCollection]] = []
        self._increment_called = False

    @property
    def n_steps(self) -> int:
        """Number of times the tracker has been incremented."""
        return len(self._increments)

    def __len__(self) -> int:
        return len(self._increments)

    def __getitem__(self, idx: int) -> Union[Metric, MetricCollection]:
        return self._increments[idx]

    def increment(self) -> None:
        """Start tracking a new (fresh) copy of the base metric."""
        self._increment_called = True
        self._increments.append(deepcopy(self._base_metric))

    # list-management parity with the reference's ModuleList base
    def append(self, metric: Union[Metric, MetricCollection]) -> "MetricTracker":
        """Append an externally constructed increment (reference ModuleList API)."""
        self._increments.append(metric)
        return self

    def extend(self, metrics: List[Union[Metric, MetricCollection]]) -> "MetricTracker":
        """Extend with externally constructed increments (reference ModuleList API)."""
        self._increments.extend(metrics)
        return self

    def insert(self, index: int, metric: Union[Metric, MetricCollection]) -> "MetricTracker":
        """Insert an externally constructed increment (reference ModuleList API)."""
        self._increments.insert(index, metric)
        return self

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Forward on the current increment."""
        self._check_for_increment("forward")
        return self._increments[-1](*args, **kwargs)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update the current increment."""
        self._check_for_increment("update")
        self._increments[-1].update(*args, **kwargs)

    def compute(self) -> Any:
        """Compute the current increment."""
        self._check_for_increment("compute")
        return self._increments[-1].compute()

    def compute_all(self) -> Any:
        """Stack all increments' results (dict of stacks for collections)."""
        self._check_for_increment("compute_all")
        res = [m.compute() for m in self._increments]
        try:
            if isinstance(res[0], dict):
                keys = res[0].keys()
                return {k: jnp.stack([jnp.asarray(r[k]) for r in res], axis=0) for k in keys}
            if isinstance(res[0], (list, tuple)):
                return jnp.stack([jnp.stack([jnp.asarray(v) for v in r], axis=0) for r in res], 0)
            return jnp.stack([jnp.asarray(r) for r in res], axis=0)
        except (TypeError, ValueError):
            return res

    def plot(self, val: Any = None, ax: Any = None):
        """Plot one or all tracked values (reference ``wrappers/tracker.py:273-311``).

        Args:
            val: result(s) to plot; defaults to :meth:`compute_all` (the full history).
            ax: existing matplotlib axis to draw into.
        """
        from torchmetrics_tpu.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute_all()
        if isinstance(val, Array) and val.ndim >= 1:
            # the stacked history plots as a time series (one entry per increment)
            val = [v for v in val]
        return plot_single_or_multi_val(val, ax=ax, name=type(self._base_metric).__name__)

    def reset(self) -> None:
        """Reset the current increment."""
        if self._increments:
            self._increments[-1].reset()

    def reset_all(self) -> None:
        """Reset every increment."""
        for m in self._increments:
            m.reset()

    def best_metric(
        self, return_step: bool = False
    ) -> Union[Any, Tuple[Any, Any]]:
        """Best value across increments (per key for collections).

        With ``maximize=None`` or on stacking failure returns ``None`` (and warns).
        """
        if self.maximize is None:
            rank_zero_warn(
                "No `maximize` argument was provided, so the best metric cannot be determined. Returning None.",
                UserWarning,
            )
            if isinstance(self._base_metric, Metric):
                return (None, None) if return_step else None
            keys = list(self.compute_all())
            none_d = {k: None for k in keys}
            return (none_d, dict(none_d)) if return_step else none_d
        if isinstance(self._base_metric, Metric):
            fn = np.argmax if self.maximize else np.argmin
            try:
                vals = np.asarray(self.compute_all())
                idx = int(fn(vals, 0))
                if return_step:
                    return float(vals[idx]), idx
                return float(vals[idx])
            except (ValueError, TypeError) as error:
                rank_zero_warn(
                    f"Encountered the following error when trying to get the best metric: {error}"
                    "this is probably due to the 'compute' method of the metric returning something "
                    "that is not a single tensor.",
                    UserWarning,
                )
                if return_step:
                    return None, None
                return None
        else:
            res = self.compute_all()
            maximize = self.maximize if isinstance(self.maximize, list) else len(res) * [self.maximize]
            value: Dict[str, Optional[float]] = {}
            idx: Dict[str, Optional[int]] = {}
            for i, (k, v) in enumerate(res.items()):
                try:
                    fn = np.argmax if maximize[i] else np.argmin
                    vals = np.asarray(v)
                    best = int(fn(vals, 0))
                    value[k], idx[k] = float(vals[best]), best
                except (ValueError, TypeError) as error:
                    rank_zero_warn(
                        f"Encountered the following error when trying to get the best metric for metric {k}:"
                        f"{error} this is probably due to the 'compute' method of the metric returning something "
                        "that is not a single tensor.",
                        UserWarning,
                    )
                    value[k], idx[k] = None, None
            if return_step:
                return value, idx
            return value

    def _check_for_increment(self, method: str) -> None:
        if not self._increment_called:
            raise ValueError(f"`{method}` cannot be called before `.increment()` has been called.")

    def _memory_children(self) -> List[Tuple[str, Union[Metric, MetricCollection]]]:
        """Base metric + every tracked increment, for state-memory accounting.

        Each :meth:`increment` deep-copies the base metric — a tracker run over
        N epochs holds N+1 full state copies. The accounting must see them
        all, or a leaking tracker reads as a constant-size metric.
        """
        children: List[Tuple[str, Union[Metric, MetricCollection]]] = [
            ("base_metric", self._base_metric)
        ]
        children.extend((f"increment[{i}]", m) for i, m in enumerate(self._increments))
        return children

    def memory_footprint(self) -> Dict[str, Any]:
        """Recursive state-memory footprint of the tracker (see ``obs.memory``)."""
        from torchmetrics_tpu.obs import memory as _memory

        return _memory.footprint(self)
