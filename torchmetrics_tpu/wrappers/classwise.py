"""Classwise output-splitting wrapper.

Parity: reference ``src/torchmetrics/wrappers/classwise.py``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric

Array = jax.Array


class ClasswiseWrapper(WrapperMetric):
    """Split a per-class metric result into a ``{name: scalar}`` dict.

    Args:
        metric: base metric returning a per-class vector (e.g. ``average=None``).
        labels: optional class names (defaults to indices).
        prefix: key prefix (default ``<metricname>_``).
        postfix: key postfix.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import ClasswiseWrapper
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy
        >>> metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None))
        >>> preds = jnp.array([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1]])
        >>> target = jnp.array([0, 1])
        >>> sorted(metric(preds, target))
        ['multiclassaccuracy_0', 'multiclassaccuracy_1', 'multiclassaccuracy_2']
    """

    def __init__(
        self,
        metric: Metric,
        labels: Optional[List[str]] = None,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
    ) -> None:
        super().__init__()
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected argument `metric` to be an instance of `Metric` but got {metric}")
        self.metric = metric
        if labels is not None and not (isinstance(labels, list) and all(isinstance(lab, str) for lab in labels)):
            raise ValueError(f"Expected argument `labels` to either be `None` or a list of strings but got {labels}")
        self.labels = labels
        if prefix is not None and not isinstance(prefix, str):
            raise ValueError(f"Expected argument `prefix` to either be `None` or a string but got {prefix}")
        self._prefix = prefix
        if postfix is not None and not isinstance(postfix, str):
            raise ValueError(f"Expected argument `postfix` to either be `None` or a string but got {postfix}")
        self._postfix = postfix
        self._update_count = 1

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self.metric._filter_kwargs(**kwargs)

    def _convert_output(self, x: Array) -> Dict[str, Any]:
        if not self._prefix and not self._postfix:
            prefix = f"{type(self.metric).__name__.lower()}_"
            postfix = ""
        else:
            prefix = self._prefix or ""
            postfix = self._postfix or ""
        if self.labels is None:
            return {f"{prefix}{i}{postfix}": val for i, val in enumerate(x)}
        return {f"{prefix}{lab}{postfix}": val for lab, val in zip(self.labels, x)}

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Batch value as a classwise dict, accumulating global state."""
        return self._convert_output(self.metric(*args, **kwargs))

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update the wrapped metric."""
        self.metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        """Classwise dict of the wrapped metric's result."""
        return self._convert_output(self.metric.compute())

    def reset(self) -> None:
        """Reset the wrapped metric (and this wrapper's compute cache)."""
        super().reset()
        self.metric.reset()
