"""Wrapper-metric base class.

Parity: reference ``src/torchmetrics/wrappers/abstract.py:19-42`` (``WrapperMetric``
disables its own sync/wrapping; the wrapped metric handles all of it).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.core.metric import Metric

Array = jax.Array


def apply_to_arrays(data: Any, fn: Callable[[Array], Any]) -> Any:
    """Apply ``fn`` to every jax array in a nested tuple/list/dict collection."""
    if isinstance(data, (jax.Array, jnp.ndarray)):
        return fn(data)
    if isinstance(data, dict):
        return {k: apply_to_arrays(v, fn) for k, v in data.items()}
    if isinstance(data, (list, tuple)):
        return type(data)(apply_to_arrays(v, fn) for v in data)
    return data


class WrapperMetric(Metric):
    """Base class for metrics that wrap another metric and forward all calls to it.

    All synchronization is the wrapped metric's job: this class's own sync is a no-op,
    and its update never routes through the jit dispatcher (delegated updates mutate
    the child's state, which is not a pure transition of the wrapper's own pytree).
    """

    def __init__(self, **kwargs: Any) -> None:
        kwargs.setdefault("jit_update", False)
        super().__init__(**kwargs)

    def _sync_dist(self, dist_sync_fn: Optional[Callable] = None) -> None:
        """No-op: the wrapped metric syncs itself."""

    @staticmethod
    def _is_memory_child(value: Any) -> bool:
        # Metric subclasses AND anything exposing the accounting hook itself —
        # MultitaskWrapper explicitly allows MetricCollection task values,
        # which is not a Metric but must not vanish from the rollup
        return isinstance(value, Metric) or (
            not isinstance(value, type) and callable(getattr(value, "_memory_children", None))
        )

    def _memory_children(self) -> list:
        """Nested metrics this wrapper holds, for state-memory accounting.

        Wrappers keep their base metric(s) in instance attributes under
        several shapes — a single metric (``Running.base_metric``,
        ``ClasswiseWrapper.metric``), a replica list (``BootStrapper.metrics``,
        ``MultioutputWrapper.metrics``) or a task dict of metrics or
        collections (``MultitaskWrapper.task_metrics``). One generic scan
        covers them all, so every wrapper's hidden copies are billed without
        per-class hooks.
        """
        children = []
        for key, value in self.__dict__.items():
            if self._is_memory_child(value):
                children.append((key, value))
            elif isinstance(value, (list, tuple)):
                children.extend(
                    (f"{key}[{i}]", v) for i, v in enumerate(value) if self._is_memory_child(v)
                )
            elif isinstance(value, dict):
                children.extend(
                    (f"{key}[{k}]", v) for k, v in value.items() if self._is_memory_child(v)
                )
        return children

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Each wrapper defines its own forward."""
        raise NotImplementedError
