"""Feature-sharing collection for model-based metrics.

Parity: reference ``src/torchmetrics/wrappers/feature_share.py:26-127``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Union

from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.utils.prints import rank_zero_warn


class NetworkCache:
    """Memoizing proxy around a feature-extractor callable.

    Different metrics in a :class:`FeatureShare` call the same backbone on the same
    batch; caching input→output pairs means the expensive forward runs once per batch
    instead of once per metric. Keys are the object ids of the input arrays; each
    cache entry keeps strong references to its key objects, so an id can never be
    recycled by a new array while its entry is alive (jax arrays are immutable, so a
    live id uniquely identifies its contents).
    """

    def __init__(self, network: Any, max_size: int = 100) -> None:
        self.max_size = max_size
        self.network = network
        # key -> (args, kwargs, output); the stored inputs pin the ids in the key
        self._cache: "dict[tuple, tuple]" = {}

    def _key(self, args: tuple, kwargs: dict) -> tuple:
        return tuple(id(a) for a in args) + tuple((k, id(v)) for k, v in sorted(kwargs.items()))

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        key = self._key(args, kwargs)
        if key in self._cache:
            return self._cache[key][2]
        out = self.network(*args, **kwargs)
        if len(self._cache) >= self.max_size:
            self._cache.pop(next(iter(self._cache)))
        self._cache[key] = (args, kwargs, out)
        return out

    def __getattr__(self, name: str) -> Any:
        return getattr(self.__dict__["network"], name)


class FeatureShare(MetricCollection):
    """MetricCollection that shares one cached feature extractor across its metrics.

    Each member metric must expose a ``feature_network`` attribute naming the
    attribute that holds its backbone; the first member's backbone (wrapped in a
    :class:`NetworkCache`) replaces every member's.
    """

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        max_cache_size: Optional[int] = None,
    ) -> None:
        # compute groups off: sharing happens at the network level instead
        super().__init__(metrics=metrics, compute_groups=False)

        if max_cache_size is None:
            max_cache_size = len(self)
        if not isinstance(max_cache_size, int):
            raise TypeError(f"max_cache_size should be an integer, but got {max_cache_size}")

        try:
            first_net = next(iter(self.values()))
            network_to_share = getattr(first_net, first_net.feature_network)
        except AttributeError as err:
            raise AttributeError(
                "Tried to extract the network to share from the first metric, but it did not have a"
                " `feature_network` attribute. Please make sure that the metric has an attribute with that"
                " name, else it cannot be shared."
            ) from err
        cached_net = NetworkCache(network_to_share, max_size=max_cache_size)

        for metric_name, metric in self.items():
            if not hasattr(metric, "feature_network"):
                raise AttributeError(
                    "Tried to set the cached network to all metrics, but one of the metrics did not have a"
                    " `feature_network` attribute. Please make sure that all metrics have a attribute with"
                    f" that name, else it cannot be shared. Failed on metric {metric_name}."
                )
            if getattr(metric, metric.feature_network) is not network_to_share:
                rank_zero_warn(
                    f"The network to share between the metrics is not the same for all metrics."
                    f" Metric {metric_name} has a different network than the first metric."
                    " This may lead to unexpected behavior.",
                    UserWarning,
                )
            setattr(metric, metric.feature_network, cached_net)
