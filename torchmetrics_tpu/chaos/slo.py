"""Declarative SLOs + the judge that turns a chaos replay into pass/fail.

A replay result (:func:`torchmetrics_tpu.chaos.replay.replay`) is a pile of
measurements; an :class:`SLOSpec` says which of them the serving stack
*promises*, and :func:`judge` renders the verdict:

- **update throughput** — batches folded per wall second across every tenant
  session, chaos included (sleeps, faults, replays and scrapes all count
  against it — that is the point).
- **scrape latency p95/p99 per route** — read from the obs server's own
  ``server.request`` histogram via
  :func:`~torchmetrics_tpu.obs.export.histogram_quantile` (bucket-midpoint
  estimates; the driver-side client-observed quantiles ride along in the
  report as corroboration).
- **time-to-fire / time-to-resolve per injected fault** — the wall delta from
  the fault's injection stamp to its watchdog's ``firing`` transition, and
  from ``firing`` to ``resolved``, derived from the alert engine's bounded
  transition history (:meth:`~torchmetrics_tpu.obs.alerts.AlertEngine.fire_resolve_times`).
  A fault whose alert never fired — or never resolved — is an SLO failure
  with that exact detail, not a missing number.
- **peak compiled-variant count under churn** — the cost ledger's
  variants-compiled delta across the run: signature churn that recompiles
  per tenant instead of per bucket shows up here first (the pjit-scaling
  paper's cost, gated).
- **flight-dump correctness** — every poisoned batch the schedule injected
  into a guarded tenant must be *named* (tenant + tenant-local batch index)
  in some flight-recorder dump.
- **fault causality** — every injected NaN batch's **trace id**
  (:mod:`~torchmetrics_tpu.obs.lineage`) must resolve end-to-end: the lineage
  record exists, a guarded tenant's poison shows a quarantine outcome AND a
  flight dump naming its id, and the victim's poison links to the value
  watchdog that fired on its commit — injection → evidence → alert as one
  joined record, not three greps.

:func:`judge` returns a plain report: per-SLO rows (value, threshold, pass,
detail), an overall verdict, and a ``configs`` dict shaped exactly like
``bench.py`` configs — units the regression sentinel
(:mod:`~torchmetrics_tpu.obs.regress`) judges, plus the strict ``slo_pass``
config — so a chaos run lands in ``BENCH_HISTORY.jsonl`` and is gated like
any perf number.

Pure stdlib.
"""

from __future__ import annotations

import math
from dataclasses import asdict, dataclass
from typing import Any, Dict, List, Optional, Tuple

from torchmetrics_tpu.obs.export import histogram_quantile, quantile_bucket

__all__ = [
    "SLOSpec",
    "flash_crowd_slo_spec",
    "format_report",
    "high_tenant_slo_spec",
    "host_crash_slo_spec",
    "hung_host_slo_spec",
    "judge",
    "rolling_deploy_slo_spec",
    "skewed_load_slo_spec",
]


@dataclass
class SLOSpec:
    """The promises a chaos run is judged against (absolute, same-hardware).

    Thresholds default loose enough for an oversubscribed CI host — the
    regression sentinel's noise-aware history gate is the tight screw; these
    are the "is the system even operable" floor. ``None`` disables an SLO
    (reported, never judged).
    """

    min_updates_per_second: Optional[float] = 5.0
    max_scrape_p95_seconds: Optional[float] = 0.75
    max_scrape_p99_seconds: Optional[float] = 1.5
    max_time_to_fire_seconds: Optional[float] = 5.0
    max_time_to_resolve_seconds: Optional[float] = 15.0
    max_compiled_variants: Optional[int] = 160
    require_poisoned_named: bool = True
    # end-to-end batch-lineage causality (obs/lineage.py): every injected NaN
    # batch's trace id must link schedule injection → quarantine/flight dump
    # (guarded tenants) or → value-watchdog firing (the victim) — the
    # grep-and-guess eliminator, judged as one strict boolean
    require_fault_causality: bool = True
    # cross-tenant fused dispatch promises (the multiplexed scenarios):
    # the run must actually have fused across tenants, and every guarded
    # tenant's poisoned batch must be quarantined by exactly its own session
    require_multiplexed: bool = False
    require_quarantine_attributed: bool = False
    # live-session migration promises (the rolling-deploy scenario): every
    # migrated tenant's restored session must compute BIT-IDENTICAL to its
    # unmigrated shadow control, the handoff must be operator-visible
    # (/healthz degraded with the migrating tenant NAMED while in flight),
    # and the whole host handoff must land inside the wall budget
    require_migration_zero_loss: bool = False
    require_migration_visible: bool = False
    max_migration_seconds: Optional[float] = None
    # continuous-checkpointing promises (the host-crash scenario): after an
    # unplanned SIGKILL-semantics death, the replay gap (batches fed but not
    # covered by the last periodic bundle) must be bounded by the cadence,
    # recovery from the newest intact bundle must land inside the wall budget,
    # post-recovery compute must be bit-identical to an unkilled shadow
    # control, and delta bundles must be measurably smaller than full ones
    # on the large-state metric (mean-bytes ratio, bundle-bytes gauge)
    max_replay_gap_batches: Optional[int] = None
    require_crash_zero_loss: bool = False
    max_recovery_seconds: Optional[float] = None
    max_delta_full_ratio: Optional[float] = None
    # hung-host fencing promises (the hung-host scenario): the scrape-driven
    # watchdog must detect the stale lease and complete the failover inside
    # the wall budgets, the zombie's late bundle write must land fenced-out
    # (rejected + counted by the next recovery scan, never selected), every
    # failed-over session must compute bit-identical to a never-hung shadow
    # control (zero double-counting), and the fence must be operator-visible
    # (/healthz degraded naming the fenced tenant + target, /leases carrying
    # the fence ledger)
    max_time_to_detect_seconds: Optional[float] = None
    max_time_to_failover_seconds: Optional[float] = None
    require_zombie_writes_rejected: bool = False
    require_fence_zero_double_count: bool = False
    require_fence_visible: bool = False
    # fleet-telemetry promises (the skewed-load scenario): the imbalance page
    # must fire from fleet samples alone (the declarative imbalance_rule over
    # the fleet.imbalance gauge — nothing is told where the skew is) inside
    # the detection budget; /fleet must serve the per-tenant rate table, the
    # skew block and ranked advisory rebalance hints derived from >= 2 real
    # samples; the mid-run hot-spot shift must re-point the hot host; and a
    # wedged gather must yield a LOUD degraded partial sample (missing hosts
    # named), never a stalled sampler
    max_time_to_detect_imbalance_seconds: Optional[float] = None
    require_fleet_served: bool = False
    require_fleet_shift_tracked: bool = False
    require_fleet_degraded_loud: bool = False
    # placement-control-plane promises (the flash-crowd scenario): the
    # controller must fix the measured skew with real session moves and close
    # its convergence episode inside the budget — including at least one
    # clean move AFTER the mid-run hot-spot shift (re-convergence, the reason
    # the scenario exists); every moved session must compute BIT-IDENTICAL to
    # an unmoved shadow control fed the exact same stream (zero-loss moves);
    # the assignment table must have been reconstructed from the durable
    # state file (the restart path, not a fresh in-memory table); GET
    # /placement must serve the table, move ledger and decision log over real
    # HTTP; and serving throughput under the live controller must hold a
    # floor ratio of the static-placement control arm's, both net of their
    # own measured compile wall (the controller must not COST meaningful
    # throughput; compile churn is capped separately by compiled_variants)
    max_placement_convergence_seconds: Optional[float] = None
    min_placement_moves: Optional[int] = None
    require_placement_zero_loss: bool = False
    require_placement_served: bool = False
    require_placement_durable_restore: bool = False
    require_placement_shift_move: bool = False
    min_placement_throughput_ratio: Optional[float] = None
    # conservation-audit promise (every scenario): the continuous auditor
    # (obs/audit.py) must have balanced every tenant's flow ledger over the
    # whole run — zero violations across admission, fusion, migration, crash
    # recovery and fencing — judged as one strict boolean
    require_accounting_clean: bool = False
    # routes whose scrape latency is judged (the driver may scrape more)
    scrape_routes: Tuple[str, ...] = ("/metrics", "/alerts", "/tenants")

    def asdict(self) -> Dict[str, Any]:
        return asdict(self)


def high_tenant_slo_spec() -> SLOSpec:
    """The SLO spec of the high-tenant multiplexed scenario
    (:func:`~torchmetrics_tpu.chaos.schedule.high_tenant_config` replayed with
    ``ReplayConfig.multiplex=True``).

    The compiled-variant budget is the headline: 64 tenants sharing two batch
    signatures must compile O(width-buckets × signatures) programs — the
    fused-program ladder (7 buckets × 2 signatures), the per-tenant replay /
    victim / hung-path programs and warmup leave comfortable slack under 60,
    where the unmultiplexed same-schedule run compiles ~4–5× more (every
    tenant's own jit cache pays every signature). Poisoned-batch evidence is
    held to BOTH standards: quarantine attribution (exactly the owning
    tenant's robust counters move) AND flight-dump naming — the multiplexer
    now carries the per-row lineage ring + dump-on-fault, so a poisoned
    tenant row produces a named-batch JSONL dump exactly like a per-tenant
    pipeline's.
    """
    return SLOSpec(
        min_updates_per_second=5.0,
        max_compiled_variants=60,
        require_poisoned_named=True,
        require_multiplexed=True,
        require_quarantine_attributed=True,
        require_accounting_clean=True,
    )


def rolling_deploy_slo_spec() -> SLOSpec:
    """The SLO spec of the rolling-deploy scenario
    (``ReplayConfig.rolling_deploy=True``): one "host" is killed mid-traffic
    and its tenant sessions migrate to the survivor via the live-session
    drain→checkpoint→restore→replay-tail protocol
    (:mod:`torchmetrics_tpu.engine.migrate`).

    The promises: every migrated session's final ``compute()`` is
    bit-identical to an unmigrated shadow control fed the same stream
    (zero loss), the handoff window is degraded-but-visible (``/healthz``
    names the migrating tenant mid-flight), the whole host handoff lands
    inside a generous wall budget, and the ordinary fault SLOs (poison
    fire/resolve, hang fire/resolve, named dumps) keep holding through the
    deploy — chaos does not pause for the migration.
    """
    return SLOSpec(
        min_updates_per_second=5.0,
        require_poisoned_named=True,
        require_migration_zero_loss=True,
        require_migration_visible=True,
        max_migration_seconds=30.0,
        require_accounting_clean=True,
    )


def host_crash_slo_spec(cadence_batches: int = 4, fuse: int = 2) -> SLOSpec:
    """The SLO spec of the host-crash scenario
    (``ReplayConfig.host_crash=True``): one "host" is SIGKILL'd mid-traffic —
    no drain, no close, no final checkpoint — and its tenant sessions are
    recovered from the last **periodic** bundle their continuous
    :class:`~torchmetrics_tpu.engine.migrate.CheckpointPolicy` wrote.

    The promises: the replay gap (batches fed but not covered by the restore
    point) stays within the exact crash-loss bound — the cadence plus the open
    fusion chunk, ``cadence_batches + max(0, fuse - 2)``, which is the cadence
    itself at the scenario's ``fuse=2`` — the whole point of periodic
    chunk-consistent bundles; recovery (scan → chain-verified restore
    → gap re-feed) lands inside a generous wall budget; post-recovery
    ``compute()`` is **bit-identical** to an unkilled shadow control fed the
    same stream; delta bundles are measurably smaller than full bundles on the
    large-state ``CatMetric`` (mean-bytes ratio ≤ 0.8, the
    ``checkpoint.bundle_bytes`` gauge's evidence); and the ordinary fault SLOs
    (poison fire/resolve, hang fire/resolve, named dumps) keep holding through
    the crash — chaos does not pause for the recovery. ``cadence_batches`` and
    ``fuse`` must match ``ReplayConfig.checkpoint_every_batches`` / ``.fuse``.
    """
    return SLOSpec(
        min_updates_per_second=5.0,
        require_poisoned_named=True,
        max_replay_gap_batches=int(cadence_batches) + max(0, int(fuse) - 2),
        require_crash_zero_loss=True,
        max_recovery_seconds=30.0,
        max_delta_full_ratio=0.8,
        require_accounting_clean=True,
    )


def hung_host_slo_spec() -> SLOSpec:
    """The SLO spec of the hung-host scenario (``ReplayConfig.hung_host=True``):
    one "host" wedges mid-traffic — alive but silent, no drain, no close, no
    lease release — and its leased tenant sessions are fenced + failed over by
    the scrape-driven :class:`~torchmetrics_tpu.robust.fence.Watchdog`.

    The promises: the stale lease is **detected** within a budget that covers
    the lease TTL plus scrape cadence plus scheduler slack; the fence + restore
    completes inside its own wall budget; the zombie's late bundle write lands
    fenced-out — rejected and counted by the next recovery scan, never selected
    as a restore point; every failed-over session's final ``compute()`` is
    **bit-identical** to a never-hung shadow control fed the same stream (zero
    double-counting: the zombie contributed nothing past the fence, the
    successor missed nothing); the fence is operator-visible (``/healthz``
    degraded with the fenced tenant and failover target named, ``/leases``
    carrying the fence ledger); and the ordinary fault SLOs keep holding —
    chaos does not pause for the failover. Detection/failover walls are
    scheduler-jitter-dominated, so (like ``migration_seconds``) their recorded
    spreads make the ABSOLUTE budgets the regression sentinel's cap.
    """
    return SLOSpec(
        min_updates_per_second=5.0,
        require_poisoned_named=True,
        max_time_to_detect_seconds=15.0,
        max_time_to_failover_seconds=30.0,
        require_zombie_writes_rejected=True,
        require_fence_zero_double_count=True,
        require_fence_visible=True,
        require_accounting_clean=True,
    )


def skewed_load_slo_spec() -> SLOSpec:
    """The SLO spec of the skewed-load scenario
    (:func:`~torchmetrics_tpu.chaos.schedule.skewed_load_config` replayed with
    ``ReplayConfig.skewed_load=True``): a static placement concentrates every
    tenant but one onto one virtual host, and the fleet telemetry plane —
    continuous sampling, rate derivation, skew signals, the ``/fleet`` read
    API — must *notice*.

    The promises: the ``fleet_imbalance`` page fires from fleet samples alone
    (the declarative :func:`~torchmetrics_tpu.obs.fleet.imbalance_rule` over
    the derived ``fleet.imbalance`` gauge, through the standard pending→firing
    machinery) within the detection budget; ``/fleet`` serves the per-tenant
    rate table, the skew block and ranked advisory rebalance hints from ≥ 2
    real samples, and its scrape latency holds the same p95/p99 bounds as
    ``/metrics``; the mid-run hot-spot shift re-points the hot host (the
    unlabeled-series design: the firing page follows the load, no stale
    labelset strands); one gather taken under a wedged 2-host fake degrades
    LOUDLY — partial sample, missing host named — instead of stalling the
    sampler; and the ordinary fault SLOs keep holding through it all, because
    skew detection that only works in a sterile run is not detection.
    Detection wall is sample-cadence + dwell + scrape-jitter dominated, so
    (like the fencing walls) the recorded spread makes the absolute budget
    the regression sentinel's cap.
    """
    return SLOSpec(
        min_updates_per_second=5.0,
        require_poisoned_named=True,
        max_time_to_detect_imbalance_seconds=10.0,
        require_fleet_served=True,
        require_fleet_shift_tracked=True,
        require_fleet_degraded_loud=True,
        require_accounting_clean=True,
        scrape_routes=("/metrics", "/alerts", "/tenants", "/fleet"),
    )


def flash_crowd_slo_spec() -> SLOSpec:
    """The SLO spec of the flash-crowd scenario
    (:func:`~torchmetrics_tpu.chaos.schedule.flash_crowd_config` replayed with
    ``ReplayConfig.flash_crowd=True``): the whole crowd lands on one of two
    provisioned virtual hosts — burst arrivals, two tenants running hot at a
    heavy factor — and the **placement controller** (not an operator) must fix
    it with real drain→checkpoint→restore session moves, then fix it AGAIN
    when the schedule shifts the hot spot mid-run.

    The promises: the ``fleet_imbalance`` page fires from fleet samples alone
    within the detection budget (the controller and the pager read the same
    gauge); the controller closes its convergence episode inside the wall
    budget, with at least one clean post-shift move — a controller that only
    converges once is a seeded table, not a control loop; every moved session
    computes bit-identical to an unmoved shadow control fed the identical
    retained stream (zero-loss moves, judged over EVERY move the run
    executed); the live table was reconstructed from the durable state file
    at startup; ``GET /placement`` serves assignments, the move ledger and
    the decision log over real HTTP at the same latency bounds as
    ``/metrics``; throughput under the live controller holds a floor ratio
    of the static-placement control arm (same schedule, controller off); and
    the conservation audit stays strict-green through every move — a
    rebalance that loses or double-counts a batch is corruption, not load
    management. Convergence walls are sampler-cadence + reconcile-cadence +
    move-wall dominated, so the recorded spread makes the absolute budget
    the regression sentinel's cap.
    """
    return SLOSpec(
        min_updates_per_second=5.0,
        require_poisoned_named=True,
        max_time_to_detect_imbalance_seconds=15.0,
        require_fleet_served=True,
        max_placement_convergence_seconds=20.0,
        min_placement_moves=2,
        require_placement_zero_loss=True,
        require_placement_served=True,
        require_placement_durable_restore=True,
        require_placement_shift_move=True,
        min_placement_throughput_ratio=0.5,
        require_accounting_clean=True,
        scrape_routes=("/metrics", "/alerts", "/tenants", "/fleet", "/placement"),
    )


def _slug(route: str) -> str:
    return route.strip("/").replace("/", "_") or "root"


def _row(
    rows: List[Dict[str, Any]],
    name: str,
    value: Optional[float],
    threshold: Optional[float],
    unit: str,
    direction: str,
    detail: str = "",
) -> Dict[str, Any]:
    """Append one judged SLO row; ``direction`` is 'max' (value <= threshold)
    or 'min' (value >= threshold). A ``None`` value with a live threshold is a
    failure (the promised number could not even be measured)."""
    if threshold is None:
        passed = True
        detail = (detail + "; " if detail else "") + "not judged (no threshold configured)"
    elif value is None:
        passed = False
        detail = detail or "no measurement"
    elif direction == "max":
        passed = value <= threshold
    else:
        passed = value >= threshold
    row = {
        "slo": name,
        "value": value,
        "threshold": threshold,
        "unit": unit,
        "direction": direction,
        "passed": bool(passed),
        "detail": detail,
    }
    rows.append(row)
    return row


def _server_route_quantile(result: Dict[str, Any], route: str, q: float) -> Optional[float]:
    """The self-instrumented scrape-latency quantile for one route, seconds."""
    stats = (result.get("scrapes") or {}).get("server") or {}
    hist = stats.get(route)
    if not hist or not hist.get("buckets"):
        return None
    return histogram_quantile(hist["buckets"], q)


def _quantile_bucket_bounds(
    result: Dict[str, Any], route: str, q: float
) -> Optional[Tuple[float, float]]:
    """``(lower, next_upper)`` error bar of the route's quantile estimate.

    A bucket-midpoint estimate is only known to ±its bucket, and a true value
    sitting near a boundary flips the estimate between *adjacent* buckets
    across runs. The recorded error bar therefore spans the estimate's bucket
    plus one bucket of slack upward (``next_upper`` is the following bound) —
    written as the config's ``spread`` so the regression sentinel's spread-cap
    tolerance absorbs adjacent-bucket quantization hops while a multi-bucket
    jump (a real order-of-magnitude regression on these log buckets) still
    flags.
    """
    stats = (result.get("scrapes") or {}).get("server") or {}
    hist = stats.get(route)
    if not hist or not hist.get("buckets"):
        return None
    buckets = hist["buckets"]
    bucket = quantile_bucket(buckets, q)  # the SAME walk the estimate used
    if bucket is None:
        return None
    lower, upper = bucket
    if upper <= lower:
        return (lower, lower)  # open-ended +Inf bucket: no further slack to give
    bounds = [bound for bound, _ in buckets]
    index = bounds.index(upper)
    next_bound = bounds[index + 1] if index + 1 < len(bounds) else upper
    return (lower, upper if math.isinf(next_bound) else next_bound)


def _fault_episode(
    result: Dict[str, Any], fault: Dict[str, Any]
) -> Tuple[Optional[Dict[str, Any]], bool]:
    """``(episode, already_firing)`` for the fault's rule.

    Preferred: the first episode that *fired* at/after the injection stamp.
    Fallback (``already_firing=True``): an episode that was still firing when
    the fault landed — a second fault of the same kind injected while the
    watchdog is already raised (recorded schedules may do this) is covered,
    not unalerted; its time-to-fire is zero by definition.
    """
    episodes = (result.get("alerts") or {}).get("episodes") or []
    injected_at = fault.get("injected_at")
    if injected_at is None:
        return None, False
    same_rule = [
        ep for ep in episodes if ep.get("rule") == fault.get("rule") and ep.get("fired_at") is not None
    ]
    candidates = [
        ep
        for ep in same_rule
        # small slack: the watchdog can catch the fault within the same
        # chunk-commit microseconds the injection stamp was taken in
        if ep["fired_at"] >= injected_at - 0.005
    ]
    if candidates:
        return min(candidates, key=lambda ep: ep["fired_at"]), False
    covering = [
        ep
        for ep in same_rule
        if ep["fired_at"] <= injected_at
        and (ep.get("resolved_at") is None or ep["resolved_at"] > injected_at)
    ]
    if covering:
        return max(covering, key=lambda ep: ep["fired_at"]), True
    return None, False


def judge(
    result: Dict[str, Any], spec: Optional[SLOSpec] = None, prefix: str = "chaos"
) -> Dict[str, Any]:
    """Judge one replay result against ``spec``; returns the SLO report.

    Report shape: ``{"passed", "n_slos", "failed": [names], "slos": [rows],
    "spec": {...}, "configs": {bench-config-shaped numbers}}``. ``prefix``
    names the emitted bench configs (default ``chaos_*``) — distinct scenarios
    MUST use distinct prefixes (e.g. ``chaos_ht`` for the high-tenant
    scenario), or the regression sentinel would baseline one scenario's
    numbers against another's workload.
    """
    spec = spec or SLOSpec()
    rows: List[Dict[str, Any]] = []
    configs: Dict[str, Any] = {}

    def config(
        name: str,
        value: Optional[float],
        unit: str,
        threshold: Optional[float],
        spread: Optional[Dict[str, float]] = None,
    ) -> None:
        if value is None:
            return  # run_record drops non-numeric values anyway; stay explicit
        entry: Dict[str, Any] = {
            "value": round(float(value), 6),
            "unit": unit,
            "kind": "slo",
            "threshold": threshold,
        }
        if spread is not None:
            entry["spread"] = spread
        configs[name] = entry

    # ------------------------------------------------------------- throughput
    throughput = result.get("updates_per_second")
    _row(
        rows,
        "update_throughput",
        throughput,
        spec.min_updates_per_second,
        "updates/sec",
        "min",
        detail=f"{result.get('batches_fed', 0)} batches over"
        f" {result.get('wall_seconds', 0)}s wall"
        f" ({result.get('sleep_seconds', 0)}s scheduled idle)",
    )
    # chaos throughput includes in-replay compiles, fault handling and scrape
    # load — runner-speed-dominated, so (like the time_to_* configs) the
    # recorded spread floor makes the ABSOLUTE SLO budget the sentinel's cap
    config(
        f"{prefix}_update_throughput",
        throughput,
        "updates/sec",
        spec.min_updates_per_second,
        spread={"min": spec.min_updates_per_second, "max": throughput, "reps": 1}
        if spec.min_updates_per_second is not None and throughput is not None
        else None,
    )

    # ---------------------------------------------------------- scrape latency
    for route in spec.scrape_routes:
        for q, bound, label in (
            (0.95, spec.max_scrape_p95_seconds, "p95"),
            (0.99, spec.max_scrape_p99_seconds, "p99"),
        ):
            estimate = _server_route_quantile(result, route, q)
            driver = ((result.get("scrapes") or {}).get("driver") or {}).get(route) or {}
            _row(
                rows,
                f"scrape_{label}_{_slug(route)}",
                estimate,
                bound,
                "s",
                "max",
                detail=(
                    f"server histogram estimate (bucket midpoint);"
                    f" driver-observed {label}:"
                    f" {driver.get(f'{label}_seconds')}"
                    if estimate is not None
                    else f"no server-side samples for {route}"
                ),
            )
            if estimate is not None:
                bucket = _quantile_bucket_bounds(result, route, q)
                config(
                    f"{prefix}_scrape_{label}_{_slug(route)}",
                    estimate * 1e6,
                    "us",
                    bound * 1e6 if bound is not None else None,
                    # the estimate's error bar is its bucket: the regression
                    # sentinel's spread cap absorbs one-bucket quantization
                    # hops without absorbing real multi-bucket regressions
                    spread={
                        "min": round(bucket[0] * 1e6, 3),
                        "max": round(bucket[1] * 1e6, 3),
                        "reps": 1,
                    }
                    if bucket is not None
                    else None,
                )

    # ------------------------------------------------- fault fire/resolve times
    kind_counts: Dict[str, int] = {}
    for fault in result.get("faults", []):
        kind = fault["fault"]
        kind_counts[kind] = kind_counts.get(kind, 0) + 1
        # a schedule may inject the same fault kind more than once: every
        # occurrence gets its own row/config (ordinal-suffixed past the
        # first) instead of the last silently overwriting the others
        name = kind if kind_counts[kind] == 1 else f"{kind}_{kind_counts[kind]}"
        episode, already_firing = _fault_episode(result, fault)
        if episode is None:
            _row(
                rows,
                f"time_to_fire_{name}",
                None,
                spec.max_time_to_fire_seconds,
                "s",
                "max",
                detail=f"alert {fault.get('rule')!r} never fired after the"
                f" {kind} fault on {fault.get('tenant')!r}",
            )
            _row(
                rows,
                f"time_to_resolve_{name}",
                None,
                spec.max_time_to_resolve_seconds,
                "s",
                "max",
                detail="nothing fired, so nothing could resolve",
            )
            continue
        # a fault landing while its watchdog is already raised was alerted
        # the whole time: time-to-fire is zero by definition, and recovery
        # is measured from this fault's injection
        anchor = fault["injected_at"] if already_firing else episode["fired_at"]
        # clamped at zero: the matching slack exists exactly because the
        # injection stamp and the catching evaluation can share an instant
        ttf = 0.0 if already_firing else max(0.0, episode["fired_at"] - fault["injected_at"])
        _row(
            rows,
            f"time_to_fire_{name}",
            round(ttf, 6),
            spec.max_time_to_fire_seconds,
            "s",
            "max",
            detail=(
                f"rule {fault.get('rule')!r} was already firing on"
                f" {episode.get('series')!r} when the fault landed"
                if already_firing
                else f"rule {fault.get('rule')!r} on {episode.get('series')!r}"
                f" fired {ttf:.3f}s after injection"
            ),
        )
        # wall-clock reaction times are scheduler-jitter-dominated (they
        # quantize to the alert-evaluation cadence), so history-relative
        # gating at 1.5x-of-best would flap on a loaded CI runner. The
        # recorded spread makes the ABSOLUTE SLO budget the sentinel's cap:
        # within budget any value is noise; beyond it the SLO row itself
        # fails and the strict slo_pass config regresses.
        config(
            f"{prefix}_time_to_fire_{name}",
            ttf,
            "s",
            spec.max_time_to_fire_seconds,
            spread={"min": 0.0, "max": spec.max_time_to_fire_seconds, "reps": 1}
            if spec.max_time_to_fire_seconds is not None
            else None,
        )
        if episode.get("resolved_at") is None:
            _row(
                rows,
                f"time_to_resolve_{name}",
                None,
                spec.max_time_to_resolve_seconds,
                "s",
                "max",
                detail=f"rule {fault.get('rule')!r} was still firing when the run ended",
            )
        else:
            ttr = episode["resolved_at"] - anchor
            _row(
                rows,
                f"time_to_resolve_{name}",
                round(ttr, 6),
                spec.max_time_to_resolve_seconds,
                "s",
                "max",
                detail=f"resolved {ttr:.3f}s after "
                + ("this fault's injection" if already_firing else "firing"),
            )
            config(
                f"{prefix}_time_to_resolve_{name}",
                ttr,
                "s",
                spec.max_time_to_resolve_seconds,
                spread={"min": 0.0, "max": spec.max_time_to_resolve_seconds, "reps": 1}
                if spec.max_time_to_resolve_seconds is not None
                else None,
            )

    # -------------------------------------------------- compiled-variant churn
    variants = (result.get("cost") or {}).get("compiled_variants")
    _row(
        rows,
        "compiled_variants",
        variants,
        spec.max_compiled_variants,
        "variants",
        "max",
        detail=f"{(result.get('cost') or {}).get('compile_seconds', 0)}s total compile"
        " wall across the run's fresh XLA executables",
    )
    config(f"{prefix}_compiled_variants", variants, "variants", spec.max_compiled_variants)

    # ------------------------------------------------- flight-dump correctness
    expected = {
        (tenant, index)
        for tenant, indices in ((result.get("schedule") or {}).get("poisoned") or {}).items()
        for index in indices
        # the victim's NaN is CAUGHT by the value watchdog, not quarantined —
        # only guarded tenants owe a named-batch dump
        if tenant != (result.get("schedule") or {}).get("victim")
    }
    named = {
        (dump.get("tenant"), index)
        for dump in ((result.get("flight") or {}).get("dumps") or [])
        for index in dump.get("poisoned_batches", [])
    }
    missing = sorted(expected - named)
    if spec.require_poisoned_named:
        _row(
            rows,
            "flight_dump_names_poisoned",
            float(len(expected - named) == 0),
            1.0,
            "bool",
            "min",
            detail=(
                f"all {len(expected)} injected poisoned batch(es) named in dumps"
                if not missing
                else f"poisoned batches never named in any dump: {missing}"
            ),
        )

    # ------------------------------------------------ batch-lineage causality
    if spec.require_fault_causality:
        lineage = result.get("lineage") or {}
        causality_rows = lineage.get("poisoned") or []
        all_poisoned = {
            (tenant, index)
            for tenant, indices in ((result.get("schedule") or {}).get("poisoned") or {}).items()
            for index in indices
        }
        covered_rows = {(row.get("tenant"), row.get("index")) for row in causality_rows}
        unlinked = sorted(
            f"{row.get('tenant')}[{row.get('index')}]"
            for row in causality_rows
            if not row.get("linked")
        )
        unmeasured = sorted(all_poisoned - covered_rows)
        if not lineage.get("enabled"):
            value: Optional[float] = None
            detail = "replay result carries no batch-lineage section"
        else:
            value = float(not unlinked and not unmeasured)
            detail = (
                f"all {len(causality_rows)} injected NaN batch(es) resolve end-to-end:"
                " trace id → quarantine/flight dump (guarded) or alert firing (victim)"
                if value
                else f"unlinked poisoned batches: {unlinked}; unmeasured: {unmeasured}"
            )
        _row(rows, "fault_causality", value, 1.0, "bool", "min", detail=detail)

    # -------------------------------------------- cross-tenant fused dispatch
    if spec.require_multiplexed:
        mux = result.get("mux") or {}
        mux_report = mux.get("report") or {}
        fused = mux_report.get("fused_updates") or 0
        dispatches = mux_report.get("dispatches") or 0
        engaged = bool(fused) and bool(dispatches) and fused > dispatches
        _row(
            rows,
            "mux_engaged",
            float(engaged),
            1.0,
            "bool",
            "min",
            detail=(
                f"{fused} tenant-updates fused into {dispatches} dispatch(es),"
                f" peak width {mux_report.get('max_width')}"
                if mux
                else "replay result carries no multiplexer accounting"
            ),
        )
    if spec.require_quarantine_attributed:
        # isolation without dump evidence: exactly the tenants the schedule
        # poisoned (victim aside) show quarantines — no cohort bleed, no miss
        expected_tenants = sorted({tenant for tenant, _ in expected})
        quarantined = (result.get("robust") or {}).get("quarantined") or {}
        missed = [t for t in expected_tenants if not quarantined.get(t)]
        bled = sorted(set(quarantined) - set(expected_tenants))
        _row(
            rows,
            "quarantine_attributed",
            float(not missed and not bled),
            1.0,
            "bool",
            "min",
            detail=(
                f"quarantines on exactly {expected_tenants}"
                if not missed and not bled
                else f"missed poisoned tenants {missed}; cohort bleed onto {bled}"
            ),
        )

    # --------------------------------------------- live-session migration
    migration = result.get("migration") or {}
    if spec.require_migration_zero_loss:
        migrated = migration.get("tenants") or []
        controls = migration.get("controls") or {}
        identical = [t for t in migrated if (controls.get(t) or {}).get("bit_identical")]
        divergent = sorted(set(migrated) - set(identical))
        ok = bool(migrated) and not divergent
        _row(
            rows,
            "migration_zero_loss",
            float(ok),
            1.0,
            "bool",
            "min",
            detail=(
                f"all {len(migrated)} migrated session(s) computed bit-identical to"
                " their unmigrated controls"
                if ok
                else (
                    f"migrated sessions diverged from their controls: {divergent}"
                    if migrated
                    else "no tenants were migrated (the rolling deploy never happened)"
                )
            ),
        )
        config(f"{prefix}_migrated_tenants", float(len(migrated)), "tenants", None)
    if spec.require_migration_visible:
        named = migration.get("healthz_named_migrating")
        _row(
            rows,
            "migration_visible_degraded",
            float(bool(named)),
            1.0,
            "bool",
            "min",
            detail=(
                "mid-migration /healthz was degraded with the migrating tenant named"
                if named
                else "no mid-migration /healthz observation named the migrating tenant"
            ),
        )
    if spec.max_migration_seconds is not None:
        seconds = migration.get("migration_seconds")
        _row(
            rows,
            "migration_seconds",
            seconds,
            spec.max_migration_seconds,
            "s",
            "max",
            detail=f"{len(migration.get('tenants') or [])} session(s)"
            " drained, checkpointed, restored and tail-replayed",
        )
        # handoff wall time is dominated by bundle I/O + restore compiles on
        # the runner: like the time_to_* configs, the recorded spread makes
        # the ABSOLUTE SLO budget the sentinel's cap
        config(
            f"{prefix}_migration_seconds",
            seconds,
            "s",
            spec.max_migration_seconds,
            spread={"min": 0.0, "max": spec.max_migration_seconds, "reps": 1},
        )

    # --------------------------------------- crash-consistent checkpointing
    crash = result.get("crash") or {}
    if spec.max_replay_gap_batches is not None:
        gap = crash.get("replay_gap_batches")
        cadence = crash.get("cadence_batches")
        _row(
            rows,
            "replay_gap_batches",
            gap,
            float(spec.max_replay_gap_batches),
            "batches",
            "max",
            detail=(
                f"max over {len(crash.get('tenants') or [])} crashed session(s);"
                f" checkpoint cadence {cadence} batches, per-session gaps"
                f" {[s['replay_gap_batches'] for s in (crash.get('sessions') or {}).values()]}"
                if crash
                else "replay result carries no crash accounting"
            ),
        )
        # the gap quantizes to where the crash lands inside the cadence
        # window: any value inside the budget is schedule geometry, not a
        # regression — the recorded spread makes the absolute bound the cap
        config(
            f"{prefix}_replay_gap_batches",
            gap,
            "batches",
            float(spec.max_replay_gap_batches),
            spread={"min": 0.0, "max": float(spec.max_replay_gap_batches), "reps": 1},
        )
    if spec.require_crash_zero_loss:
        crashed = crash.get("tenants") or []
        crash_controls = crash.get("controls") or {}
        identical = [t for t in crashed if (crash_controls.get(t) or {}).get("bit_identical")]
        divergent = sorted(set(crashed) - set(identical))
        ok = bool(crashed) and not divergent and bool(crash.get("torn_bundle_skipped", True))
        _row(
            rows,
            "crash_zero_loss",
            float(ok),
            1.0,
            "bool",
            "min",
            detail=(
                f"all {len(crashed)} recovered session(s) computed bit-identical to"
                " their unkilled controls (torn mid-write bundle skipped)"
                if ok
                else (
                    f"recovered sessions diverged from their controls: {divergent}"
                    if crashed and divergent
                    else (
                        "the torn mid-write bundle was chosen as a restore point"
                        if crashed
                        else "no tenants were crashed (the host crash never happened)"
                    )
                )
            ),
        )
        config(f"{prefix}_crashed_tenants", float(len(crashed)), "tenants", None)
    if spec.max_recovery_seconds is not None:
        seconds = crash.get("recovery_seconds")
        _row(
            rows,
            "recovery_seconds",
            seconds,
            spec.max_recovery_seconds,
            "s",
            "max",
            detail=f"{len(crash.get('tenants') or [])} session(s) scanned,"
            " chain-verified, restored and gap-re-fed",
        )
        config(
            f"{prefix}_recovery_seconds",
            seconds,
            "s",
            spec.max_recovery_seconds,
            spread={"min": 0.0, "max": spec.max_recovery_seconds, "reps": 1},
        )
    if spec.max_delta_full_ratio is not None:
        checkpoints = crash.get("checkpoints") or {}
        ratio = checkpoints.get("delta_full_ratio")
        _row(
            rows,
            "delta_bundle_bytes_ratio",
            ratio,
            spec.max_delta_full_ratio,
            "ratio",
            "max",
            detail=(
                f"delta mean {checkpoints.get('delta_bytes_mean'):.0f}B over"
                f" {checkpoints.get('delta_bundles')} bundle(s) vs full mean"
                f" {checkpoints.get('full_bytes_mean'):.0f}B over"
                f" {checkpoints.get('full_bundles')} (checkpoint.bundle_bytes gauge)"
                if ratio is not None
                else "no full+delta bundle pair was written"
            ),
        )
        config(
            f"{prefix}_delta_bundle_bytes_ratio",
            ratio,
            "ratio",
            spec.max_delta_full_ratio,
            spread={"min": 0.0, "max": spec.max_delta_full_ratio, "reps": 1},
        )

    # --------------------------------------------------- hung-host fencing
    fence = result.get("fence") or {}
    if spec.max_time_to_detect_seconds is not None:
        seconds = fence.get("time_to_detect_seconds")
        _row(
            rows,
            "time_to_detect_seconds",
            seconds,
            spec.max_time_to_detect_seconds,
            "s",
            "max",
            detail=(
                f"max wedge-to-detection wall over {len(fence.get('tenants') or [])}"
                f" fenced session(s); lease TTL {fence.get('lease_seconds')}s,"
                " detection driven by the /metrics scrape loop"
                if fence
                else "replay result carries no fence accounting"
            ),
        )
        # detection lands wherever the next scrape tick falls after the lease
        # expires: any wall inside the budget is scrape cadence + scheduler
        # jitter, not a regression — the recorded spread makes the absolute
        # budget the regression sentinel's cap
        config(
            f"{prefix}_time_to_detect_seconds",
            seconds,
            "s",
            spec.max_time_to_detect_seconds,
            spread={"min": 0.0, "max": spec.max_time_to_detect_seconds, "reps": 1},
        )
    if spec.max_time_to_failover_seconds is not None:
        seconds = fence.get("time_to_failover_seconds")
        _row(
            rows,
            "time_to_failover_seconds",
            seconds,
            spec.max_time_to_failover_seconds,
            "s",
            "max",
            detail=f"{len(fence.get('tenants') or [])} session(s) fenced, restored"
            " elsewhere under a new epoch and gap-re-fed",
        )
        config(
            f"{prefix}_time_to_failover_seconds",
            seconds,
            "s",
            spec.max_time_to_failover_seconds,
            spread={"min": 0.0, "max": spec.max_time_to_failover_seconds, "reps": 1},
        )
    if spec.require_zombie_writes_rejected:
        zombie = fence.get("zombie") or {}
        ok = bool(
            zombie.get("landed")
            and int(zombie.get("rejected_count") or 0) >= 1
            and zombie.get("discarded")
        )
        _row(
            rows,
            "zombie_writes_rejected",
            float(ok),
            1.0,
            "bool",
            "min",
            detail=(
                f"zombie {zombie.get('tenant')!r} wrote {zombie.get('bundle')!r}"
                " post-fence; the recovery scan counted it rejected"
                f" ({zombie.get('rejected_count')}x) and selected"
                f" {zombie.get('selected')!r} instead"
                if ok
                else (
                    "the zombie's post-fence bundle write was not provably"
                    f" discarded: {zombie or 'no zombie accounting recorded'}"
                )
            ),
        )
    if spec.require_fence_zero_double_count:
        fenced = fence.get("tenants") or []
        fence_controls = fence.get("controls") or {}
        identical = [t for t in fenced if (fence_controls.get(t) or {}).get("bit_identical")]
        divergent = sorted(set(fenced) - set(identical))
        ok = bool(fenced) and not divergent and bool(fence.get("zero_double_count"))
        _row(
            rows,
            "fence_zero_double_count",
            float(ok),
            1.0,
            "bool",
            "min",
            detail=(
                f"all {len(fenced)} failed-over session(s) computed bit-identical"
                " to their never-hung controls (zombie contributed nothing past"
                " the fence, the successor missed nothing)"
                if ok
                else (
                    f"failed-over sessions diverged from their controls: {divergent}"
                    if fenced and divergent
                    else (
                        "double-count check did not pass"
                        if fenced
                        else "no tenants were fenced (the host never hung)"
                    )
                )
            ),
        )
        config(f"{prefix}_failed_over_tenants", float(len(fenced)), "tenants", None)
    if spec.require_fence_visible:
        ok = bool(fence.get("healthz_named_fenced")) and int(fence.get("leases_page_fences") or 0) >= 1
        _row(
            rows,
            "fence_visible_degraded",
            float(ok),
            1.0,
            "bool",
            "min",
            detail=(
                "/healthz went degraded-not-dead naming the fenced tenant and"
                f" failover target; /leases carried {fence.get('leases_page_fences')}"
                " fence ledger entr(ies)"
                if ok
                else (
                    f"fence visibility probes failed: healthz_named_fenced="
                    f"{fence.get('healthz_named_fenced')!r},"
                    f" leases_page_fences={fence.get('leases_page_fences')!r}"
                )
            ),
        )

    # ------------------------------------------------ fleet telemetry plane
    fleet = result.get("fleet") or {}
    if spec.max_time_to_detect_imbalance_seconds is not None:
        seconds = fleet.get("time_to_detect_imbalance_seconds")
        _row(
            rows,
            "time_to_detect_imbalance_seconds",
            seconds,
            spec.max_time_to_detect_imbalance_seconds,
            "s",
            "max",
            detail=(
                "skew onset (first batch under the hot placement) to the"
                " fleet_imbalance page's fired_at, derived from"
                f" {fleet.get('samples')} fleet sample(s) at"
                f" {fleet.get('cadence_seconds')}s cadence — the rule read"
                " only the fleet.imbalance gauge"
                if fleet
                else "replay result carries no fleet accounting"
            ),
        )
        # the page lands wherever dwell + the next sample + the next scrape
        # tick fall: any wall inside the budget is cadence + scheduler
        # jitter, not a regression — the recorded spread makes the absolute
        # budget the regression sentinel's cap
        config(
            f"{prefix}_time_to_detect_imbalance_seconds",
            seconds,
            "s",
            spec.max_time_to_detect_imbalance_seconds,
            spread={
                "min": 0.0,
                "max": spec.max_time_to_detect_imbalance_seconds,
                "reps": 1,
            },
        )
    if spec.require_fleet_served:
        probe = fleet.get("probe") or {}
        n_samples = int(((probe.get("sampler") or {}).get("samples")) or 0)
        has_rates = bool(
            any(
                (row or {}).get("updates_per_second") is not None
                for row in (probe.get("tenants") or {}).values()
            )
        )
        skew_block = probe.get("skew") or {}
        has_skew = skew_block.get("imbalance") is not None and bool(skew_block.get("hosts"))
        rebalance = probe.get("rebalance") or {}
        has_hints = bool(rebalance.get("advisory")) and "hints" in rebalance
        ok = bool(probe.get("enabled")) and n_samples >= 2 and has_rates and has_skew and has_hints
        _row(
            rows,
            "fleet_served",
            float(ok),
            1.0,
            "bool",
            "min",
            detail=(
                f"GET /fleet served the per-tenant rate table, the skew block"
                f" and {len(rebalance.get('hints') or [])} advisory rebalance"
                f" hint(s) from {n_samples} real samples"
                f" ({fleet.get('history_samples')} in /fleet/history)"
                if ok
                else (
                    "the /fleet probe did not serve a full report:"
                    f" enabled={probe.get('enabled')!r} samples={n_samples}"
                    f" rates={has_rates} skew={has_skew} hints={has_hints}"
                )
            ),
        )
        config(f"{prefix}_fleet_samples", float(fleet.get("samples") or 0), "samples", None)
    if spec.require_fleet_shift_tracked:
        shift = fleet.get("shift") or {}
        ok = bool(shift.get("hot_host_shifted")) and bool(fleet.get("alert_fired"))
        _row(
            rows,
            "fleet_shift_tracked",
            float(ok),
            1.0,
            "bool",
            "min",
            detail=(
                "the mid-run placement flip re-pointed the hot host"
                f" ({shift.get('hot_host_before')!r} →"
                f" {shift.get('hot_host_after')!r}) while the imbalance page"
                " stayed on the single unlabeled fleet.imbalance series"
                if ok
                else (
                    "hot-spot shift was not tracked:"
                    f" before={shift.get('hot_host_before')!r}"
                    f" after={shift.get('hot_host_after')!r}"
                    f" alert_fired={fleet.get('alert_fired')!r}"
                )
            ),
        )
    if spec.require_fleet_degraded_loud:
        wedged = (fleet.get("shift") or {}).get("wedged_sample") or {}
        ok = bool(wedged.get("degraded")) and bool(wedged.get("missing_hosts"))
        _row(
            rows,
            "fleet_degraded_loud",
            float(ok),
            1.0,
            "bool",
            "min",
            detail=(
                "the gather under a wedged 2-host fake degraded loudly in"
                f" {wedged.get('sample_seconds')}s — partial sample, hosts"
                f" {wedged.get('missing_hosts')} named missing — instead of"
                " stalling the sampler"
                if ok
                else f"no loud degraded sample recorded: {wedged or 'no wedged-sample evidence'}"
            ),
        )

    # ------------------------------------------- placement control plane
    placement = result.get("placement") or {}
    if spec.max_placement_convergence_seconds is not None:
        converged = bool(placement.get("converged"))
        seconds = placement.get("convergence_seconds") if converged else None
        _row(
            rows,
            "placement_convergence_seconds",
            seconds,
            spec.max_placement_convergence_seconds,
            "s",
            "max",
            detail=(
                f"the controller closed {placement.get('episodes_closed')}"
                " convergence episode(s); the last imbalance episode closed"
                f" {seconds}s after it opened, with"
                f" {placement.get('moves_completed')} move(s) completed over"
                f" the run and {placement.get('settle_sweeps')} settle"
                " sweep(s) past the schedule's end"
                if seconds is not None
                else (
                    "the run ended with the imbalance episode still open"
                    f" (episodes_closed={placement.get('episodes_closed')!r})"
                    if placement
                    else "replay result carries no placement accounting"
                )
            ),
        )
        # convergence lands wherever sampler cadence + reconcile cadence +
        # the moves' checkpoint/restore walls fall: any wall inside the
        # budget is cadence + scheduler jitter, not a regression — the
        # recorded spread makes the absolute budget the sentinel's cap
        config(
            f"{prefix}_placement_convergence_seconds",
            seconds,
            "s",
            spec.max_placement_convergence_seconds,
            spread={
                "min": 0.0,
                "max": spec.max_placement_convergence_seconds,
                "reps": 1,
            },
        )
    if spec.min_placement_moves is not None:
        moves = placement.get("moves_completed")
        _row(
            rows,
            "placement_moves_completed",
            None if moves is None else float(moves),
            float(spec.min_placement_moves),
            "moves",
            "min",
            detail=(
                f"{moves} controller-ordered drain→checkpoint→restore move(s)"
                f" completed, {placement.get('moves_failed')} failed,"
                f" {placement.get('post_shift_moves')} after the hot-spot"
                " shift"
                if moves is not None
                else "replay result carries no placement accounting"
            ),
        )
        config(f"{prefix}_placement_moves", None if moves is None else float(moves), "moves", None)
    if spec.require_placement_zero_loss:
        controls = placement.get("controls") or {}
        ok = bool(placement.get("zero_loss")) and bool(controls)
        _row(
            rows,
            "placement_zero_loss",
            float(ok),
            1.0,
            "bool",
            "min",
            detail=(
                f"all {len(controls)} moved session(s) computed BIT-identical"
                " to unmoved shadow controls fed the identical retained"
                f" stream: {sorted(controls)}"
                if ok
                else (
                    "moved sessions diverged from their shadow controls: "
                    + ", ".join(
                        f"{t} (restored={row.get('restored')!r},"
                        f" control={row.get('control')!r})"
                        for t, row in sorted(controls.items())
                        if not row.get("bit_identical")
                    )
                    if controls
                    else "no moved sessions to compare — a flash crowd the"
                    " controller never answered is a failed run"
                )
            ),
        )
    if spec.require_placement_served:
        probe = placement.get("probe") or {}
        has_table = bool(probe.get("assignments"))
        has_moves = isinstance(probe.get("moves"), dict)
        has_decisions = isinstance(probe.get("decisions"), list)
        has_convergence = isinstance(probe.get("convergence"), dict)
        ok = has_table and has_moves and has_decisions and has_convergence
        _row(
            rows,
            "placement_served",
            float(ok),
            1.0,
            "bool",
            "min",
            detail=(
                f"GET /placement served {len(probe.get('assignments') or {})}"
                f" assignment(s), the move ledger and"
                f" {len(probe.get('decisions') or [])} decision-log row(s)"
                " over real HTTP"
                if ok
                else (
                    "the /placement probe did not serve a full report:"
                    f" table={has_table} moves={has_moves}"
                    f" decisions={has_decisions} convergence={has_convergence}"
                )
            ),
        )
    if spec.require_placement_durable_restore:
        ok = bool(placement.get("restored_from_disk"))
        _row(
            rows,
            "placement_durable_restore",
            float(ok),
            1.0,
            "bool",
            "min",
            detail=(
                "the live controller reconstructed its assignment table from"
                " the durable schema-versioned state file a prior controller"
                " persisted — the restart path, not a fresh in-memory table"
                if ok
                else "the assignment table was not restored from disk"
            ),
        )
    if spec.require_placement_shift_move:
        n = int(placement.get("post_shift_moves") or 0)
        _row(
            rows,
            "placement_shift_move",
            float(n >= 1),
            1.0,
            "bool",
            "min",
            detail=(
                f"{n} clean move(s) landed after the schedule's hot-spot"
                " shift — the controller re-converged on the NEW skew, it"
                " did not just ride out its first table"
                if n >= 1
                else "no clean move landed after the hot-spot shift"
            ),
        )
    if spec.min_placement_throughput_ratio is not None:
        # feed-rate ratio with each arm's measured XLA compile wall and
        # scheduled idle excluded from its own denominator. Every restore a
        # move performs mints a fresh compiled program, so on a cold-cache
        # harness raw wall-clock charges the controller for compile time the
        # compiled_variants SLO already measures and caps separately — the
        # ratio judges the steady-state serving rate, not compile churn
        def _adjusted_rate(sample: Optional[Dict[str, Any]]) -> Optional[float]:
            if not sample:
                return None
            batches = sample.get("batches_fed")
            wall = sample.get("wall_seconds")
            if batches is None or wall is None:
                return None
            active = (
                float(wall)
                - float(sample.get("sleep_seconds") or 0.0)
                - float(sample.get("compile_seconds") or 0.0)
            )
            if active <= 0:
                return None
            return float(batches) / active

        control_sample = placement.get("control_arm") or {}
        control_arm = placement.get("control_arm_updates_per_second")
        live = result.get("updates_per_second")
        live_adjusted = _adjusted_rate(
            {
                "batches_fed": result.get("batches_fed"),
                "wall_seconds": result.get("wall_seconds"),
                "sleep_seconds": result.get("sleep_seconds"),
                "compile_seconds": (result.get("cost") or {}).get(
                    "compile_seconds"
                ),
            }
        )
        control_adjusted = _adjusted_rate(control_sample)
        if live_adjusted is not None and control_adjusted:
            ratio = live_adjusted / control_adjusted
        elif live is not None and control_arm:
            # older payloads carry only the raw scalar — fall back honestly
            ratio = float(live) / float(control_arm)
        else:
            ratio = None
        _row(
            rows,
            "placement_throughput_ratio",
            ratio,
            spec.min_placement_throughput_ratio,
            "ratio",
            "min",
            detail=(
                f"{round(live_adjusted, 3) if live_adjusted is not None else live}"
                " updates/s under the live controller vs"
                f" {round(control_adjusted, 3) if control_adjusted else control_arm}"
                " updates/s for the static-placement control arm (same"
                " schedule, controller off), both net of measured compile"
                " wall + scheduled idle — the floor proves the controller"
                " does not COST meaningful serving throughput; same-host"
                " virtual moves cannot prove it ADDS any, and compile churn"
                " is judged separately by compiled_variants"
                if ratio is not None
                else "no control-arm throughput recorded (run the scenario"
                " through bench.py --chaos, which replays the control arm"
                " first)"
            ),
        )
        config(
            f"{prefix}_placement_throughput_ratio",
            ratio,
            "ratio",
            spec.min_placement_throughput_ratio,
            spread={
                "min": spec.min_placement_throughput_ratio,
                "max": ratio,
                "reps": 1,
            }
            if ratio is not None
            else None,
        )

    # ------------------------------------------------- conservation audit
    if spec.require_accounting_clean:
        audit = result.get("audit") or {}
        violations = audit.get("violations") or []
        ok = (
            bool(audit.get("enabled"))
            and int(audit.get("ticks") or 0) >= 1
            and not violations
        )
        _row(
            rows,
            "accounting_clean",
            float(ok),
            1.0,
            "bool",
            "min",
            detail=(
                f"the conservation auditor balanced every flow ledger over"
                f" {audit.get('ticks')} tick(s) across {audit.get('sessions')}"
                " session(s): zero violations"
                + (
                    " (honest-approximate: lineage records evicted)"
                    if audit.get("approximate")
                    else ""
                )
                if ok
                else (
                    "conservation violations: "
                    + "; ".join(
                        f"{v.get('invariant')} [tenant {v.get('tenant')}"
                        + (
                            f", trace {v.get('trace_id')}"
                            if v.get("trace_id")
                            else ""
                        )
                        + f"]: {v.get('detail')}"
                        for v in violations[:5]
                    )
                    if violations
                    else (
                        "no audit evidence recorded:"
                        f" {audit or 'audit plane was off'}"
                    )
                )
            ),
        )
        config(
            f"{prefix}_audit_violations", float(len(violations)), "violations", None
        )

    failed = [row["slo"] for row in rows if not row["passed"]]
    passed = not failed
    config(f"{prefix}_slo_pass", 1.0 if passed else 0.0, "slo_pass", 1.0)
    return {
        "passed": passed,
        "n_slos": len(rows),
        "failed": failed,
        "slos": rows,
        "spec": spec.asdict(),
        "configs": configs,
    }


def format_report(report: Dict[str, Any]) -> str:
    """Aligned human-readable SLO table (the chaos analog of regress tables)."""
    rows = report.get("slos", [])
    header = "== chaos SLO report =="
    if not rows:
        return header + "\n  (no SLOs judged)\n"
    width = max(len(r["slo"]) for r in rows)
    lines = [header]
    for row in rows:
        verdict = "ok" if row["passed"] else "FAILED"
        value = "n/a" if row["value"] is None else f"{row['value']:g}"
        bound = "-" if row["threshold"] is None else f"{row['threshold']:g}"
        op = "<=" if row["direction"] == "max" else ">="
        lines.append(
            f"  {row['slo']:<{width}}  {verdict:<7} value={value} {op} {bound}"
            f" {row['unit']}  {row['detail']}"
        )
    n_bad = len(report.get("failed", []))
    lines.append(
        f"-- {'PASS' if report.get('passed') else 'FAIL'}:"
        f" {n_bad} failure(s) across {len(rows)} SLO(s) --"
    )
    return "\n".join(lines) + "\n"
