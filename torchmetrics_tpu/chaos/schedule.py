"""Seeded, deterministic traffic schedules for the chaos replay bench.

``bench.py`` has always timed one clean loop; production is many tenants with
mixed batch shapes, signature churn, bursts and idle gaps, poisoned batches
and the occasional hung host. A :class:`TrafficSchedule` is that workload as
*data*: an ordered event timeline generated from one integer seed, so a chaos
round is exactly reproducible and a recorded schedule can be replayed against
any later build (the serving-comparison argument: operational behavior under
churn is the number that matters, so the workload that produces it must be
pinned).

Determinism contract: :func:`generate` uses a single ``random.Random(seed)``
stream and embeds **no wall-clock timestamps** — the same config serializes to
byte-identical JSONL every time (asserted by tests). Replay wall times are
measured by :mod:`~torchmetrics_tpu.chaos.replay`, never stored here.

Wire format (JSONL, atomic writes via ``utils/fileio``): one ``meta`` line
(``schema`` = :data:`SCHEDULE_SCHEMA`, the generating config, tenant roles,
``n_events``), then one ``event`` line per event carrying its ordinal ``i``.
Loading is **loud**: a schema mismatch, an unparseable line, an ordinal gap or
a truncated tail raises :class:`ScheduleError` — a chaos bench driven by half
a schedule would report SLOs for a workload nobody asked for.

Event kinds (executed in order by the replay driver):

- ``batch`` — one update batch for ``tenant`` (``size`` rows, ``poison`` True
  replaces the floating-point inputs with NaNs at the fault-injection seam).
- ``sleep`` — an idle gap of ``seconds`` (bursts are simply runs of ``batch``
  events with no ``sleep`` between them).
- ``arm`` — arm the named alert rules (the absence watchdog is armed only
  after warm traffic exists, so it watches for *going* quiet, not for never
  having spoken).
- ``hang_start`` / ``hang_end`` — the simulated hung host: the driver fires
  the hanging-collective fake (``robust/faults.py``) against the hung
  tenant's metric at ``hang_start``, and the schedule keeps that tenant
  silent until ``hang_end``.
- ``repair`` — the operator fixes the poisoned tenant (state reset); the
  drain traffic that follows lets its watchdog resolve.

Pure stdlib — importable without jax/numpy.
"""

from __future__ import annotations

import json
import random
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from torchmetrics_tpu.utils.fileio import atomic_write_text

__all__ = [
    "EVENT_KINDS",
    "ROLE_GUARDED",
    "ROLE_HUNG",
    "ROLE_VICTIM",
    "SCHEDULE_SCHEMA",
    "ScheduleConfig",
    "ScheduleError",
    "TrafficSchedule",
    "flash_crowd_config",
    "generate",
    "high_tenant_config",
    "skewed_load_config",
    "load",
    "loads",
]

# wire-format version of the JSONL schedule; bump on any structural change —
# loaders REJECT other versions (a schedule is a pinned workload, not a hint)
SCHEDULE_SCHEMA = 1

EVENT_KINDS = ("batch", "sleep", "arm", "hang_start", "hang_end", "repair")

# tenant roles: guarded tenants quarantine poisoned batches (flight-dump
# correctness), the victim lets NaN through to its value timeline (the
# non-finite watchdog's fire/resolve), the hung tenant goes silent for the
# hang window (the absence watchdog's fire/resolve + the collective fake)
ROLE_GUARDED = "guarded"
ROLE_VICTIM = "victim"
ROLE_HUNG = "hung"


class ScheduleError(RuntimeError):
    """A schedule file/text that cannot be trusted (schema, truncation, order)."""


@dataclass
class ScheduleConfig:
    """Knobs of :func:`generate`; serialized into the schedule's meta line.

    Args:
        seed: the single RNG seed — same config, same bytes.
        tenants: total tenant sessions (>= 3: one victim, one hung, the rest
            guarded).
        warm_batches: clean batches per tenant before any fault (the absence
            watchdog arms only after these).
        churn_batches: mixed-shape burst batches per tenant mid-run (the
            signature-churn phase that prices compiled-variant growth).
        drain_batches: recovery batches per tenant after the faults (lets the
            watchdogs resolve and the throughput tail stabilize).
        batch_sizes: the shape buckets batches are drawn from (mixed sizes on
            one tenant stream force chunk flushes and fresh compiles).
        num_classes: classification width of the guarded/hung tenants'
            metric.
        poisoned_guarded: NaN batches injected into one guarded tenant
            (quarantined, flight-dumped, named).
        hang_seconds: how long the hung tenant stays silent.
        absent_after_seconds: the absence watchdog's staleness budget (must be
            < ``hang_seconds`` or the hang can end before the alert fires).
        idle_gap_seconds: the small sleep between bursts.
        burst: batch events emitted back-to-back between idle gaps.
        hot_tenants: flash-crowd width — how many guarded tenants run HOT
            (``hot_factor`` × the baseline per-sweep traffic). ``0`` (the
            default) emits no hot traffic and preserves the historical byte
            stream exactly. With ``hot_tenants`` set, the first
            ``hot_tenants`` guarded tenants are hot through warm + churn, and
            at the ``repair`` event the hot spot SHIFTS: the *next*
            ``hot_tenants`` guarded tenants take over for the drain phase —
            the mid-run load migration a placement controller must chase.
        hot_factor: the hot tenants' traffic multiple per sweep (>= 2 when
            ``hot_tenants`` is set — a crowd of 1× is no crowd).
    """

    seed: int = 0
    tenants: int = 8
    warm_batches: int = 3
    churn_batches: int = 3
    drain_batches: int = 4
    batch_sizes: Tuple[int, ...] = (16, 24)
    num_classes: int = 4
    poisoned_guarded: int = 1
    hang_seconds: float = 0.8
    absent_after_seconds: float = 0.25
    idle_gap_seconds: float = 0.02
    burst: int = 4
    hot_tenants: int = 0
    hot_factor: int = 1

    def __post_init__(self) -> None:
        if self.tenants < 3:
            raise ValueError(
                f"Expected `tenants` >= 3 (victim + hung + >=1 guarded), got {self.tenants}"
            )
        self.batch_sizes = tuple(int(b) for b in self.batch_sizes)
        if not self.batch_sizes or min(self.batch_sizes) < 1:
            raise ValueError(f"Expected positive `batch_sizes`, got {self.batch_sizes}")
        for name in ("warm_batches", "churn_batches", "drain_batches"):
            if getattr(self, name) < 1:
                raise ValueError(f"Expected `{name}` >= 1, got {getattr(self, name)}")
        if self.poisoned_guarded < 1:
            raise ValueError(
                f"Expected `poisoned_guarded` >= 1, got {self.poisoned_guarded}"
            )
        if self.hang_seconds <= self.absent_after_seconds:
            raise ValueError(
                f"`hang_seconds` ({self.hang_seconds}) must exceed"
                f" `absent_after_seconds` ({self.absent_after_seconds}) or the hang"
                " window ends before the absence watchdog can fire"
            )
        if self.burst < 1:
            raise ValueError(f"Expected `burst` >= 1, got {self.burst}")
        if self.hot_tenants < 0:
            raise ValueError(f"Expected `hot_tenants` >= 0, got {self.hot_tenants}")
        if self.hot_factor < 1:
            raise ValueError(f"Expected `hot_factor` >= 1, got {self.hot_factor}")
        if self.hot_tenants:
            if self.hot_factor < 2:
                raise ValueError(
                    f"Expected `hot_factor` >= 2 with hot tenants, got {self.hot_factor}"
                    " (a flash crowd at 1x baseline traffic is no crowd)"
                )
            # two disjoint hot sets (initial + shifted) must fit inside the
            # guarded pool with at least one plain guarded tenant left over
            # for the poison draw — the fault surfaces never run hot
            if self.tenants < 2 * self.hot_tenants + 3:
                raise ValueError(
                    f"Expected `tenants` >= {2 * self.hot_tenants + 3} for"
                    f" `hot_tenants`={self.hot_tenants} (victim + hung + two"
                    " disjoint hot sets + >=1 cold guarded tenant), got"
                    f" {self.tenants}"
                )


@dataclass
class TrafficSchedule:
    """One generated (or loaded) chaos workload: config + roles + events."""

    config: ScheduleConfig
    roles: Dict[str, str]
    events: List[Dict[str, Any]] = field(default_factory=list)

    # ---------------------------------------------------------------- reading

    @property
    def tenants(self) -> List[str]:
        return sorted(self.roles)

    def tenants_with_role(self, role: str) -> List[str]:
        return sorted(t for t, r in self.roles.items() if r == role)

    @property
    def victim(self) -> str:
        return self.tenants_with_role(ROLE_VICTIM)[0]

    @property
    def hung(self) -> str:
        return self.tenants_with_role(ROLE_HUNG)[0]

    @property
    def guarded(self) -> List[str]:
        return self.tenants_with_role(ROLE_GUARDED)

    @property
    def hot_tenants_initial(self) -> List[str]:
        """The flash-crowd hot set through warm + churn (empty when the
        config runs no hot traffic). Derived, not stored: hot sets are the
        first ``hot_tenants`` guarded tenants in sorted order, so a loaded
        schedule reconstructs them from its config alone."""
        hot = getattr(self.config, "hot_tenants", 0)
        return self.guarded[:hot] if hot else []

    @property
    def hot_tenants_shifted(self) -> List[str]:
        """The post-shift hot set (takes over at the ``repair`` event)."""
        hot = getattr(self.config, "hot_tenants", 0)
        return self.guarded[hot : 2 * hot] if hot else []

    def batches(self) -> List[Dict[str, Any]]:
        return [ev for ev in self.events if ev["kind"] == "batch"]

    def poisoned(self) -> Dict[str, List[int]]:
        """Tenant-local poisoned batch ordinals, per tenant (the ground truth
        the flight-dump-correctness SLO checks replay output against)."""
        out: Dict[str, List[int]] = {}
        for ev in self.batches():
            if ev.get("poison"):
                out.setdefault(ev["tenant"], []).append(ev["index"])
        return {tenant: sorted(indices) for tenant, indices in out.items()}

    def total_sleep_seconds(self) -> float:
        return sum(ev["seconds"] for ev in self.events if ev["kind"] == "sleep")

    # ------------------------------------------------------------ wire format

    def to_jsonl(self) -> str:
        """The canonical byte representation (sorted keys, no timestamps)."""
        lines = [
            json.dumps(
                {
                    "type": "meta",
                    "schema": SCHEDULE_SCHEMA,
                    "config": asdict(self.config),
                    "roles": self.roles,
                    "n_events": len(self.events),
                },
                sort_keys=True,
            )
        ]
        for i, ev in enumerate(self.events):
            lines.append(json.dumps({"type": "event", "i": i, **ev}, sort_keys=True))
        return "\n".join(lines) + "\n"

    def save(self, path: str) -> str:
        """Atomically materialize the schedule at ``path``; returns the path."""
        return atomic_write_text(path, self.to_jsonl())


def loads(text: str, source: str = "<string>") -> TrafficSchedule:
    """Parse schedule JSONL, loudly. See the module docstring for what's fatal."""
    lines = text.splitlines()
    if not lines or not lines[0].strip():
        raise ScheduleError(f"{source}: empty schedule (no meta line)")
    try:
        meta = json.loads(lines[0])
    except ValueError as err:
        raise ScheduleError(f"{source}:1: unparseable meta line ({err})") from None
    if not isinstance(meta, dict) or meta.get("type") != "meta":
        raise ScheduleError(f"{source}:1: first line is not a schedule meta record")
    schema = meta.get("schema")
    if schema != SCHEDULE_SCHEMA:
        raise ScheduleError(
            f"{source}: schedule schema {schema!r} does not match this build's"
            f" {SCHEDULE_SCHEMA} — regenerate the schedule (a silently reinterpreted"
            " workload would invalidate every SLO judged from it)"
        )
    try:
        config = ScheduleConfig(**meta["config"])
    except (KeyError, TypeError, ValueError) as err:
        raise ScheduleError(f"{source}:1: bad schedule config ({err})") from None
    roles = meta.get("roles")
    if not isinstance(roles, dict) or not roles:
        raise ScheduleError(f"{source}:1: meta line carries no tenant roles")
    known_roles = (ROLE_GUARDED, ROLE_VICTIM, ROLE_HUNG)
    unknown = sorted({role for role in roles.values() if role not in known_roles})
    if unknown:
        raise ScheduleError(
            f"{source}:1: unknown tenant role(s) {unknown}; this build understands {known_roles}"
        )
    counts = {role: sum(1 for r in roles.values() if r == role) for role in known_roles}
    if counts[ROLE_VICTIM] != 1 or counts[ROLE_HUNG] != 1 or counts[ROLE_GUARDED] < 1:
        raise ScheduleError(
            f"{source}:1: roles must name exactly one victim, exactly one hung tenant"
            f" and at least one guarded tenant; got {counts} — the replay driver"
            " cannot run a fault scenario with missing surfaces"
        )
    events: List[Dict[str, Any]] = []
    for lineno, line in enumerate(lines[1:], 2):
        if not line.strip():
            raise ScheduleError(
                f"{source}:{lineno}: blank line inside the event stream (truncated"
                " or hand-edited schedule)"
            )
        try:
            record = json.loads(line)
        except ValueError:
            raise ScheduleError(
                f"{source}:{lineno}: unparseable (likely truncated) event line —"
                " refusing to replay a partial schedule"
            ) from None
        if record.get("type") != "event":
            raise ScheduleError(f"{source}:{lineno}: expected an event record")
        if record.get("i") != len(events):
            raise ScheduleError(
                f"{source}:{lineno}: event ordinal {record.get('i')!r} != expected"
                f" {len(events)} (reordered or spliced schedule)"
            )
        if record.get("kind") not in EVENT_KINDS:
            raise ScheduleError(
                f"{source}:{lineno}: unknown event kind {record.get('kind')!r};"
                f" this build understands {EVENT_KINDS}"
            )
        tenant = record.get("tenant")
        if tenant is not None and tenant not in roles:
            raise ScheduleError(
                f"{source}:{lineno}: event references tenant {tenant!r} that the"
                " roles map does not name — a spliced or hand-edited schedule"
            )
        events.append({k: v for k, v in record.items() if k not in ("type", "i")})
    n_events = meta.get("n_events")
    if n_events != len(events):
        raise ScheduleError(
            f"{source}: meta promises {n_events} event(s) but {len(events)} parsed"
            " — truncated schedule rejected"
        )
    return TrafficSchedule(config=config, roles=roles, events=events)


def load(path: str) -> TrafficSchedule:
    """Load (and validate, loudly) a schedule JSONL file."""
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError as err:
        raise ScheduleError(f"cannot read schedule {path}: {err}") from None
    return loads(text, source=path)


def high_tenant_config(seed: int = 0, tenants: int = 64) -> ScheduleConfig:
    """The high-tenant-count chaos preset: the multiplexer's stress workload.

    ≥64 tenant sessions sharing two batch-size signatures (shared signatures
    are what cross-tenant fusion batches on; two sizes keep signature churn in
    play), bursty arrivals (long back-to-back runs, short idle gaps) and a
    compressed warm/churn/drain cycle so the scenario stays CI-sized while the
    tenant axis — not the per-tenant stream length — carries the load. The
    fault surfaces (one victim, one hung tenant, one poisoned guarded tenant)
    are unchanged from the default scenario, so the same SLO fire/resolve
    machinery judges it.

    This is the workload behind ``bench.py --chaos --chaos-scenario
    high_tenant``: unmultiplexed it compiles O(tenants × signatures) variants
    (every tenant's metric instance owns its own jit cache); through
    :class:`~torchmetrics_tpu.engine.mux.TenantMultiplexer` the same traffic
    compiles O(width-buckets × signatures) — ``chaos_ht_compiled_variants``
    is that collapse, measured.
    """
    if tenants < 64:
        raise ValueError(
            f"Expected `tenants` >= 64 for the high-tenant preset, got {tenants}"
            " (the point is the tenant axis)"
        )
    return ScheduleConfig(
        seed=seed,
        tenants=tenants,
        warm_batches=2,
        churn_batches=2,
        drain_batches=2,
        batch_sizes=(16, 24),
        num_classes=4,
        poisoned_guarded=1,
        hang_seconds=0.8,
        absent_after_seconds=0.25,
        idle_gap_seconds=0.005,
        burst=16,
    )


def skewed_load_config(seed: int = 0, tenants: int = 8) -> ScheduleConfig:
    """The skewed-load chaos preset: the fleet telemetry plane's workload.

    A modest tenant count (the skew lives in the *placement*, which the
    replay supplies — every tenant but one lands on virtual host "0", so the
    hot host carries ~⅞ of the measured rate) with a slightly longer drain
    phase than the default: the imbalance page needs dwell time to ride the
    pending→firing machinery, and the post-shift world needs enough trailing
    traffic for the sampler to re-point the hot host before the run ends.
    The standard fault surfaces (victim, hung tenant, poisoned guarded
    tenant) are unchanged — skew detection must hold up WHILE the usual
    faults fire, not in a sterile run.

    This is the workload behind ``bench.py --chaos --chaos-scenario
    skewed_load``: the judged number is ``chaos_sk_time_to_detect_imbalance``
    — skew onset (first batch) to the ``fleet_imbalance`` page's fired_at,
    derived from fleet samples alone.
    """
    if tenants < 4:
        raise ValueError(
            f"Expected `tenants` >= 4 for the skewed-load preset, got {tenants}"
            " (one cold tenant against fewer than three hot ones is not skew)"
        )
    return ScheduleConfig(
        seed=seed,
        tenants=tenants,
        warm_batches=3,
        churn_batches=3,
        drain_batches=6,
        batch_sizes=(16, 24),
        num_classes=4,
        poisoned_guarded=1,
        hang_seconds=0.8,
        absent_after_seconds=0.25,
        idle_gap_seconds=0.02,
        burst=4,
    )


def flash_crowd_config(seed: int = 0, tenants: int = 12) -> ScheduleConfig:
    """The flash-crowd chaos preset: the placement control plane's workload.

    Two guarded tenants run HOT (5× the baseline per-sweep traffic, emitted
    as back-to-back bursts) through warm + churn, and at the ``repair`` event
    the hot spot SHIFTS to a disjoint pair for the drain phase. Replayed with
    ``ReplayConfig.flash_crowd=True`` every tenant is seeded onto virtual
    host ``"0"``, so the measured imbalance opens at 1.0; the
    :class:`~torchmetrics_tpu.fleet.placement.PlacementController` must drain
    it below the hysteresis floor by executing real
    drain→checkpoint→restore→replay-tail moves chosen from
    ``FleetSampler.rebalance_hints()`` alone — then do it AGAIN when the
    shift invalidates the converged table. The drain phase runs long so the
    post-shift world has traffic to converge against (the replay's settle
    loop extends it adaptively when the runner is slow).

    This is the workload behind ``bench.py --chaos --chaos-scenario
    flash_crowd``: judged on convergence wall time, completed-move counts
    (pre- and post-shift), bit-identity of every moved session vs an unmoved
    shadow control, and throughput against a controller-off control arm
    (configs prefixed ``chaos_fc_*``).
    """
    if tenants < 9:
        raise ValueError(
            f"Expected `tenants` >= 9 for the flash-crowd preset, got {tenants}"
            " (two disjoint 2-tenant hot sets + the fault surfaces + cold ballast)"
        )
    return ScheduleConfig(
        seed=seed,
        tenants=tenants,
        warm_batches=4,
        churn_batches=3,
        drain_batches=10,
        batch_sizes=(16, 24),
        num_classes=4,
        poisoned_guarded=1,
        hang_seconds=0.8,
        absent_after_seconds=0.25,
        idle_gap_seconds=0.03,
        burst=6,
        hot_tenants=2,
        hot_factor=5,
    )


# ------------------------------------------------------------------ generation


def _tenant_names(n: int) -> List[str]:
    return [f"tenant-{i:02d}" for i in range(n)]


def generate(config: Optional[ScheduleConfig] = None, **overrides: Any) -> TrafficSchedule:
    """Generate a deterministic chaos workload from ``config`` (or kwargs).

    Phases (all interleaving and shape choices drawn from one seeded stream):

    1. **warm** — round-robin clean traffic for every tenant, mixed sizes.
    2. **arm** — the absence watchdog arms (warm timelines now exist).
    3. **poison** — NaN batches land on the victim (value watchdog) and on one
       rng-chosen guarded tenant (quarantine + flight dump).
    4. **churn** — shuffled cross-tenant bursts with per-batch size draws: the
       signature-churn phase, hung tenant still participating.
    5. **hang** — ``hang_start``; every *other* tenant keeps bursting while
       sleeps accumulate to ``hang_seconds``; ``hang_end``.
    6. **repair + drain** — the victim is repaired, then every tenant
       (hung and victim included) drains clean traffic so the watchdogs
       resolve on measured wall clock.
    """
    if config is None:
        config = ScheduleConfig(**overrides)
    elif overrides:
        config = ScheduleConfig(**{**asdict(config), **overrides})
    rng = random.Random(config.seed)
    names = _tenant_names(config.tenants)
    victim, hung = names[0], names[1]
    roles = {name: ROLE_GUARDED for name in names}
    roles[victim] = ROLE_VICTIM
    roles[hung] = ROLE_HUNG
    # flash-crowd hot sets (empty at the default hot_tenants=0): chosen
    # deterministically WITHOUT the rng so the default byte stream is
    # untouched — the first `hot_tenants` guarded tenants run hot through
    # warm + churn, the next `hot_tenants` take over for the drain phase
    # (the shift lands at the `repair` event, which replay wall-stamps)
    guarded_sorted = names[2:]
    hot_initial = guarded_sorted[: config.hot_tenants] if config.hot_tenants else []
    hot_shifted = (
        guarded_sorted[config.hot_tenants : 2 * config.hot_tenants]
        if config.hot_tenants
        else []
    )

    counters = {name: 0 for name in names}
    events: List[Dict[str, Any]] = []

    def batch(tenant: str, poison: bool = False) -> None:
        events.append(
            {
                "kind": "batch",
                "tenant": tenant,
                "index": counters[tenant],
                "size": rng.choice(config.batch_sizes),
                "poison": bool(poison),
            }
        )
        counters[tenant] += 1

    def sleep(seconds: float) -> None:
        events.append({"kind": "sleep", "seconds": round(float(seconds), 6)})

    # 1. warm: round-robin, one idle gap per sweep; the initial hot set's
    # extra batches ride each sweep back-to-back (burst arrivals)
    for _ in range(config.warm_batches):
        for name in names:
            batch(name)
        for name in hot_initial:
            for _ in range(config.hot_factor - 1):
                batch(name)
        sleep(config.idle_gap_seconds)

    # 2. arm the absence watchdog now that every tenant has a warm timeline
    events.append({"kind": "arm", "rules": ["hang_absent"]})

    # 3. poison: the victim's NaN batch (value watchdog) + guarded quarantines.
    # Hot tenants are excluded from the draw (fault surfaces never run hot —
    # a moved-AND-poisoned tenant would entangle two proofs); at hot_tenants=0
    # the candidate list is the historical one, so the rng stream is unchanged
    poisoned_guarded_tenant = rng.choice(
        sorted(
            t
            for t, r in roles.items()
            if r == ROLE_GUARDED and t not in hot_initial and t not in hot_shifted
        )
    )
    batch(victim, poison=True)
    for _ in range(config.poisoned_guarded):
        batch(poisoned_guarded_tenant, poison=True)
    # clean traffic rides along so the poisoned batches sit inside real streams
    for name in names:
        batch(name)
    sleep(config.idle_gap_seconds)

    # 4. churn: shuffled cross-tenant bursts, per-batch size draws; the hot
    # set's traffic multiple holds through the churn (an empty extension at
    # hot_tenants=0 leaves the shuffle — and the byte stream — unchanged)
    churn_pool = [name for name in names for _ in range(config.churn_batches)]
    churn_pool += [
        name
        for name in hot_initial
        for _ in range((config.hot_factor - 1) * config.churn_batches)
    ]
    rng.shuffle(churn_pool)
    for i, name in enumerate(churn_pool):
        batch(name)
        if (i + 1) % config.burst == 0:
            sleep(config.idle_gap_seconds)

    # 5. hang: the hung tenant goes silent; everyone else keeps serving
    events.append({"kind": "hang_start", "tenant": hung, "seconds": config.hang_seconds})
    others = [name for name in names if name != hung]
    # split the window into slices, each a short sleep plus a small burst from
    # the surviving tenants — the obs plane is scraped under load, not at rest
    slices = max(2, int(round(config.hang_seconds / max(config.absent_after_seconds / 2, 0.05))))
    for _ in range(slices):
        sleep(config.hang_seconds / slices)
        for name in rng.sample(others, k=min(2, len(others))):
            batch(name)
    events.append({"kind": "hang_end", "tenant": hung})

    # 6. repair the victim, then drain everyone so the watchdogs resolve.
    # The repair event is also the flash crowd's HOT-SPOT SHIFT: the drained
    # world's extra traffic belongs to the second hot set — yesterday's hot
    # tenants go cold, a disjoint set heats up, and whatever placement the
    # controller converged on pre-shift is wrong again
    events.append({"kind": "repair", "tenant": victim})
    for _ in range(config.drain_batches):
        for name in names:
            batch(name)
        for name in hot_shifted:
            for _ in range(config.hot_factor - 1):
                batch(name)
        sleep(config.idle_gap_seconds)

    return TrafficSchedule(config=config, roles=roles, events=events)
