"""Traffic-replay chaos bench: production as a measured, SLO-judged scenario.

Three parts, composed by ``bench.py --chaos`` and usable standalone:

- :mod:`~torchmetrics_tpu.chaos.schedule` — a seeded, deterministic traffic
  schedule (many tenants, mixed shapes, bursts, poisoned batches, a hung
  host) with a schema-versioned JSONL record/load format.
- :mod:`~torchmetrics_tpu.chaos.replay` — the driver: the schedule through
  per-tenant :class:`~torchmetrics_tpu.engine.pipeline.MetricPipeline`
  sessions — or, with ``ReplayConfig.multiplex``, through ONE cross-tenant
  :class:`~torchmetrics_tpu.engine.mux.TenantMultiplexer` — while a
  background thread scrapes the live obs server.
- :mod:`~torchmetrics_tpu.chaos.slo` — the declarative SLO spec + judge:
  throughput, p95/p99 scrape latency, time-to-fire/time-to-resolve for the
  injected faults, compiled-variant churn, flight-dump correctness — emitted
  as bench configs so the regression sentinel gates them like perf numbers.

    from torchmetrics_tpu import chaos

    sched = chaos.generate(chaos.ScheduleConfig(seed=0, tenants=8))
    report = chaos.judge(chaos.replay(sched))
    print(chaos.format_report(report))
"""

from torchmetrics_tpu.chaos.schedule import (
    SCHEDULE_SCHEMA,
    ScheduleConfig,
    ScheduleError,
    TrafficSchedule,
    flash_crowd_config,
    generate,
    high_tenant_config,
    load,
    loads,
    skewed_load_config,
)
from torchmetrics_tpu.chaos.replay import ReplayConfig, ReplayError, replay
from torchmetrics_tpu.chaos.slo import (
    SLOSpec,
    flash_crowd_slo_spec,
    format_report,
    high_tenant_slo_spec,
    host_crash_slo_spec,
    hung_host_slo_spec,
    judge,
    rolling_deploy_slo_spec,
    skewed_load_slo_spec,
)

__all__ = [
    "SCHEDULE_SCHEMA",
    "ReplayConfig",
    "ReplayError",
    "SLOSpec",
    "ScheduleConfig",
    "ScheduleError",
    "TrafficSchedule",
    "flash_crowd_config",
    "flash_crowd_slo_spec",
    "format_report",
    "generate",
    "high_tenant_config",
    "high_tenant_slo_spec",
    "host_crash_slo_spec",
    "hung_host_slo_spec",
    "judge",
    "load",
    "loads",
    "replay",
    "rolling_deploy_slo_spec",
    "skewed_load_config",
    "skewed_load_slo_spec",
]
