"""Chaos replay driver: a schedule through tenant pipelines under live scrape.

This is where a :class:`~torchmetrics_tpu.chaos.schedule.TrafficSchedule`
becomes measured reality. :func:`replay` builds one
:class:`~torchmetrics_tpu.engine.pipeline.MetricPipeline` **session per
tenant** (``PipelineConfig.tenant`` + the shared alert engine), starts the
live introspection server on an ephemeral port, and executes the schedule's
events in order while a background thread concurrently scrapes the server —
the Prometheus model, run *during* the chaos rather than after it. Faults
travel the production seams:

- **Poisoned batches** arrive as NaN inputs. Guarded tenants
  (``error_policy="quarantine"``) degrade the fused chunk to a per-batch
  replay that quarantines exactly the poisoned batch and dumps the flight
  recorder with it *named*; the victim tenant runs an unguarded
  ``MeanSquaredError`` whose state goes NaN, so the ``non_finite`` value
  watchdog fires mid-stream (and resolves after the scheduled ``repair``).
- **The hung host** fires the hanging-collective fake
  (:func:`~torchmetrics_tpu.robust.faults.inject_collective_fault` under a
  short :func:`~torchmetrics_tpu.robust.degraded.sync_guard`): the guarded
  eager collective times out, the metric degrades loudly
  (``sync_degraded``), and the tenant stays silent for the hang window so
  the ``absent`` watchdog fires — then resolves when drain traffic returns.
- **Scrape latency** is measured twice: by the driver's scrape thread
  (client-observed, per route) and by the server's own
  ``server.request`` histogram (:mod:`~torchmetrics_tpu.obs.server`
  self-instrumentation) — the SLO judge reads the histogram via
  :func:`~torchmetrics_tpu.obs.export.histogram_quantile`.

:func:`replay` returns a plain-data result dict;
:mod:`~torchmetrics_tpu.chaos.slo` judges it. The driver leaves the process
clean (server stopped, pipelines closed, no engine installed globally), but
the tenant registry keeps the session rows — that is telemetry, not leakage.
"""

from __future__ import annotations

import json
import math
import os
import tempfile
import threading
import time
import urllib.request
import warnings
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from torchmetrics_tpu.chaos.schedule import ROLE_VICTIM, TrafficSchedule
from torchmetrics_tpu.obs import audit as _audit
from torchmetrics_tpu.obs import hostprof as _hostprof
from torchmetrics_tpu.obs import lineage as _lineage
from torchmetrics_tpu.obs import trace as _trace
from torchmetrics_tpu.obs.alerts import AlertEngine, AlertRule
from torchmetrics_tpu.obs.server import IntrospectionServer

__all__ = ["ReplayConfig", "ReplayError", "replay"]


class ReplayError(RuntimeError):
    """The replay could not execute the schedule it was given."""


@dataclass
class ReplayConfig:
    """Execution knobs of :func:`replay` (the *workload* lives in the schedule).

    Args:
        fuse: micro-batch fusion depth of every tenant pipeline (``1`` keeps
            the per-batch path — faster to warm up, no scan variants).
        multiplex: drive the guarded/hung tenants through ONE
            :class:`~torchmetrics_tpu.engine.mux.TenantMultiplexer` (cross-
            tenant fused dispatch, shared compiled programs) instead of one
            pipeline per tenant. The victim keeps its own unguarded pipeline
            — it runs a different metric class and the value-watchdog path is
            its whole point. This is the before/after lever for the
            compiled-variant-collapse SLO.
        mux_max_width: the multiplexer's top tenant-width bucket.
        rolling_deploy: simulate a rolling deploy — half the clean guarded
            tenants live on "host B", which is **killed mid-traffic** (at the
            schedule's midpoint): every host-B session is drained,
            checkpointed to a bundle, and restored as a fresh session on the
            survivor via the live-session migration protocol
            (:mod:`torchmetrics_tpu.engine.migrate`), with a shadow control
            metric fed the identical stream proving the restored ``compute()``
            bit-identical. The fault-surface tenants (victim, hung, the
            poisoned guarded tenant) stay on host A so their scenarios run
            unchanged *through* the deploy. Incompatible with ``multiplex``.
        migrate_fraction: fraction of the eligible (clean guarded) tenants
            placed on host B.
        host_crash: simulate an **unplanned** host death — the crash-consistent
            twin of ``rolling_deploy``. Host B's tenants run pipelines with a
            :class:`~torchmetrics_tpu.engine.migrate.CheckpointPolicy` writing
            **continuous periodic bundles** (delta-encoded, full compaction
            points, retention-swept) to per-tenant directories, and drive a
            large-state ``CatMetric`` (a capacity ``MaskedBuffer``) so the
            full-vs-delta bundle-bytes evidence is measurable. At the schedule
            midpoint host B is killed with SIGKILL semantics: **no drain, no
            close, no final checkpoint** — its pipelines are simply abandoned
            (batches in the open fusion chunk are lost). Recovery restores
            each tenant from :func:`~torchmetrics_tpu.engine.migrate.latest_valid_bundle`
            (a planted mid-write garbage bundle must be skipped), re-feeds the
            replay gap from the retained deterministic stream, and the run
            continues; shadow controls prove end-of-run bit-identity and the
            gap is judged against the cadence. Incompatible with
            ``multiplex`` and ``rolling_deploy``.
        checkpoint_every_batches: the host-crash tenants' checkpoint cadence
            (batches between periodic bundles — the replay-gap bound the SLO
            judges).
        checkpoint_dir: where the bundle streams land (default: a fresh
            tempdir per replay, removed on return).
        hung_host: simulate a host that **hangs** mid-traffic — the fencing
            twin of ``host_crash``. Host B's tenants run continuous-checkpoint
            pipelines holding a short renewable **lease**
            (:mod:`torchmetrics_tpu.robust.fence`); at the schedule midpoint
            host B wedges: no drain, no close, no lease release — its
            sessions simply stop renewing while their objects stay live (the
            defining difference from a crash: a zombie can still *write*).
            The scrape-driven :class:`~torchmetrics_tpu.robust.fence.Watchdog`
            detects the stale leases, fences the zombie epochs and restores
            each tenant elsewhere under a fresh epoch; the driver re-feeds the
            gap plus the wedge-period traffic from the retained stream. The
            zombie then attempts a late bundle write, which must land
            fenced-out: the next recovery scan rejects it (counted) and never
            selects it. Shadow controls prove end-of-run bit-identity — zero
            double-counting between zombie and successor. Incompatible with
            ``multiplex``, ``rolling_deploy`` and ``host_crash``.
        skewed_load: simulate a fleet with **heavily skewed per-host load** —
            the fleet-telemetry-plane scenario. A static placement maps every
            tenant but one onto virtual host ``"0"`` (the hot host) and the
            last tenant onto host ``"1"``; a
            :class:`~torchmetrics_tpu.obs.fleet.FleetSampler` with that
            placement is installed so the background scraper's ``/metrics``
            pulls drive continuous fleet sampling, rate derivation and the
            ``fleet.imbalance`` gauge, and the declarative
            :func:`~torchmetrics_tpu.obs.fleet.imbalance_rule` must fire
            through the standard pending→firing machinery — detection comes
            from fleet samples alone, nothing is told where the skew is. At
            two-thirds of the schedule the **hot spot shifts** (the placement
            flips hosts — the load concentration moves), which the sampler
            must track without stranding a stale firing series; right after
            the shift one sample is taken under the hanging-collective fake,
            proving a wedged host yields a LOUD degraded partial sample
            (``missing_hosts``) instead of stalling the sampler. ``/fleet``
            is scraped throughout and probed at end of run. Incompatible with
            ``multiplex``, ``rolling_deploy``, ``host_crash`` and
            ``hung_host``.
        flash_crowd: simulate a **flash crowd with a mid-run hot-spot shift**
            — the placement-control-plane scenario. Every tenant is seeded
            onto virtual host ``"0"`` (durably: the seeded table is written
            to disk by a throwaway controller and the live
            :class:`~torchmetrics_tpu.fleet.placement.PlacementController`
            is reconstructed FROM that state file — the restart path runs
            every replay), a :class:`~torchmetrics_tpu.obs.fleet.FleetSampler`
            is installed, and the controller — ticked by the background
            scraper's ``/metrics`` pulls — must notice the measured
            imbalance, open a hysteresis episode and drain it with REAL
            moves: drain → checkpoint → restore → swap, each under
            ``scope.migration(tenant, "rebalance")``, targets chosen from
            ``FleetSampler.rebalance_hints()`` alone. The schedule's hot-spot
            shift (the ``repair`` event — hot set B takes over) invalidates
            the converged table mid-run, forcing a second episode. A settle
            loop keeps post-shift traffic flowing until the controller
            converges (bounded) — decay-to-zero idle "convergence" is not
            accepted. Every moved session's final ``compute()`` is proven
            bit-identical to an unmoved shadow control rebuilt from the
            retained stream. ``/placement`` is scraped throughout and probed
            at end of run. Incompatible with every other scenario flag.
        placement_enabled: ``False`` runs the flash-crowd **control arm**:
            identical traffic, sampler installed, static all-on-"0"
            placement, NO controller — the throughput baseline the
            placement-overhead SLO compares against.
        placement_cadence_seconds: the controller's reconcile cadence (short
            so convergence fits a CI run; production cadences are tens of
            seconds).
        placement_max_moves: the controller's per-pass move budget.
        fleet_cadence_seconds: the fleet sampler's cadence (short, so a CI
            run accumulates enough samples; production cadences are seconds).
        lease_seconds: the hung-host tenants' lease TTL (short, so detection
            fits a CI run; production leases are tens of seconds).
        scrape_interval_seconds: pause between scrape sweeps of the routes.
        scrape_routes: routes the background thread hits each sweep.
        sync_timeout_seconds: the sync guard's per-attempt timeout for the
            injected hanging collective (the hang "costs" this much wall).
        flight_dump_dir: where fault dumps land (default: a fresh tempdir per
            replay, so dump-correctness checks see only this run's dumps).
        max_events: trace ring capacity while the replay records.
        alert_history: bounded transition-history size of the shared engine.
        hostprof: host-profiler plane. ``None`` (default) auto-enables the
            continuous sampler for the multiplexed scenario only;
            ``True``/``False`` force it on/off for any scenario. While live,
            the per-seam breakdown + floor report land in the run record
            under ``hostprof`` and a mid-run ``GET /profile`` probe proves
            the plane answers over HTTP during the fault window.
        hostprof_rate_hz: sampling rate for the host profiler when live.
        audit: conservation audit plane. ``None`` (default) enables the
            continuous :class:`~torchmetrics_tpu.obs.audit.ConservationAuditor`
            for every scenario — exactly-once accounting is part of what a
            chaos run proves (the ``accounting_clean`` SLO) — and the final
            ledger + invariant results land in the run record under
            ``audit``; ``False`` forces it off.
    """

    fuse: int = 2
    multiplex: bool = False
    mux_max_width: int = 64
    rolling_deploy: bool = False
    migrate_fraction: float = 0.5
    host_crash: bool = False
    checkpoint_every_batches: int = 4
    checkpoint_dir: Optional[str] = None
    hung_host: bool = False
    skewed_load: bool = False
    flash_crowd: bool = False
    placement_enabled: bool = True
    placement_cadence_seconds: float = 0.15
    placement_max_moves: int = 1
    fleet_cadence_seconds: float = 0.1
    lease_seconds: float = 0.25
    scrape_interval_seconds: float = 0.05
    scrape_routes: Tuple[str, ...] = ("/metrics", "/alerts", "/tenants", "/healthz")
    # host-profiler plane: None = auto (live for the multiplexed/high-tenant
    # scenario, where the Python floor under the mux path is the question the
    # profiler exists to answer); True/False force it on/off for any scenario
    hostprof: Optional[bool] = None
    hostprof_rate_hz: float = 200.0
    audit: Optional[bool] = None
    sync_timeout_seconds: float = 0.05
    flight_dump_dir: Optional[str] = None
    max_events: int = 8192
    alert_history: int = 1024

    def __post_init__(self) -> None:
        if self.fuse < 1:
            raise ValueError(f"Expected `fuse` >= 1, got {self.fuse}")
        if self.hostprof_rate_hz <= 0:
            raise ValueError(
                f"Expected positive `hostprof_rate_hz`, got {self.hostprof_rate_hz}"
            )
        if self.mux_max_width < 1:
            raise ValueError(f"Expected `mux_max_width` >= 1, got {self.mux_max_width}")
        if self.rolling_deploy and self.multiplex:
            raise ValueError(
                "`rolling_deploy` drives per-tenant pipeline sessions (each one a"
                " migratable bundle); it cannot be combined with `multiplex`"
            )
        if self.host_crash and (self.multiplex or self.rolling_deploy):
            raise ValueError(
                "`host_crash` drives per-tenant pipeline sessions with continuous"
                " checkpointing; it cannot be combined with `multiplex` or"
                " `rolling_deploy`"
            )
        if self.hung_host and (self.multiplex or self.rolling_deploy or self.host_crash):
            raise ValueError(
                "`hung_host` drives per-tenant leased pipeline sessions with"
                " continuous checkpointing; it cannot be combined with"
                " `multiplex`, `rolling_deploy` or `host_crash`"
            )
        if self.skewed_load and (
            self.multiplex or self.rolling_deploy or self.host_crash or self.hung_host
        ):
            raise ValueError(
                "`skewed_load` drives default per-tenant pipeline sessions under a"
                " fleet sampler; it cannot be combined with `multiplex`,"
                " `rolling_deploy`, `host_crash` or `hung_host`"
            )
        if self.flash_crowd and (
            self.multiplex
            or self.rolling_deploy
            or self.host_crash
            or self.hung_host
            or self.skewed_load
        ):
            raise ValueError(
                "`flash_crowd` drives default per-tenant pipeline sessions under a"
                " fleet sampler + placement controller; it cannot be combined with"
                " `multiplex`, `rolling_deploy`, `host_crash`, `hung_host` or"
                " `skewed_load`"
            )
        if self.placement_cadence_seconds <= 0:
            raise ValueError(
                f"Expected positive `placement_cadence_seconds`, got"
                f" {self.placement_cadence_seconds}"
            )
        if self.placement_max_moves < 1:
            raise ValueError(
                f"Expected `placement_max_moves` >= 1, got {self.placement_max_moves}"
            )
        if self.fleet_cadence_seconds <= 0:
            raise ValueError(
                f"Expected positive `fleet_cadence_seconds`, got {self.fleet_cadence_seconds}"
            )
        if self.lease_seconds <= 0:
            raise ValueError(f"Expected positive `lease_seconds`, got {self.lease_seconds}")
        if self.checkpoint_every_batches < 1:
            raise ValueError(
                f"Expected `checkpoint_every_batches` >= 1, got {self.checkpoint_every_batches}"
            )
        if (self.host_crash or self.hung_host) and self.fuse > self.checkpoint_every_batches:
            # the replay gap's worst case is cadence + fuse - 2 (commits land
            # on a fuse-spaced grid); a fusion depth beyond the cadence makes
            # the open chunk, not the cadence, the dominant loss window —
            # reject the misconfiguration instead of judging a vacuous bound
            # (host_crash_slo_spec(cadence, fuse=...) carries the exact bound)
            raise ValueError(
                f"`host_crash`/`hung_host` bound the replay gap by the checkpoint cadence"
                f" ({self.checkpoint_every_batches}) plus the open fusion chunk;"
                f" `fuse` ({self.fuse}) > the cadence would make the chunk the"
                " dominant loss window — deepen the cadence or shrink the fusion"
                " depth"
            )
        if not 0.0 < self.migrate_fraction <= 1.0:
            raise ValueError(
                f"Expected `migrate_fraction` in (0, 1], got {self.migrate_fraction}"
            )
        if self.scrape_interval_seconds <= 0:
            raise ValueError(
                f"Expected positive `scrape_interval_seconds`, got {self.scrape_interval_seconds}"
            )
        if self.sync_timeout_seconds <= 0:
            raise ValueError(
                f"Expected positive `sync_timeout_seconds`, got {self.sync_timeout_seconds}"
            )


# rule names are part of the replay's contract with the SLO judge
POISON_RULE = "chaos_poison_nonfinite"
HANG_RULE = "chaos_hang_absent"
IMBALANCE_RULE = "fleet_imbalance"  # minted by obs.fleet.imbalance_rule()


class _Scraper(threading.Thread):
    """Background scrape loop: client-observed per-route latencies + errors."""

    def __init__(self, base_url: str, routes: Tuple[str, ...], interval: float) -> None:
        super().__init__(name="tm-tpu-chaos-scraper", daemon=True)
        self.base_url = base_url
        self.routes = routes
        self.interval = interval
        self.latencies: Dict[str, List[float]] = {route: [] for route in routes}
        self.errors: Dict[str, int] = {route: 0 for route in routes}
        self.degraded_seen = 0
        self.sweeps = 0
        # NB: not `_stop` — threading.Thread owns an internal _stop() method
        self._halt = threading.Event()

    def stop(self, timeout: float = 10.0) -> None:
        self._halt.set()
        self.join(timeout)

    def run(self) -> None:
        while not self._halt.is_set():
            for route in self.routes:
                start = time.perf_counter()
                try:
                    with urllib.request.urlopen(self.base_url + route, timeout=10) as resp:
                        body = resp.read()
                except Exception:
                    self.errors[route] += 1
                    continue
                self.latencies[route].append(time.perf_counter() - start)
                if route == "/healthz" and b'"degraded"' in body:
                    # evidence that the injected faults were operator-visible
                    # mid-run, not only in the post-hoc history
                    self.degraded_seen += 1
            self.sweeps += 1
            self._halt.wait(self.interval)

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for route in self.routes:
            samples = sorted(self.latencies[route])

            def q(p: float) -> Optional[float]:
                if not samples:
                    return None
                # nearest-rank: ceil(p*n)-th order statistic, 0-indexed —
                # int(p*n) would be one rank high (p50 of two samples must be
                # the first, not the max)
                rank = math.ceil(p * len(samples)) - 1
                return samples[min(len(samples) - 1, max(0, rank))]

            out[route] = {
                "count": len(samples),
                "errors": self.errors[route],
                "p50_seconds": q(0.50),
                "p95_seconds": q(0.95),
                "p99_seconds": q(0.99),
                "max_seconds": samples[-1] if samples else None,
            }
        return out


# the host-crash tenants' large-state metric: a capacity MaskedBuffer whose
# appends only touch a few delta segments per checkpoint interval — the
# full-vs-delta bundle-bytes evidence the SLO reads
_CRASH_CAT_CAPACITY = 1 << 15


def _eligible_clean_guarded(schedule: TrafficSchedule, fraction: float) -> List[str]:
    """The "host B" tenant set: clean guarded tenants (fault surfaces stay on
    host A so their scenarios run unchanged through the deploy/crash)."""
    poisoned_tenants = set(schedule.poisoned())
    eligible = [t for t in schedule.guarded if t not in poisoned_tenants]
    n = max(1, int(round(len(eligible) * fraction)))
    return eligible[:n]


def _build_tenants(
    schedule: TrafficSchedule,
    config: ReplayConfig,
    engine: AlertEngine,
    dump_dir: str,
    crash_tenants: Tuple[str, ...] = (),
    ckpt_dir: Optional[str] = None,
    lease_seconds: Optional[float] = None,
):
    """(metrics, pipelines, mux, guarded_metric, crash_metric) keyed by tenant.

    Per-tenant pipeline sessions by default; with ``config.multiplex`` every
    guarded/hung tenant instead rides ONE cross-tenant multiplexer (shared
    fused programs, per-tenant state and robust isolation) and only the
    victim keeps a pipeline of its own. ``guarded_metric`` is returned so the
    rolling-deploy path can build same-spec restore targets and shadow
    controls; ``crash_metric`` builds the host-crash tenants' large-state
    ``CatMetric`` the same way. Host-crash tenants' pipelines carry the
    continuous :class:`~torchmetrics_tpu.engine.migrate.CheckpointPolicy`.
    """
    from torchmetrics_tpu.aggregation import CatMetric
    from torchmetrics_tpu.classification import MulticlassAccuracy
    from torchmetrics_tpu.engine.migrate import CheckpointPolicy
    from torchmetrics_tpu.engine.mux import MuxConfig, TenantMultiplexer
    from torchmetrics_tpu.engine.pipeline import MetricPipeline, PipelineConfig
    from torchmetrics_tpu.regression import MeanSquaredError

    def guarded_metric(tenant: str) -> Any:
        return MulticlassAccuracy(
            num_classes=schedule.config.num_classes,
            average="micro",
            validate_args=False,
            error_policy="quarantine",
            # the hung tenant's collective runs under the injected fault;
            # a 2-host world is claimed so Metric.sync enters the guard
            distributed_available_fn=(lambda: True) if tenant == schedule.hung else None,
        )

    def crash_metric() -> Any:
        # nan_strategy="disable" keeps the jitted (fusable) update path: the
        # crash tenants' streams are clean by selection, and the point is a
        # LARGE MaskedBuffer state whose periodic delta bundles only rewrite
        # the segments the appends touched
        return CatMetric(capacity=_CRASH_CAT_CAPACITY, nan_strategy="disable")

    metrics: Dict[str, Any] = {}
    pipelines: Dict[str, Any] = {}
    mux: Optional[TenantMultiplexer] = None
    if config.multiplex:
        mux = TenantMultiplexer(
            config=MuxConfig(
                max_width=config.mux_max_width,
                alert_engine=engine,
                alert_every=1,
                flight_records=64,
                flight_dump_dir=dump_dir,
            ),
            metrics={
                tenant: guarded_metric(tenant)
                for tenant in schedule.tenants
                if schedule.roles[tenant] != ROLE_VICTIM
            },
        )
        for tenant in mux.tenants():
            metrics[tenant] = mux.metric(tenant)
    for tenant in schedule.tenants:
        role = schedule.roles[tenant]
        if role != ROLE_VICTIM and mux is not None:
            continue  # multiplexed tenants built above
        checkpoint = None
        if role == ROLE_VICTIM:
            # deliberately unguarded: the NaN must REACH the value timeline so
            # the non-finite watchdog (not an input guard) is what catches it
            metric = MeanSquaredError()
        elif tenant in crash_tenants:
            metric = crash_metric()
            checkpoint = CheckpointPolicy(
                directory=os.path.join(ckpt_dir, tenant),
                every_batches=config.checkpoint_every_batches,
                full_every=4,
                keep=8,
                segment_bytes=4096,
            )
        else:
            metric = guarded_metric(tenant)
        metrics[tenant] = metric
        pipe_kwargs: Dict[str, Any] = {}
        if lease_seconds is not None and tenant in crash_tenants:
            # hung-host tenants lease short so stale-lease detection fits CI
            pipe_kwargs["lease_seconds"] = lease_seconds
        pipelines[tenant] = MetricPipeline(
            metric,
            PipelineConfig(
                fuse=config.fuse,
                max_in_flight=2,
                prefetch=1,
                tenant=tenant,
                alert_engine=engine,
                alert_every=1,
                flight_records=32,
                flight_dump_dir=dump_dir,
                checkpoint=checkpoint,
                **pipe_kwargs,
            ),
        )
    return metrics, pipelines, mux, guarded_metric, crash_metric


def _read_dump(path: str) -> Optional[Dict[str, Any]]:
    """The meta line of one flight dump (tenant, reason, poisoned batches)."""
    try:
        with open(path, encoding="utf-8") as fh:
            meta = json.loads(fh.readline())
    except (OSError, ValueError):
        return None
    if meta.get("type") != "meta":
        return None
    return {
        "path": path,
        "tenant": meta.get("tenant"),
        "reason": meta.get("reason"),
        "poisoned_batches": meta.get("poisoned_batches") or [],
        "poisoned_trace_ids": meta.get("poisoned_trace_ids") or [],
    }


def replay(schedule: TrafficSchedule, config: Optional[ReplayConfig] = None) -> Dict[str, Any]:
    """Execute ``schedule`` end to end; returns the plain-data measurement.

    The result dict carries everything :func:`torchmetrics_tpu.chaos.slo.judge`
    needs: wall/throughput totals, driver- and server-side scrape latencies,
    the alert transition history plus derived fire/resolve episodes, the
    injected-fault timeline (wall-stamped at injection), flight-dump metadata
    against the schedule's poisoned-batch ground truth, compiled-variant and
    compile-seconds deltas from the cost ledger, and the end-of-run health and
    tenant pages.
    """
    from unittest import mock

    import jax.numpy as jnp
    import numpy as np

    from torchmetrics_tpu.obs import cost as _cost
    from torchmetrics_tpu.obs import fleet as _fleet_mod
    from torchmetrics_tpu.obs import values as _values
    from torchmetrics_tpu.parallel import sync as _sync_mod
    from torchmetrics_tpu.robust import faults as _faults
    from torchmetrics_tpu.robust.degraded import sync_guard

    config = config or ReplayConfig()
    rng = np.random.RandomState(schedule.config.seed)
    # batch lineage is part of what a chaos run proves (the fault_causality
    # SLO): enable it for this run, restoring the prior enabled-state on
    # return. A caller that already runs with lineage on keeps its live index
    # (reset only when WE turned lineage on — clobbering a serving process's
    # /trace records to run a bench would be theft); per-session epochs keep
    # this run's ids collision-free either way.
    lineage_was_enabled = _lineage.ENABLED
    _lineage.enable(reset=not lineage_was_enabled)
    # the conservation audit plane (obs/audit.py): live for the run unless
    # forced off, so the accounting_clean SLO has evidence. Sessions register
    # their ledger hooks at construction — install BEFORE _build_tenants. The
    # caller's auditor (a serving process's) is restored on return.
    auditor: Optional[_audit.ConservationAuditor] = None
    auditor_prev: Optional[_audit.ConservationAuditor] = None
    if config.audit is not False:
        auditor = _audit.ConservationAuditor(
            cadence_seconds=max(0.05, config.scrape_interval_seconds)
        )
        auditor_prev = _audit.install_auditor(auditor)
    # an auto-created dump dir is consumed (metas read into the result) and
    # removed before returning — repeated replays must not litter the tempdir;
    # a caller-provided directory is theirs to keep
    own_dump_dir = config.flight_dump_dir is None
    dump_dir = config.flight_dump_dir or tempfile.mkdtemp(prefix="tm_tpu_chaos_")

    # host crash: "host B" gets the clean guarded tenants, re-metric'd onto a
    # large-state CatMetric with a continuous CheckpointPolicy; their fed
    # batches are retained so the post-restore replay gap can be re-fed from
    # the deterministic stream (seeded, so this IS the schedule's traffic)
    own_ckpt_dir = config.checkpoint_dir is None
    crash_tenants: List[str] = []
    ckpt_dir: Optional[str] = None
    if config.host_crash:
        crash_tenants = _eligible_clean_guarded(schedule, config.migrate_fraction)
        if not crash_tenants:
            raise ReplayError(
                "host_crash needs at least one clean guarded tenant to kill;"
                f" the schedule offers none (guarded={schedule.guarded},"
                f" poisoned={sorted(schedule.poisoned())})"
            )
        ckpt_dir = config.checkpoint_dir or tempfile.mkdtemp(prefix="tm_tpu_ckpt_")
    # hung host: "host B" gets the clean guarded tenants on leased continuous-
    # checkpoint pipelines (the same large-state CatMetric build as host_crash
    # — they ride the crash-tenant build path below); their fed batches are
    # retained so the post-failover gap + wedge-period traffic can be re-fed
    fence_tenants: List[str] = []
    if config.hung_host:
        fence_tenants = _eligible_clean_guarded(schedule, config.migrate_fraction)
        if not fence_tenants:
            raise ReplayError(
                "hung_host needs at least one clean guarded tenant to wedge;"
                f" the schedule offers none (guarded={schedule.guarded},"
                f" poisoned={sorted(schedule.poisoned())})"
            )
        ckpt_dir = config.checkpoint_dir or tempfile.mkdtemp(prefix="tm_tpu_ckpt_")

    rules = [
        AlertRule(
            name=POISON_RULE,
            kind="non_finite",
            metric="MeanSquaredError",
            tenant=schedule.victim,
            severity="critical",
        )
    ]
    if config.skewed_load or config.flash_crowd:
        # the declarative preset, armed BEFORE any load lands: detection must
        # come from the fleet samples alone through the standard pending→
        # firing machinery (dwell = 2 cadences, so one noisy sample never
        # pages). The rule name is obs.fleet's IMBALANCE_RULE contract.
        # The flash-crowd scenario arms it too: the page and the placement
        # controller read the SAME samples — paging is not suppressed just
        # because something is acting on the skew.
        rules.append(
            _fleet_mod.imbalance_rule(
                above=0.5,
                for_seconds=2 * config.fleet_cadence_seconds,
                severity="page",
            )
        )
    engine = AlertEngine(rules=rules, history=config.alert_history)
    metrics, pipelines, mux, guarded_metric, crash_metric = _build_tenants(
        schedule,
        config,
        engine,
        dump_dir,
        crash_tenants=tuple(crash_tenants) or tuple(fence_tenants),
        ckpt_dir=ckpt_dir,
        lease_seconds=config.lease_seconds if fence_tenants else None,
    )
    # the checkpoint liveness registry is process-global and tenant names are
    # deterministic: snapshot it NOW so this run's full-vs-delta evidence is a
    # delta against whatever earlier replays in this process recorded
    ckpt_baseline: Dict[str, Any] = {}
    if crash_tenants:
        import torchmetrics_tpu.obs.scope as _scope_mod

        ckpt_baseline = _scope_mod.checkpoint_status()
    victim, hung = schedule.victim, schedule.hung
    n_classes = schedule.config.num_classes

    # rolling deploy: "host B" gets half the CLEAN guarded tenants (the fault
    # surfaces — victim, hung, the poisoned guarded tenant — stay on host A so
    # their scenarios run unchanged THROUGH the deploy); each host-B tenant
    # also feeds a shadow control metric eagerly, the bit-identity oracle
    migrate_tenants: List[str] = []
    controls: Dict[str, Any] = {}
    if config.rolling_deploy:
        migrate_tenants = _eligible_clean_guarded(schedule, config.migrate_fraction)
        if not migrate_tenants:
            raise ReplayError(
                "rolling_deploy needs at least one clean guarded tenant to migrate;"
                f" the schedule offers none (guarded={schedule.guarded},"
                f" poisoned={sorted(set(schedule.poisoned()))})"
            )
        controls = {tenant: guarded_metric(tenant) for tenant in migrate_tenants}
    # the crash tenants' shadow controls: eager CatMetrics fed the identical
    # stream, the unkilled side of the end-of-run bit-identity proof
    controls.update({tenant: crash_metric() for tenant in crash_tenants})
    crash_set = set(crash_tenants)
    crash_history: Dict[str, List[tuple]] = {tenant: [] for tenant in crash_tenants}
    # the hung-host tenants' shadow controls: the no-hang world the failed-over
    # sessions must match bit-for-bit (zero double-counting, zero loss)
    controls.update({tenant: crash_metric() for tenant in fence_tenants})
    fence_set = set(fence_tenants)
    fence_history: Dict[str, List[tuple]] = {tenant: [] for tenant in fence_tenants}

    # flash-crowd only: controller-ordered moves run on scrape handler
    # threads (render_metrics ticks the controller), so a move's drain/swap
    # must be serialized against the schedule's feed loop — the replay's
    # stand-in for the serving process's per-session ownership
    flash_lock: Optional[threading.Lock] = (
        threading.Lock() if config.flash_crowd else None
    )

    def feed_tenant(tenant: str, *args: Any) -> None:
        if flash_lock is not None:
            with flash_lock:
                pipelines[tenant].feed(*args)
            return
        if mux is not None and tenant not in pipelines:
            mux.feed(tenant, *args)
        else:
            pipelines[tenant].feed(*args)

    def tenant_trace_id(tenant: str, index: int) -> str:
        """The lineage id of a tenant's ``index``-th fed batch — computable by
        the driver because ids are deterministic given the session epoch."""
        if mux is not None and tenant not in pipelines:
            return mux.trace_id_for(tenant, index)
        return pipelines[tenant].trace_id_for(index)

    def flush_tenant(tenant: str) -> None:
        if flash_lock is not None:
            with flash_lock:
                pipelines[tenant].flush()
            return
        if mux is not None and tenant not in pipelines:
            mux.flush()
        else:
            pipelines[tenant].flush()

    def make_batch(tenant: str, size: int, poison: bool) -> Tuple[Any, ...]:
        if tenant in crash_set or tenant in fence_set:
            # the host-crash/hung-host tenants drive single-array CatMetric
            # appends; their streams are clean by selection (no poison)
            return (jnp.asarray(rng.rand(size).astype(np.float32)),)
        if schedule.roles[tenant] == ROLE_VICTIM:
            preds = rng.rand(size).astype(np.float32)
            target = rng.rand(size).astype(np.float32)
        else:
            preds = rng.rand(size, n_classes).astype(np.float32)
            target = rng.randint(0, n_classes, size)
        if poison:
            preds = np.full_like(preds, np.nan)
        return jnp.asarray(preds), jnp.asarray(target)

    faults_injected: List[Dict[str, Any]] = []
    batches_fed = 0
    sleep_seconds = 0.0
    cost_mark = _cost.get_ledger().mark()
    server = IntrospectionServer(metrics=list(metrics.values()), port=0, alert_engine=engine)
    scraper: Optional[_Scraper] = None
    closed = False
    migration_info: Optional[Dict[str, Any]] = None
    migrate_at = len(schedule.events) // 2 if migrate_tenants else None
    bundle_dir = tempfile.mkdtemp(prefix="tm_tpu_migrate_") if migrate_tenants else None
    crash_info: Optional[Dict[str, Any]] = None
    crash_at = len(schedule.events) // 2 if crash_tenants else None
    fence_info: Optional[Dict[str, Any]] = None
    wedge_at = len(schedule.events) // 2 if fence_tenants else None
    # skewed load: a static placement concentrates every tenant but the last
    # onto virtual host "0"; the installed sampler's ticks ride the scraper's
    # /metrics pulls, so detection cadence IS the serving path's cadence. The
    # hot spot shifts (placement flips) two-thirds in — late enough that the
    # pre-shift skew had time to page, early enough to observe the re-point.
    fleet_info: Optional[Dict[str, Any]] = None
    fleet_sampler: Optional[Any] = None
    fleet_placement: Dict[str, str] = {}
    fleet_shift: Optional[Dict[str, Any]] = None
    fleet_shift_at: Optional[int] = None
    fleet_probe: Optional[Dict[str, Any]] = None
    fleet_history_n: Optional[int] = None
    if config.skewed_load:
        cold = set(schedule.tenants[-1:])
        fleet_placement = {
            tenant: ("1" if tenant in cold else "0") for tenant in schedule.tenants
        }
        fleet_sampler = _fleet_mod.FleetSampler(
            cadence_seconds=config.fleet_cadence_seconds,
            placement=dict(fleet_placement),
        )
        _fleet_mod.install_sampler(fleet_sampler)
        fleet_shift_at = (len(schedule.events) * 2) // 3
    # flash crowd: every tenant seeded on host "0" under a live placement
    # controller whose reconcile ticks ride the scraper's /metrics pulls —
    # the controller must notice the measured skew and fix it with real
    # session moves, twice (at `repair` the schedule shifts the hot spot AND
    # the second wave of the crowd re-lands concentrated on host "0")
    placement_info: Optional[Dict[str, Any]] = None
    placement_controller: Optional[Any] = None
    placement_prev: Optional[Any] = None
    placement_probe: Optional[Dict[str, Any]] = None
    placement_restored_from_disk = False
    flash_streams: Dict[str, List[Tuple[Any, ...]]] = {}
    flash_shift_wall: Optional[float] = None
    flash_settle_sweeps = 0
    # driver-side record of every tenant the mover physically relocated: the
    # assignment table's per-row `moves` counters reset when the shift-time
    # re-seed adopts the second wave's placement, so the zero-loss verdict
    # keys off this set, not the table
    flash_moved: set = set()
    flash_dir: Optional[str] = None
    if config.flash_crowd:
        from torchmetrics_tpu import fleet as _placement_mod

        # the flash crowd arrives concentrated: EVERY tenant starts on host
        # "0", so the first measured imbalance is 1.0 and the seeded table is
        # maximally wrong on purpose
        fleet_placement = dict.fromkeys(schedule.tenants, "0")
        fleet_sampler = _fleet_mod.FleetSampler(
            cadence_seconds=config.fleet_cadence_seconds,
            placement=dict(fleet_placement),
            # the provisioned universe: host "1" is idle at t=0 (the whole
            # crowd lands on "0") and must still count in the skew math —
            # without it the concentrated fleet reads as balanced
            hosts=("0", "1"),
        )
        _fleet_mod.install_sampler(fleet_sampler)
        flash_dir = tempfile.mkdtemp(prefix="tm_tpu_rebalance_")

        def flash_mover(tenant: str, from_host: str, to_host: str) -> bool:
            """One controller-ordered move, executed on whatever scrape
            handler thread ticked the controller: the live-session handoff
            (drain → checkpoint → restore → swap the serving surface) —
            the same sequence the rolling deploy runs, here chosen by the
            control plane instead of an operator."""
            from torchmetrics_tpu.engine import migrate as _migrate

            with flash_lock:
                old_pipe = pipelines.get(tenant)
                if old_pipe is None:
                    raise ReplayError(
                        f"placement mover asked to move unknown tenant {tenant!r}"
                    )
                bundle = os.path.join(flash_dir, f"move-{tenant}-{len(os.listdir(flash_dir))}")
                _migrate.checkpoint_session(old_pipe, bundle, alert_engine=engine)
                old_pipe.close()
                fresh = guarded_metric(tenant)
                new_pipe, _manifest = _migrate.restore_session(
                    fresh, bundle, alert_engine=engine
                )
                pipelines[tenant] = new_pipe
                server.unregister(metrics[tenant])
                metrics[tenant] = fresh
                server.register(fresh)
                flash_moved.add(tenant)
            return True

        placement_config = _placement_mod.PlacementConfig(
            hosts=("0", "1"),
            cadence_seconds=config.placement_cadence_seconds,
            max_concurrent_moves=config.placement_max_moves,
            state_path=os.path.join(flash_dir, "placement.json"),
            # operator pins on the fault surfaces: the victim's session is a
            # different metric class than the guarded factory restores, and
            # the poisoned tenant's repair resets its state mid-run — both
            # are exactly the "drain/restore known-unsafe" sessions the pin
            # knob exists for
            pinned=(victim,) + tuple(sorted(schedule.poisoned())),
        )
        if config.placement_enabled:
            # the durable-restore proof is folded into every run: a throwaway
            # controller seeds + persists the all-on-"0" table, then the LIVE
            # controller reconstructs its assignment table from that state
            # file — the restart path, not a fresh in-memory table
            _placement_mod.PlacementController(placement_config).seed(fleet_placement)
            placement_controller = _placement_mod.PlacementController(
                placement_config, mover=flash_mover
            )
            placement_restored_from_disk = bool(
                placement_controller.assignments()
            ) and all(
                placement_controller.lookup(tenant) == "0"
                for tenant in schedule.tenants
            )
            placement_prev = _placement_mod.install_controller(placement_controller)
    # zombie sessions after the wedge (still live objects — a hung host is not
    # a dead one) and the failovers the scrape-driven watchdog completes
    # (appended from the scraper thread; list.append is atomic)
    zombies: Dict[str, Any] = {}
    failover_swaps: List[Tuple[Any, Dict[str, Any]]] = []

    def kill_host_b_sigkill() -> Dict[str, Any]:
        """The unplanned death: host B dies with SIGKILL semantics.

        No drain, no close, no final checkpoint — the crashed pipelines are
        simply abandoned mid-flight, so batches in their open fusion chunks
        are LOST. The supervisor restart then recovers each tenant from
        :func:`~torchmetrics_tpu.engine.migrate.latest_valid_bundle` (a
        planted torn mid-write bundle proves the scan skips garbage), re-feeds
        the replay gap from the retained deterministic stream, and re-attaches
        the restored session (checkpoint policy included — the bundle stream
        continues past the crash). The measured gap and recovery wall time are
        what the host-crash SLO spec judges.
        """
        from torchmetrics_tpu.engine import migrate as _migrate
        from torchmetrics_tpu.engine.migrate import CheckpointPolicy

        fed_at_crash = {tenant: len(crash_history[tenant]) for tenant in crash_tenants}
        for tenant in crash_tenants:
            # SIGKILL: the session object is dropped where it stands
            pipelines.pop(tenant)
            server.unregister(metrics[tenant])
        # a torn mid-write artifact next to the first victim's stream: the
        # recovery scan must skip it (loudly) and restore from the intact link
        planted = os.path.join(ckpt_dir, crash_tenants[0], "bundle-999999")
        os.makedirs(planted, exist_ok=True)
        with open(os.path.join(planted, "state.npz"), "wb") as fh:
            fh.write(b"\x00torn-mid-write")
        sessions: Dict[str, Dict[str, Any]] = {}
        start = time.perf_counter()
        for tenant in crash_tenants:
            tenant_dir = os.path.join(ckpt_dir, tenant)
            bundle = _migrate.latest_valid_bundle(tenant_dir)
            if bundle is None:
                raise ReplayError(
                    f"no intact bundle under {tenant_dir} for crashed tenant {tenant!r}"
                )
            fresh = crash_metric()
            new_pipe, manifest = _migrate.restore_session(
                fresh,
                bundle,
                alert_engine=engine,
                # the restored session keeps checkpointing into the same
                # stream (the checkpointer seeds its sequence past the
                # existing bundles instead of clobbering the chain)
                checkpoint=CheckpointPolicy(
                    directory=tenant_dir,
                    every_batches=config.checkpoint_every_batches,
                    full_every=4,
                    keep=8,
                    segment_bytes=4096,
                ),
            )
            cursor = int((manifest.get("cursor") or {}).get("batches_ingested", 0) or 0)
            gap = fed_at_crash[tenant] - cursor
            for args in crash_history[tenant][cursor : fed_at_crash[tenant]]:
                new_pipe.feed(*args)
            pipelines[tenant] = new_pipe
            metrics[tenant] = fresh
            server.register(fresh)
            sessions[tenant] = {
                "fed_at_crash": fed_at_crash[tenant],
                "restored_cursor": cursor,
                "replay_gap_batches": gap,
                "bundle": os.path.basename(bundle),
            }
        recovery_seconds = time.perf_counter() - start
        return {
            "tenants": list(crash_tenants),
            "cadence_batches": config.checkpoint_every_batches,
            "recovery_seconds": round(recovery_seconds, 6),
            "replay_gap_batches": max(row["replay_gap_batches"] for row in sessions.values()),
            "sessions": sessions,
            # the planted torn bundle was never chosen as a restore point
            "torn_bundle_skipped": all(
                row["bundle"] != "bundle-999999" for row in sessions.values()
            ),
        }

    def wedge_host_b() -> Dict[str, Any]:
        """The hung host: host B wedges mid-traffic — alive but silent.

        No drain, no close, no lease release: the sessions are popped off the
        serving set with their objects (and leases) intact, which is exactly
        what distinguishes a hang from a crash — the zombie can still write.
        A scrape-driven :class:`~torchmetrics_tpu.robust.fence.Watchdog` is
        installed watching each wedged tenant's bundle stream; the background
        scraper's ``/metrics`` pulls drive its ticks, so detection + failover
        ride the production observation path, not a bespoke timer. The
        survivor's guarded collective with the hung host is also exercised:
        under the injected hanging-collective fake it must time out and
        degrade loudly instead of hanging the run.
        """
        from torchmetrics_tpu.engine.migrate import CheckpointPolicy
        from torchmetrics_tpu.robust import fence as _fence_mod

        wedge_unix = time.time()
        for tenant in fence_tenants:
            zombies[tenant] = pipelines.pop(tenant)
        # the survivor's collective with the hung host: guarded, so it times
        # out and degrades loudly (sync_degraded) instead of wedging the run
        probe = metrics[fence_tenants[0]]
        with mock.patch.object(_sync_mod, "distributed_available", lambda: True):
            with sync_guard(timeout=config.sync_timeout_seconds, retries=0):
                with _faults.inject_collective_fault(mode="hang", times=99):
                    try:
                        probe.sync()
                    except Exception:
                        pass  # raise-path builds still mean "degraded"
        watchdog = _fence_mod.Watchdog(
            on_failover=lambda pipe, report: failover_swaps.append((pipe, report))
        )
        for tenant in fence_tenants:
            tenant_dir = os.path.join(ckpt_dir, tenant)
            watchdog.watch(
                tenant,
                tenant_dir,
                crash_metric,
                config=_fence_mod.WatchdogConfig(
                    # both halves of detection: the lease must be past TTL AND
                    # the bundle stream must be provably stale (a host whose
                    # renewals are lost but whose bundles still land is slow,
                    # not hung)
                    require_checkpoint_stale=True,
                    restore_overrides={
                        "alert_engine": engine,
                        "checkpoint": CheckpointPolicy(
                            directory=tenant_dir,
                            every_batches=config.checkpoint_every_batches,
                            full_every=4,
                            keep=8,
                            segment_bytes=4096,
                        ),
                    },
                ),
            )
        _fence_mod.install_watchdog(watchdog)
        return {
            "tenants": list(fence_tenants),
            "lease_seconds": config.lease_seconds,
            "wedge_unix": wedge_unix,
            "fed_at_wedge": {t: len(fence_history[t]) for t in fence_tenants},
            "degraded_collective": bool(getattr(probe, "sync_degraded", False)),
        }

    def finish_failover(base: Dict[str, Any]) -> Dict[str, Any]:
        """Wait for the scrape-driven failovers, prove zombie rejection,
        re-feed the gap + wedge-period traffic into the restored sessions."""
        import torchmetrics_tpu.obs.scope as _scope_mod
        from torchmetrics_tpu.engine import migrate as _migrate

        deadline = time.monotonic() + 30.0
        while len(failover_swaps) < len(fence_tenants) and time.monotonic() < deadline:
            time.sleep(0.02)
        if len(failover_swaps) < len(fence_tenants):
            raise ReplayError(
                f"the scrape-driven watchdog failed over {len(failover_swaps)}"
                f"/{len(fence_tenants)} hung tenant(s) within 30s (lease"
                f" {config.lease_seconds}s, scrape every"
                f" {config.scrape_interval_seconds}s)"
            )
        reports = {report["tenant"]: report for _, report in failover_swaps}
        # zombie write-rejection proof, BEFORE the restored sessions write any
        # bundles of their own: the zombie's late bundle must LAND on disk
        # (the write path is the zombie's own view — it cannot know it is
        # fenced) and then be rejected, counted, and never selected by the
        # next recovery scan
        zt = fence_tenants[0]
        rejected_before = _scope_mod.fenced_rejected_count()
        zombie_bundle = zombies[zt].checkpoint_now()
        selected = _migrate.latest_valid_bundle(os.path.join(ckpt_dir, zt))
        rejected_delta = _scope_mod.fenced_rejected_count() - rejected_before
        zombie_name = os.path.basename(zombie_bundle) if zombie_bundle else None
        selected_name = os.path.basename(selected) if selected else None
        zombie_info = {
            "tenant": zt,
            "bundle": zombie_name,
            "landed": bool(zombie_bundle and os.path.isdir(zombie_bundle)),
            "rejected_count": rejected_delta,
            "selected": selected_name,
            "discarded": bool(
                zombie_name is not None
                and rejected_delta >= 1
                and selected_name is not None
                and selected_name != zombie_name
            ),
        }
        # hand each tenant to its restored session: swap the serving surface,
        # then close the gap — everything from the restore point's cursor
        # through the wedge-period backlog, replayed from the retained stream
        sessions: Dict[str, Any] = {}
        detect_max = failover_max = 0.0
        for pipe, report in failover_swaps:
            tenant = report["tenant"]
            cursor = int(report.get("restored_cursor") or 0)
            for args in fence_history[tenant][cursor:]:
                pipe.feed(*args)
            server.unregister(metrics[tenant])
            metrics[tenant] = pipe.metric
            server.register(pipe.metric)
            pipelines[tenant] = pipe
            detect = max(0.0, report["detected_unix"] - base["wedge_unix"])
            detect_max = max(detect_max, detect)
            failover_max = max(failover_max, float(report["failover_seconds"]))
            sessions[tenant] = {
                "fed_at_wedge": base["fed_at_wedge"][tenant],
                "restored_cursor": cursor,
                "refed_batches": len(fence_history[tenant]) - cursor,
                "fenced_epoch": report["fenced_epoch"],
                "new_epoch": report["new_epoch"],
                "bundle": os.path.basename(report["bundle"]),
                "detect_seconds": round(detect, 6),
                "failover_seconds": round(float(report["failover_seconds"]), 6),
            }
        # operator visibility: /healthz must be degraded with every fenced
        # tenant NAMED (plus its failover target), and /leases must carry the
        # fence ledger — probed deterministically, not left to scraper luck
        healthz_named = False
        leases_fences = 0
        try:
            with urllib.request.urlopen(server.url + "/healthz", timeout=10) as resp:
                payload = json.loads(resp.read())
            healthz_named = payload.get("status") == "degraded" and all(
                tenant in (payload.get("tenants_fenced") or {})
                for tenant in fence_tenants
            )
            with urllib.request.urlopen(server.url + "/leases", timeout=10) as resp:
                leases_fences = len((json.loads(resp.read()) or {}).get("fences") or {})
        except Exception:
            pass  # visibility is judged; a missed probe fails the SLO
        return {
            **base,
            "time_to_detect_seconds": round(detect_max, 6),
            "time_to_failover_seconds": round(failover_max, 6),
            "sessions": sessions,
            "zombie": zombie_info,
            "healthz_named_fenced": healthz_named,
            "leases_page_fences": leases_fences,
        }

    def kill_host_b() -> Dict[str, Any]:
        """The rolling deploy: host B dies; its sessions move to the survivor.

        Per migrated tenant: drain → checkpoint (atomic bundle) → the dying
        host's pipeline closes → restore onto a fresh same-spec metric →
        replay-tail. A /healthz probe mid-handoff records whether the
        migration was operator-visible (degraded, tenant NAMED) — the
        deterministic observation the SLO judges, independent of the
        background scraper's timing luck.
        """
        import json as _json

        import torchmetrics_tpu.obs.scope as _scope_mod
        from torchmetrics_tpu.engine import migrate as _migrate

        healthz_named = False
        start = time.perf_counter()
        for tenant in migrate_tenants:
            old_pipe = pipelines[tenant]
            with _scope_mod.migration(tenant, "rolling_deploy"):
                bundle = os.path.join(bundle_dir, tenant)
                _migrate.checkpoint_session(old_pipe, bundle, alert_engine=engine)
                try:
                    with urllib.request.urlopen(server.url + "/healthz", timeout=10) as resp:
                        payload = _json.loads(resp.read())
                    if payload.get("status") == "degraded" and tenant in (
                        payload.get("tenants_migrating") or {}
                    ):
                        healthz_named = True
                except Exception:
                    pass  # visibility is judged; a missed probe fails the SLO
                old_pipe.close()  # host B's session ends
                fresh = guarded_metric(tenant)
                new_pipe, _manifest = _migrate.restore_session(
                    fresh, bundle, alert_engine=engine
                )
                pipelines[tenant] = new_pipe
                # the dead host's instance leaves the serving surface with its
                # session: /metrics, /healthz and /memory must not keep a
                # stale duplicate frozen at checkpoint-time values
                server.unregister(metrics[tenant])
                metrics[tenant] = fresh
                server.register(fresh)
        return {
            "tenants": list(migrate_tenants),
            "migration_seconds": round(time.perf_counter() - start, 6),
            "healthz_named_migrating": healthz_named,
            "bundles": len(migrate_tenants),
        }

    def shift_hot_spot() -> Dict[str, Any]:
        """Mid-run hot-spot shift + wedged-gather probe (``skewed_load`` only).

        The load concentration MOVES: every placement host label flips, so
        the tenants that made host "0" hot now make host "1" hot. Nothing
        tells the alert plane — ``fleet.imbalance`` is deliberately one
        unlabeled series, so the already-firing page must follow the new hot
        host (named live by ``/healthz`` from the skew block) instead of
        stranding a stale per-host labelset. Immediately after the flip one
        sample is forced under the hanging-collective fake — a claimed
        2-host world (the ``_host_meta`` seam) whose allgather hangs — and
        must come back as a LOUD degraded partial sample naming the missing
        peer within the sync guard's budget, never a stalled sampler.
        """
        # hot-host verdicts smooth over ~10 cadences: adjacent-sample rates
        # are twitchy (one quiet tick can momentarily crown the cold host)
        before = fleet_sampler.skew(
            window=10 * config.fleet_cadence_seconds
        ).get("hot_host")
        fleet_sampler.placement = {
            tenant: ("0" if host == "1" else "1")
            for tenant, host in fleet_sampler.placement.items()
        }
        shifted_at = time.time()
        wedge_started = time.perf_counter()
        with mock.patch.object(
            _trace,
            "_host_meta",
            lambda: {"process_index": 0, "process_count": 2, "host_id": "chaos-host-a:0"},
        ):
            with mock.patch.object(_sync_mod, "distributed_available", lambda: True):
                with sync_guard(timeout=config.sync_timeout_seconds, retries=0):
                    with _faults.inject_collective_fault(mode="hang", times=99):
                        degraded = fleet_sampler.sample()
        return {
            "hot_host_before": before,
            "shifted_at": shifted_at,
            "wedged_sample": {
                "degraded": bool(degraded.get("degraded")),
                "missing_hosts": list(degraded.get("missing_hosts") or []),
                "sample_seconds": round(time.perf_counter() - wedge_started, 6),
            },
        }

    profiler: Optional[_hostprof.HostProfiler] = None
    profiler_prev: Optional[_hostprof.HostProfiler] = None
    profile_probe: Optional[Dict[str, Any]] = None
    profile_probe_at = max(1, len(schedule.events) // 2)
    try:
        with _trace.observe(max_events=config.max_events):
            server.start()
            scrape_routes = tuple(config.scrape_routes)
            if (config.skewed_load or config.flash_crowd) and "/fleet" not in scrape_routes:
                # the control-plane read API is scraped throughout: /fleet
                # latency rides the same per-route SLO stats as /metrics
                scrape_routes += ("/fleet",)
            if config.flash_crowd and "/placement" not in scrape_routes:
                # the placement table/decision-log API is scraped throughout
                # too — reading the control plane must stay cheap WHILE it is
                # moving sessions, and its latency is judged like /metrics
                scrape_routes += ("/placement",)
            scraper = _Scraper(
                server.url, scrape_routes, config.scrape_interval_seconds
            )
            scraper.start()
            if config.hostprof or (config.hostprof is None and config.multiplex):
                # the continuous host profiler rides the replay: sampling is
                # live through the fault window, the per-seam breakdown and
                # floor report land in the run record, and GET /profile is
                # probed MID-RUN below — live attribution, not a post-mortem
                profiler = _hostprof.HostProfiler(rate_hz=config.hostprof_rate_hz)
                profiler_prev = _hostprof.install(profiler)
                profiler.start()
            wall_start, perf_start = time.time(), time.perf_counter()
            with warnings.catch_warnings():
                # degrade/quarantine warnings are the *expected* output of a
                # chaos run; their counts land in the result, not on stderr
                warnings.simplefilter("ignore")
                for ev_index, ev in enumerate(schedule.events):
                    if migrate_at is not None and ev_index >= migrate_at:
                        migration_info = kill_host_b()
                        migrate_at = None  # one deploy per run
                    if crash_at is not None and ev_index >= crash_at:
                        crash_info = kill_host_b_sigkill()
                        crash_at = None  # one crash per run
                    if wedge_at is not None and ev_index >= wedge_at:
                        fence_info = wedge_host_b()
                        wedge_at = None  # one hang per run
                    if fleet_shift_at is not None and ev_index >= fleet_shift_at:
                        fleet_shift = shift_hot_spot()
                        fleet_shift_at = None  # one shift per run
                    if profiler is not None and profile_probe is None and ev_index >= profile_probe_at:
                        # the live mid-run GET /profile: the host-vs-XLA
                        # floor split must be servable while the run is
                        # still feeding, not only in the post-hoc record
                        try:
                            with urllib.request.urlopen(
                                server.url + "/profile?top=5", timeout=10
                            ) as resp:
                                page = json.loads(resp.read())
                            profile_probe = {
                                "at_event": ev_index,
                                "running": page.get("running"),
                                "samples": page.get("samples"),
                                "self_overhead_percent": page.get("self_overhead_percent"),
                                "attributed_percent": page.get("attributed_percent"),
                                "mux_floor": ((page.get("floor") or {}).get("paths") or {}).get("mux"),
                            }
                        except Exception:
                            profile_probe = None  # retried at the next event
                    kind = ev["kind"]
                    if kind == "batch":
                        tenant = ev["tenant"]
                        if tenant in fence_set:
                            # retained for the post-failover re-feed; while
                            # host B is wedged its traffic cannot land — the
                            # shadow control (the no-hang world) still folds
                            # it, and the restored session catches up later
                            batch_args = make_batch(tenant, ev["size"], False)
                            fence_history[tenant].append(batch_args)
                            controls[tenant].update(*batch_args)
                            if tenant not in zombies:
                                feed_tenant(tenant, *batch_args)
                                batches_fed += 1
                            continue
                        if ev.get("poison") and tenant == victim:
                            faults_injected.append(
                                {
                                    "fault": "poison",
                                    "tenant": tenant,
                                    "rule": POISON_RULE,
                                    "injected_at": time.time(),
                                    "batch_index": ev["index"],
                                }
                            )
                        batch_args = make_batch(tenant, ev["size"], bool(ev.get("poison")))
                        if config.flash_crowd and tenant != victim:
                            # retained: a moved tenant's unmoved shadow
                            # control is rebuilt from this exact stream at end
                            # of run (the bit-identity side of zero-loss)
                            flash_streams.setdefault(tenant, []).append(batch_args)
                        if tenant in crash_set:
                            # retained so the post-restore replay gap can be
                            # re-fed exactly (the stream is seeded — this IS
                            # the deterministic traffic schedule's data)
                            crash_history[tenant].append(batch_args)
                        feed_tenant(tenant, *batch_args)
                        if ev.get("poison"):
                            # the poisoned batch's OWN lineage record is the
                            # causal anchor: time-to-fire is measured from its
                            # ingest stamp (not the pre-feed wall stamp), and
                            # the trace id rides the fault row so the SLO
                            # judge and /trace read the same identity
                            poison_tid = tenant_trace_id(tenant, ev["index"])
                            poison_rec = _lineage.lookup(poison_tid)
                            if tenant == victim and faults_injected:
                                fault_row = faults_injected[-1]
                                if fault_row.get("fault") == "poison":
                                    fault_row["trace_id"] = poison_tid
                                    if poison_rec is not None:
                                        fault_row["injected_at"] = poison_rec[
                                            "ingest_unix"
                                        ]
                        if tenant in controls:
                            # the shadow control folds the identical batch
                            # eagerly — the unmigrated side of the
                            # bit-identity proof
                            controls[tenant].update(*batch_args)
                        batches_fed += 1
                    elif kind == "sleep":
                        sleep_seconds += ev["seconds"]
                        time.sleep(ev["seconds"])
                    elif kind == "arm":
                        if "hang_absent" in ev.get("rules", ()):
                            engine.add_rule(
                                name=HANG_RULE,
                                kind="absent",
                                metric="*",
                                tenant=hung,
                                max_age_seconds=schedule.config.absent_after_seconds,
                                severity="critical",
                            )
                    elif kind == "hang_start":
                        # freshen the hung tenant's value timeline and settle
                        # the watchdog BEFORE stamping the injection: an
                        # absence that began during an earlier idle gap must
                        # not be credited to this hang window (time-to-fire
                        # would otherwise measure the schedule, not the alert)
                        flush_tenant(ev["tenant"])
                        _values.sample_local(metrics[ev["tenant"]], log=engine._log())
                        engine.evaluate()
                        faults_injected.append(
                            {
                                "fault": "hang",
                                "tenant": ev["tenant"],
                                "rule": HANG_RULE,
                                "injected_at": time.time(),
                                "window_seconds": ev.get("seconds"),
                            }
                        )
                        # the hanging-collective fake: a 2-host world is
                        # claimed at the module seam (the _obs_demo pattern —
                        # the injected hang raises before any real allgather
                        # could run), then the guarded eager sync parks until
                        # the guard's timeout and degrades loudly. times=99
                        # covers every per-leaf collective — a partially-hung
                        # sync that quietly completed its remaining leaves
                        # would not be a hung host
                        with mock.patch.object(_sync_mod, "distributed_available", lambda: True):
                            with sync_guard(timeout=config.sync_timeout_seconds, retries=0):
                                with _faults.inject_collective_fault(mode="hang", times=99):
                                    try:
                                        metrics[ev["tenant"]].sync()
                                    except Exception:
                                        pass  # raise-path builds still mean "degraded"
                    elif kind == "hang_end":
                        for fault in faults_injected:
                            if fault["fault"] == "hang" and "ended_at" not in fault:
                                fault["ended_at"] = time.time()
                    elif kind == "repair":
                        fault_tenant = ev["tenant"]
                        flush_tenant(fault_tenant)
                        metrics[fault_tenant].reset()
                        for fault in faults_injected:
                            if fault["tenant"] == fault_tenant and fault["fault"] == "poison":
                                fault.setdefault("repaired_at", time.time())
                        if config.flash_crowd and flash_shift_wall is None:
                            # the schedule's hot-spot shift rides the repair
                            # event: from here the drain traffic belongs to
                            # hot set B — and the SECOND WAVE of the crowd
                            # lands exactly like the first, concentrated on
                            # host "0". The re-seed below is the operator
                            # surface for that re-landing (a redeploy that
                            # pins everything back to the primary): without
                            # it, pre-shift convergence can happen to leave
                            # hot set B already split across hosts, the
                            # post-shift table is legitimately balanced, and
                            # a correct controller would (rightly) never
                            # move again — re-convergence must be FORCED to
                            # be provable
                            flash_shift_wall = time.time()
                            if placement_controller is not None:
                                placement_controller.seed(
                                    dict.fromkeys(schedule.tenants, "0")
                                )
                    else:  # pragma: no cover - generate()/loads() only emit known kinds
                        raise ReplayError(f"unknown schedule event kind {kind!r}")
                if config.flash_crowd and placement_controller is not None:
                    # settle loop: the schedule has ended but the controller
                    # converges on its own cadence. Convergence is judged
                    # UNDER LOAD, not during decay-to-idle — keep the
                    # post-shift traffic shape flowing until the table has
                    # answered the hot-spot shift with at least one clean
                    # move and closed the imbalance episode, or the hard
                    # deadline passes and the SLO judge flunks convergence
                    settle_deadline = time.monotonic() + 30.0
                    hot_b = set(schedule.hot_tenants_shifted)
                    sweep_size = schedule.config.batch_sizes[0]
                    while time.monotonic() < settle_deadline:
                        rep = placement_controller.report()
                        settled = (
                            not rep["convergence"]["episode_open"]
                            and not rep["moving"]
                            and flash_shift_wall is not None
                            and any(
                                row.get("action") == "move"
                                and row.get("ok")
                                and row.get("unix", 0.0) >= flash_shift_wall
                                for row in rep["decisions"]
                            )
                        )
                        if settled:
                            break
                        # feed cap: past ~150 sweeps keep polling but stop
                        # feeding — a run that hasn't settled by then is
                        # already flunking convergence, and an unbounded
                        # sweep flood would evict the poisoned batches'
                        # records from the bounded lineage ring and take the
                        # causality verdict down as collateral
                        if flash_settle_sweeps < 150:
                            for tenant in schedule.tenants:
                                if tenant == victim:
                                    continue
                                repeats = (
                                    schedule.config.hot_factor
                                    if tenant in hot_b
                                    else 1
                                )
                                for _ in range(repeats):
                                    sweep_args = make_batch(
                                        tenant, sweep_size, False
                                    )
                                    flash_streams.setdefault(tenant, []).append(
                                        sweep_args
                                    )
                                    if tenant in controls:
                                        controls[tenant].update(*sweep_args)
                                    feed_tenant(tenant, *sweep_args)
                                    batches_fed += 1
                        flash_settle_sweeps += 1
                        time.sleep(config.scrape_interval_seconds)
                if fence_info is not None:
                    fence_info = finish_failover(fence_info)
                for pipe in pipelines.values():
                    pipe.close()
                if mux is not None:
                    mux.close()
                closed = True
                engine.evaluate()
                # one stitched GET /trace/<id> of an injected NaN batch,
                # fetched over HTTP while the server is still up — the CI
                # artifact proving the lookup plane answers end to end
                sample_trace = None
                sample_trace_id = next(
                    (
                        fault.get("trace_id")
                        for fault in faults_injected
                        if fault.get("fault") == "poison" and fault.get("trace_id")
                    ),
                    None,
                )
                if sample_trace_id is not None:
                    try:
                        with urllib.request.urlopen(
                            server.url + "/trace/" + sample_trace_id, timeout=10
                        ) as resp:
                            sample_trace = json.loads(resp.read())
                    except Exception:
                        sample_trace = None
                if migration_info is not None:
                    # the zero-loss verdict: every migrated session's final
                    # compute must be BIT-identical to its unmigrated shadow
                    control_rows: Dict[str, Any] = {}
                    for tenant in migrate_tenants:
                        restored_val = np.asarray(metrics[tenant].compute())
                        control_val = np.asarray(controls[tenant].compute())
                        control_rows[tenant] = {
                            "restored": float(restored_val),
                            "control": float(control_val),
                            "bit_identical": bool(
                                restored_val.dtype == control_val.dtype
                                and restored_val.tobytes() == control_val.tobytes()
                            ),
                        }
                    migration_info["controls"] = control_rows
                    migration_info["zero_loss"] = all(
                        row["bit_identical"] for row in control_rows.values()
                    )
                if crash_info is not None:
                    # the crash-consistency verdict: every recovered session's
                    # final compute must be BIT-identical to its unkilled
                    # shadow control (the replay gap was re-fed, so no loss)
                    crash_rows: Dict[str, Any] = {}
                    for tenant in crash_tenants:
                        restored_val = np.asarray(metrics[tenant].compute())
                        control_val = np.asarray(controls[tenant].compute())
                        crash_rows[tenant] = {
                            "dtype": str(restored_val.dtype),
                            "items": int(restored_val.size),
                            "bit_identical": bool(
                                restored_val.dtype == control_val.dtype
                                and restored_val.tobytes() == control_val.tobytes()
                            ),
                        }
                    crash_info["controls"] = crash_rows
                    crash_info["zero_loss"] = all(
                        row["bit_identical"] for row in crash_rows.values()
                    )
                    # full-vs-delta bundle-bytes evidence, read back from the
                    # checkpoint liveness registry (it outlives the crashed
                    # session objects; the same numbers feed the
                    # checkpoint.bundle_bytes gauge the scrapes exported)
                    import torchmetrics_tpu.obs.scope as _scope_mod

                    status = _scope_mod.checkpoint_status()
                    ck_rows: Dict[str, Any] = {}
                    full_bytes = full_count = delta_bytes = delta_count = 0
                    for tenant in crash_tenants:
                        row = status.get(tenant) or {}
                        base = ckpt_baseline.get(tenant) or {}
                        bundles = {
                            kind: (row.get("bundles") or {}).get(kind, 0)
                            - (base.get("bundles") or {}).get(kind, 0)
                            for kind in ("full", "delta")
                        }
                        nbytes = {
                            kind: (row.get("bytes") or {}).get(kind, 0)
                            - (base.get("bytes") or {}).get(kind, 0)
                            for kind in ("full", "delta")
                        }
                        ck_rows[tenant] = {
                            "bundles": dict(bundles),
                            "bytes": dict(nbytes),
                            "failures": row.get("failures", 0) - base.get("failures", 0),
                        }
                        full_count += bundles.get("full", 0)
                        full_bytes += nbytes.get("full", 0)
                        delta_count += bundles.get("delta", 0)
                        delta_bytes += nbytes.get("delta", 0)
                    full_mean = full_bytes / full_count if full_count else None
                    delta_mean = delta_bytes / delta_count if delta_count else None
                    crash_info["checkpoints"] = {
                        "per_tenant": ck_rows,
                        "full_bundles": full_count,
                        "delta_bundles": delta_count,
                        "full_bytes_mean": full_mean,
                        "delta_bytes_mean": delta_mean,
                        "delta_full_ratio": (
                            delta_mean / full_mean if full_mean and delta_mean is not None else None
                        ),
                    }
                if fence_info is not None:
                    # the zero-double-counting verdict: every failed-over
                    # session's final compute must be BIT-identical to its
                    # never-hung shadow control — the zombie contributed
                    # nothing past the fence, the successor missed nothing
                    fence_rows: Dict[str, Any] = {}
                    for tenant in fence_tenants:
                        restored_val = np.asarray(metrics[tenant].compute())
                        control_val = np.asarray(controls[tenant].compute())
                        fence_rows[tenant] = {
                            "dtype": str(restored_val.dtype),
                            "items": int(restored_val.size),
                            "bit_identical": bool(
                                restored_val.dtype == control_val.dtype
                                and restored_val.tobytes() == control_val.tobytes()
                            ),
                        }
                    fence_info["controls"] = fence_rows
                    fence_info["zero_double_count"] = all(
                        row["bit_identical"] for row in fence_rows.values()
                    )
                if fleet_sampler is not None:
                    # one final forced sample (the scrape loop may have just
                    # gone idle), then the end-of-run control-plane probes:
                    # the /fleet payload an operator would actually read, and
                    # the bounded-history depth — both over real HTTP
                    fleet_sampler.sample()
                    try:
                        with urllib.request.urlopen(
                            server.url + "/fleet", timeout=10
                        ) as resp:
                            fleet_probe = json.loads(resp.read())
                    except Exception:
                        fleet_probe = None  # visibility is judged; a missed probe fails the SLO
                    try:
                        with urllib.request.urlopen(
                            server.url + "/fleet/history?window=600", timeout=10
                        ) as resp:
                            fleet_history_n = json.loads(resp.read()).get("n_samples")
                    except Exception:
                        fleet_history_n = None
                if config.flash_crowd:
                    # the placement verdict. Three proofs are assembled here:
                    # the read plane answered over real HTTP while the run was
                    # still live; every controller-ordered move was zero-loss
                    # (moved session bit-identical to an unmoved shadow fed
                    # the exact retained stream); and the table converged —
                    # including at least one clean move AFTER the hot-spot
                    # shift, the re-convergence the scenario exists to test
                    try:
                        with urllib.request.urlopen(
                            server.url + "/placement", timeout=10
                        ) as resp:
                            placement_probe = json.loads(resp.read())
                    except Exception:
                        placement_probe = None
                    placement_rows: Dict[str, Any] = {}
                    post_shift_moves = 0
                    final_report: Optional[Dict[str, Any]] = None
                    if placement_controller is not None:
                        final_report = placement_controller.report()
                        moved = sorted(
                            flash_moved
                            | {
                                tenant
                                for tenant, row in final_report[
                                    "assignments"
                                ].items()
                                if row.get("moves", 0) > 0
                            }
                        )
                        for tenant in moved:
                            shadow = guarded_metric(tenant)
                            for args in flash_streams.get(tenant, ()):
                                shadow.update(*args)
                            restored_val = np.asarray(metrics[tenant].compute())
                            control_val = np.asarray(shadow.compute())
                            placement_rows[tenant] = {
                                "host": final_report["assignments"][tenant]["host"],
                                "moves": final_report["assignments"][tenant]["moves"],
                                "restored": float(restored_val),
                                "control": float(control_val),
                                "bit_identical": bool(
                                    restored_val.dtype == control_val.dtype
                                    and restored_val.tobytes() == control_val.tobytes()
                                ),
                            }
                        post_shift_moves = sum(
                            1
                            for row in final_report["decisions"]
                            if row.get("action") == "move"
                            and row.get("ok")
                            and flash_shift_wall is not None
                            and row.get("unix", 0.0) >= flash_shift_wall
                        )
                    placement_info = {
                        "enabled": bool(config.placement_enabled),
                        "hosts": ["0", "1"],
                        "initial_placement": dict(fleet_placement),
                        "restored_from_disk": placement_restored_from_disk,
                        "shift_wall_unix": flash_shift_wall,
                        "settle_sweeps": flash_settle_sweeps,
                        "moved": sorted(placement_rows),
                        "controls": placement_rows,
                        "zero_loss": (
                            all(
                                row["bit_identical"]
                                for row in placement_rows.values()
                            )
                            if placement_rows
                            else None
                        ),
                        "moves_completed": (
                            final_report["moves"]["completed"]
                            if final_report is not None
                            else 0
                        ),
                        "moves_failed": (
                            final_report["moves"]["failed"]
                            if final_report is not None
                            else 0
                        ),
                        "post_shift_moves": post_shift_moves,
                        "converged": (
                            final_report is not None
                            and not final_report["convergence"]["episode_open"]
                            and final_report["convergence"]["episodes_closed"] >= 1
                        ),
                        "episodes_closed": (
                            final_report["convergence"]["episodes_closed"]
                            if final_report is not None
                            else 0
                        ),
                        "convergence_seconds": (
                            final_report["convergence"]["last_convergence_seconds"]
                            if final_report is not None
                            else None
                        ),
                        "final_placement": (
                            {
                                tenant: row["host"]
                                for tenant, row in final_report[
                                    "assignments"
                                ].items()
                            }
                            if final_report is not None
                            else {}
                        ),
                        "report": final_report,
                        "probe": placement_probe,
                    }
            elapsed = time.perf_counter() - perf_start
            scraper.stop()
            driver_scrapes = scraper.summary()
            degraded_seen = scraper.degraded_seen
            scraper = None
            health = server.health()
            tenants_page = server.tenants_report()
            server_scrapes = server.request_stats()
    finally:
        # back to the one-branch disabled path (the index keeps this run's
        # records for the post-hoc joins below — lookups work either way)
        if not lineage_was_enabled:
            _lineage.disable()
        if config.hung_host:
            # the scrape-driven watchdog is process-global: leave none behind
            from torchmetrics_tpu.robust import fence as _fence_mod

            _fence_mod.install_watchdog(None)
        if config.skewed_load:
            # the installed sampler is process-global too: leave none behind
            _fleet_mod.install_sampler(None)
        if config.flash_crowd:
            # the sampler AND the controller are process-global: restore the
            # caller's controller (usually none) and leave no sampler behind
            from torchmetrics_tpu import fleet as _placement_mod

            _fleet_mod.install_sampler(None)
            _placement_mod.install_controller(placement_prev)
        if profiler is not None:
            # stop sampling and restore whatever profiler the caller had
            # installed; the stopped profiler's tables stay readable for the
            # run-record join below
            profiler.stop()
            _hostprof.install(profiler_prev)
        if scraper is not None:
            scraper.stop()
        server.stop()
        if not closed:
            for pipe in pipelines.values():
                try:
                    pipe.close()
                except Exception:
                    pass
            if mux is not None:
                try:
                    mux.close()
                except Exception:
                    pass
        if auditor is not None:
            # uninstall AFTER the live-session close loop (close() freezes
            # each session's final ledger rows) but BEFORE the zombie closes
            # below; the auditor object stays readable for the run-record
            # join below
            _audit.install_auditor(auditor_prev)
        # the zombies never serve again; closing them releases resources but
        # NOT the successors' leases (close only releases a lease whose epoch
        # still owns the scope row — the fenced epochs don't). Closed with the
        # audit plane already detached: a close-time flush of a wedge-split
        # chunk would fold under the fenced epoch, and in the real deployment
        # that fold happens on the DEAD host, outside the fencer's process —
        # its audited footprint is the rejected late bundle (an event this
        # run already recorded), not a local no_post_fence_fold violation
        for zpipe in zombies.values():
            try:
                zpipe.close()
            except Exception:
                pass

    cost_delta = _cost.get_ledger().since(cost_mark)
    dump_paths = [path for pipe in pipelines.values() for path in pipe.flight_dumps]
    if mux is not None:
        # the mux flight recorder's dumps (per faulted tenant, tenant-local
        # batch indices) ride the same correctness check as pipeline dumps
        dump_paths += mux.flight_dumps
    dumps = [meta for meta in (_read_dump(path) for path in dump_paths) if meta is not None]
    if own_dump_dir:
        import shutil

        shutil.rmtree(dump_dir, ignore_errors=True)
    if bundle_dir is not None:
        import shutil

        shutil.rmtree(bundle_dir, ignore_errors=True)
    if own_ckpt_dir and ckpt_dir is not None:
        import shutil

        shutil.rmtree(ckpt_dir, ignore_errors=True)
    if flash_dir is not None:
        import shutil

        shutil.rmtree(flash_dir, ignore_errors=True)
    # batch-lineage causality evidence (the fault_causality SLO's input): one
    # row per injected NaN batch — does its trace id resolve to a record, and
    # does that record link the full story (guarded tenants: quarantine
    # outcome + a dump naming the id; the victim: the value watchdog its
    # commit fired, or an episode already covering its ingest)?
    episodes = engine.fire_resolve_times()
    if fleet_sampler is not None:
        # skew detection verdict: time from skew onset (the first batch — the
        # static placement concentrates load from the very start) to the
        # imbalance page's fired_at, measured off the standard episode stream
        fired = [
            ep["fired_at"]
            for ep in episodes
            if ep.get("rule") == IMBALANCE_RULE and ep.get("fired_at") is not None
        ]
        first_fired = min(fired) if fired else None
        final_skew = fleet_sampler.skew(window=10 * config.fleet_cadence_seconds)
        hot_after = final_skew.get("hot_host")
        fleet_info = {
            "cadence_seconds": config.fleet_cadence_seconds,
            "placement": fleet_placement,
            "samples": fleet_sampler.samples_taken,
            "degraded_samples": fleet_sampler.degraded_samples,
            "history_samples": fleet_history_n,
            "alert_fired": first_fired is not None,
            "time_to_detect_imbalance_seconds": (
                round(max(0.0, first_fired - wall_start), 6)
                if first_fired is not None
                else None
            ),
            "imbalance": final_skew.get("imbalance"),
            "hot_host": hot_after,
            "shift": (
                dict(
                    fleet_shift,
                    hot_host_after=hot_after,
                    hot_host_shifted=bool(
                        fleet_shift.get("hot_host_before") is not None
                        and hot_after is not None
                        and fleet_shift["hot_host_before"] != hot_after
                    ),
                )
                if fleet_shift is not None
                else None
            ),
            "probe": fleet_probe,
        }
    causality_rows: List[Dict[str, Any]] = []
    for poisoned_tenant, poisoned_indices in schedule.poisoned().items():
        for poisoned_index in poisoned_indices:
            tid = tenant_trace_id(poisoned_tenant, poisoned_index)
            rec = _lineage.lookup(tid)
            dump_named = any(tid in (d.get("poisoned_trace_ids") or []) for d in dumps)
            ingest = float(rec["ingest_unix"]) if rec is not None else None
            alert_linked = bool(rec and rec.get("alerts"))
            if not alert_linked and rec is not None:
                # a later poison landing while the watchdog is already raised
                # fired no fresh transition — a covering episode still links
                alert_linked = any(
                    ep.get("tenant") == poisoned_tenant
                    and ep.get("fired_at") is not None
                    and (
                        ep["fired_at"] >= ingest - 0.005
                        or ep.get("resolved_at") is None
                        or ep["resolved_at"] > ingest
                    )
                    for ep in episodes
                )
            quarantine_out = bool(
                rec and rec.get("outcome") in ("quarantined", "skipped", "raised")
            )
            linked = bool(rec) and (
                (quarantine_out and dump_named)
                if poisoned_tenant != victim
                else alert_linked
            )
            causality_rows.append(
                {
                    "tenant": poisoned_tenant,
                    "index": poisoned_index,
                    "trace_id": tid,
                    "found": rec is not None,
                    "outcome": rec.get("outcome") if rec else None,
                    "dump_named": dump_named,
                    "alert_linked": alert_linked,
                    "linked": linked,
                }
            )
    lineage_info = {
        "enabled": True,
        "index": _lineage.get_index().stats(),
        "poisoned": causality_rows,
        "sample_trace_id": sample_trace_id,
        "sample_trace": sample_trace,
    }
    hostprof_info = None
    if profiler is not None:
        # the continuous profiler's verdict for this run: per-seam breakdown,
        # the Python-floor report (incl. the mux-path host-vs-XLA split), the
        # measured self-overhead, and the mid-run HTTP probe evidence
        hostprof_info = {
            "enabled": True,
            "rate_hz": profiler.rate_hz,
            "duration_seconds": round(profiler.duration_seconds(), 6),
            "self_overhead_percent": round(profiler.self_overhead_percent(), 4),
            "attributed_percent": round(profiler.attributed_percent(), 4),
            "breakdown": profiler.breakdown(),
            "floor": profiler.floor_report(),
            "stats": profiler.stats(),
            "probe": profile_probe,
            # bounded collapsed-stack text (flamegraph.pl input) so the bench
            # can ship the flamegraph as a CI artifact without re-sampling
            "collapsed": profiler.collapsed(top=500),
        }
    audit_info = None
    if auditor is not None:
        # one final audit pass over the frozen ledgers (the scrape-cadence
        # gate has long passed by now), then the full /audit-shaped payload:
        # per-tenant ledgers, invariant results, named violations, fence
        # events — the accounting_clean SLO's evidence
        auditor.tick()
        audit_info = auditor.report()
    reports = {tenant: pipe.report().asdict() for tenant, pipe in pipelines.items()}
    sync_degraded = sorted(
        tenant for tenant, metric in metrics.items() if getattr(metric, "sync_degraded", False)
    )
    quarantined = {
        tenant: int(getattr(metric, "updates_quarantined", 0) or 0)
        for tenant, metric in metrics.items()
        if int(getattr(metric, "updates_quarantined", 0) or 0)
    }
    return {
        "schedule": {
            "seed": schedule.config.seed,
            "tenants": len(schedule.tenants),
            "events": len(schedule.events),
            "victim": victim,
            "hung": hung,
            "poisoned": schedule.poisoned(),
        },
        "wall_seconds": round(elapsed, 6),
        "sleep_seconds": round(sleep_seconds, 6),
        "batches_fed": batches_fed,
        "updates_per_second": round(batches_fed / elapsed, 3) if elapsed > 0 else None,
        "wall_start_unix": wall_start,
        "faults": faults_injected,
        "alerts": {
            "history": engine.history(),
            "episodes": engine.fire_resolve_times(),
            "evaluations": engine.evaluations,
        },
        "scrapes": {
            "driver": driver_scrapes,
            "server": server_scrapes,
            # how many mid-run /healthz scrapes saw "degraded": the injected
            # faults were operator-visible while they were happening
            "degraded_healthz_seen": degraded_seen,
        },
        "cost": {
            "compiled_variants": cost_delta.get("variants_compiled", 0),
            "compile_seconds": cost_delta.get("compile_seconds", 0.0),
        },
        # dump metas were read above; an auto-created dir is gone by now
        "flight": {"dump_dir": None if own_dump_dir else dump_dir, "dumps": dumps},
        # batch-lineage causality evidence + trace-index cardinality (the
        # fault_causality SLO's input and the recorded-never-judged bench key)
        "lineage": lineage_info,
        # conservation-audit evidence (None when ReplayConfig.audit=False):
        # the /audit-shaped payload — per-tenant flow ledgers, invariant
        # results and named violations — the accounting_clean SLO's input
        "audit": audit_info,
        # cross-tenant fused dispatch accounting (None when unmultiplexed):
        # the SLO judge's mux-engagement check and the before/after evidence
        # next to the compiled-variant delta above
        "mux": (
            {
                "max_width": config.mux_max_width,
                "tenants": len(mux.tenants()),
                "report": mux.report().asdict(),
                "cache": mux.cache_info(),
            }
            if mux is not None
            else None
        ),
        # continuous host-profiler accounting (None unless the profiler was
        # live — auto for the multiplexed scenario): per-seam host-time
        # breakdown, the Python-floor report with the mux-path host-vs-XLA
        # split, sampler self-overhead, and the mid-run GET /profile probe
        "hostprof": hostprof_info,
        "robust": {"sync_degraded": sync_degraded, "quarantined": quarantined},
        # rolling-deploy accounting (None unless ReplayConfig.rolling_deploy):
        # migrated tenants, handoff wall time, the mid-flight /healthz
        # observation, and the per-tenant bit-identity verdicts vs controls
        "migration": migration_info,
        # host-crash accounting (None unless ReplayConfig.host_crash): crashed
        # tenants, per-session replay gaps vs the checkpoint cadence, recovery
        # wall time, bit-identity verdicts vs unkilled controls, and the
        # full-vs-delta bundle-bytes evidence
        "crash": crash_info,
        # hung-host fencing accounting (None unless ReplayConfig.hung_host):
        # wedged tenants, time-to-detect / time-to-failover via the scrape-
        # driven watchdog, the zombie's rejected late bundle write, operator
        # visibility probes, and the zero-double-counting verdicts vs controls
        "fence": fence_info,
        # fleet-telemetry accounting (None unless ReplayConfig.skewed_load):
        # sample/degraded counts, time-to-detect for the imbalance page, the
        # mid-run hot-spot shift + wedged-gather evidence, and the HTTP-probed
        # /fleet payload an operator would read
        "fleet": fleet_info,
        # placement-control-plane accounting (None unless
        # ReplayConfig.flash_crowd): durable-restore evidence, the controller's
        # move ledger + decision log, zero-loss bit-identity verdicts for every
        # moved session, convergence (including the post-shift re-convergence),
        # and the HTTP-probed /placement payload an operator would read
        "placement": placement_info,
        "health": health,
        "tenants": tenants_page,
        "pipelines": reports,
    }
