"""AOT warmup + persistent compile cache for the streaming evaluation engine.

Two jobs, both about paying XLA compiles *before* the hot loop instead of inside
it:

- :func:`configure_compile_cache` wires JAX's **persistent compilation cache**
  (``jax_compilation_cache_dir``) to a directory — explicit argument, or the
  ``TM_TPU_COMPILE_CACHE`` environment variable. Once configured, every XLA
  compile this process performs is written to (and on restart, read back from)
  disk, so a re-run of the same metric configuration skips compilation entirely.
  A monitoring listener counts persistent-cache hits so
  :func:`persistent_cache_stats` can report hit/miss totals (surfaced in
  ``bench.py``'s engine configs and the warmup manifest).
- The **warmup manifest** records what a warmup pass precompiled — one entry per
  (function, shape-bucket) variant with its compile wall time and whether it was
  fresh — and round-trips through :func:`save_manifest` / :func:`load_manifest`
  (atomic writes via ``utils/fileio``). A manifest next to a run's output answers
  "what did startup compile, and how long did it take" without a profiler.

The actual precompiles are driven by :meth:`MetricPipeline.warmup
<torchmetrics_tpu.engine.pipeline.MetricPipeline.warmup>` (which lowers every
fused shape-bucket variant plus the per-batch replay path) and
:meth:`TenantMultiplexer.warmup
<torchmetrics_tpu.engine.mux.TenantMultiplexer.warmup>` (every tenant-width
bucket of the cross-tenant fused program, manifest entries ``kind: "mux"``),
both through :meth:`StaticLeafJit.warmup
<torchmetrics_tpu.core.jit.StaticLeafJit.warmup>`, using the helpers here for
cache wiring, the shared :func:`pow2_buckets` ladder, and manifest assembly.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import torchmetrics_tpu.obs.trace as _trace
from torchmetrics_tpu.utils.fileio import atomic_write_text
from torchmetrics_tpu.utils.prints import rank_zero_warn

__all__ = [
    "CACHE_ENV_VAR",
    "MANIFEST_SCHEMA",
    "build_manifest",
    "configure_compile_cache",
    "configured_cache_dir",
    "load_manifest",
    "persistent_cache_stats",
    "pow2_buckets",
    "save_manifest",
]


def pow2_buckets(cap: int) -> tuple:
    """The engine's shared bucket ladder: powers of two up to (and including)
    ``cap``, with ``cap`` itself always the top bucket.

    One discipline, two axes: the streaming pipeline buckets fused *chunk
    lengths* and the tenant multiplexer buckets fused *tenant widths* with the
    same ladder, so both keep their compiled-variant count ``O(log cap)`` per
    signature instead of one program per observed size.
    """
    if cap < 1:
        raise ValueError(f"Expected `cap` >= 1, got {cap}")
    out, b = [], 1
    while b < cap:
        out.append(b)
        b *= 2
    out.append(int(cap))
    return tuple(out)

CACHE_ENV_VAR = "TM_TPU_COMPILE_CACHE"
MANIFEST_SCHEMA = 1

_lock = threading.Lock()
_configured_dir: Optional[str] = None
_listener_installed = False
_warned_cache_unavailable = False
# persistent-cache monitoring totals (plain ints: readable without obs tracing)
_cache_events = {"requests": 0, "hits": 0}


def _install_cache_listener() -> None:
    """Count JAX's persistent-compilation-cache monitoring events.

    JAX records ``/jax/compilation_cache/cache_hits`` on every disk-cache hit and
    ``.../compile_requests_use_cache`` on every compile that consulted the cache;
    the listener keeps plain-int totals (misses = requests - hits). Guarded:
    monitoring is a private-ish surface and its absence only costs the stats.
    """
    global _listener_installed
    if _listener_installed:
        return
    try:
        from jax._src import monitoring as _monitoring

        def _on_event(event: str, **kwargs: Any) -> None:
            if event == "/jax/compilation_cache/compile_requests_use_cache":
                _cache_events["requests"] += 1
            elif event == "/jax/compilation_cache/cache_hits":
                _cache_events["hits"] += 1
                if _trace.ENABLED:
                    _trace.inc("engine.compile_cache_hit")

        _monitoring.register_event_listener(_on_event)
        _listener_installed = True
    except Exception:  # pragma: no cover - monitoring API drift
        _listener_installed = True  # do not retry per call


def configure_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Resolution order: explicit argument, then ``$TM_TPU_COMPILE_CACHE``; with
    neither set this is a no-op returning ``None`` (the in-memory-only default).
    The entry-size/compile-time floors are dropped so even the small CPU-backend
    programs metric updates compile to are cached — without that, warmup on the
    test/bench hosts would never exercise the disk path the TPU runs rely on.
    Idempotent per directory; safe to call from every pipeline constructor.
    """
    global _configured_dir, _warned_cache_unavailable
    resolved = cache_dir or os.environ.get(CACHE_ENV_VAR) or None
    if resolved is None:
        return _configured_dir
    resolved = os.path.abspath(resolved)
    with _lock:
        if _configured_dir == resolved:
            return resolved
        try:
            import jax

            os.makedirs(resolved, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", resolved)
            for knob, value in (
                ("jax_persistent_cache_min_compile_time_secs", 0.0),
                ("jax_persistent_cache_min_entry_size_bytes", -1),
            ):
                try:
                    jax.config.update(knob, value)
                except Exception:  # knob renamed/removed: floors stay at defaults
                    pass
            try:
                # any compile that ran before the dir was set latches the cache
                # module as initialized-with-no-store (_cache_initialized=True,
                # _cache=None) and jax 0.4.x does NOT reset it on config update —
                # without this reset a late configure silently caches nothing
                from jax._src import compilation_cache as _compilation_cache

                _compilation_cache.reset_cache()
            except Exception:  # private-API drift: a pre-config compile keeps the latch
                pass
        except Exception as err:
            if not _warned_cache_unavailable:
                _warned_cache_unavailable = True
                rank_zero_warn(
                    f"Persistent compilation cache could not be configured at {resolved!r}:"
                    f" {type(err).__name__}: {err}. Compiles stay in-memory only; restarts"
                    " will recompile from scratch.",
                    RuntimeWarning,
                )
            return None
        _install_cache_listener()
        _configured_dir = resolved
    if _trace.ENABLED:
        _trace.event("engine.compile_cache_configured", dir=resolved)
    return resolved


def configured_cache_dir() -> Optional[str]:
    """The directory the persistent cache was wired to (``None`` when unwired)."""
    return _configured_dir


def persistent_cache_stats() -> Dict[str, Any]:
    """Persistent-cache accounting: directory, on-disk entries, hit/miss totals.

    ``entries`` counts the ``*-cache`` payload files in the configured directory
    (what a restart can hit); ``hits``/``misses`` count this process's lookups.
    All zeros/None when no cache is configured.
    """
    entries = 0
    if _configured_dir is not None and os.path.isdir(_configured_dir):
        try:
            entries = sum(1 for name in os.listdir(_configured_dir) if name.endswith("-cache"))
        except OSError:
            entries = 0
    requests, hits = _cache_events["requests"], _cache_events["hits"]
    return {
        "dir": _configured_dir,
        "entries": entries,
        "requests": requests,
        "hits": hits,
        "misses": max(0, requests - hits),
    }


# ------------------------------------------------------------------------ manifest


def build_manifest(entries: List[Dict[str, Any]], cache_dir: Optional[str] = None) -> Dict[str, Any]:
    """Assemble a warmup manifest from per-variant entries.

    Each entry comes from :meth:`StaticLeafJit.warmup` plus the pipeline's
    bucket/shape annotations; the manifest adds schema/backend/cache context and
    the compile-time total so one record describes the whole warmup pass.

    Entries carry per-variant ``flops`` / ``bytes_accessed`` when the cost
    ledger could read them off the compiled executable (cached variants
    included); the summed ``estimated_flops`` / ``estimated_bytes`` answer
    "what does one pass over every precompiled variant cost" next to "what did
    compiling them cost" — ``None`` when the backend reported no cost analysis.
    """
    backend = None
    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # pragma: no cover - warmup without an initializable backend
        pass
    fresh = [e for e in entries if e.get("fresh")]
    flops = [e["flops"] for e in entries if isinstance(e.get("flops"), (int, float))]
    bytes_accessed = [
        e["bytes_accessed"] for e in entries if isinstance(e.get("bytes_accessed"), (int, float))
    ]
    return {
        "schema_version": MANIFEST_SCHEMA,
        "created_unix": time.time(),
        "backend": backend,
        "cache_dir": cache_dir if cache_dir is not None else _configured_dir,
        "entries": list(entries),
        "variants": len(entries),
        "fresh_compiles": len(fresh),
        "total_compile_seconds": round(sum(float(e.get("seconds", 0.0)) for e in fresh), 6),
        "estimated_flops": sum(flops) if flops else None,
        "estimated_bytes": sum(bytes_accessed) if bytes_accessed else None,
    }


def save_manifest(manifest: Dict[str, Any], path: str) -> str:
    """Atomically write ``manifest`` as JSON; returns the absolute path."""
    return atomic_write_text(path, json.dumps(manifest, indent=2, sort_keys=True) + "\n")


def load_manifest(path: str) -> Dict[str, Any]:
    """Load a manifest written by :func:`save_manifest`, validating the schema."""
    with open(path, "r", encoding="utf-8") as fh:
        manifest = json.load(fh)
    if not isinstance(manifest, dict) or manifest.get("schema_version") != MANIFEST_SCHEMA:
        raise ValueError(
            f"{path!r} is not a warmup manifest (schema_version"
            f" {manifest.get('schema_version') if isinstance(manifest, dict) else None!r},"
            f" expected {MANIFEST_SCHEMA})"
        )
    return manifest
