"""Live-session checkpoint/restore: a running pipeline as a migratable object.

PR 1's atomic SHA-256 metric checkpoints froze *state*; PR 8's tenant sessions
made a :class:`~torchmetrics_tpu.engine.pipeline.MetricPipeline` a live,
attributed serving object. This module composes them: a **session bundle**
captures everything a running session *is* — not just its metric state — and
restores it on another host with nothing lost:

- **metric state**, mid-stream, via the existing ``__robust__``-aware
  ``state_dict`` machinery (update counts, quarantine counters and
  ``sync_degraded`` ride along), written as a plain ``state.npz`` payload +
  JSON skeleton — deliberately **not** orbax: orbax's multihost save barrier
  would deadlock exactly the asymmetric one-host-checkpoints-while-the-other-
  serves handoff this module exists for;
- the **replay tail**: the fusion/prefetch plane is drained to a cursor
  (:meth:`MetricPipeline.drain` dispatches the open chunk and blocks the
  in-flight window, so state is exactly the fold of every dispatched batch)
  and the batches *behind* the cursor — the admission-deferred backlog plus
  any caller-buffered arrivals — are persisted verbatim and re-fed after
  restore;
- the **flight-recorder ring** (a restored session's first fault dump still
  carries pre-migration lineage), the **pipeline report** (accounting keeps
  counting, not restarting), the **tenant registry row** (lifetime
  updates/computes merge onto the restoring host), the session's **value
  timelines** (step anchors intact) and its **alert state machines**
  (``pending``/``firing`` resume with their dwell clocks).

Durability is the hardened PR-1 writer: the whole bundle is materialized under
a temp directory, digested file-by-file into ``INTEGRITY.json``, and swapped
into place with the displace-then-rename loop
(:func:`~torchmetrics_tpu.utils.checkpoint.atomic_install_dir`) — preemption
mid-checkpoint leaves the old bundle or the new one, never a hybrid. Restores
verify the digest and the schema-versioned manifest **before touching the
target**: a truncated, tampered or schema-mismatched bundle raises
:class:`SessionBundleError` loudly and the restoring process is untouched.

The protocol is **drain → checkpoint → restore → replay-tail**, and it is
degraded-not-dead while in flight: both halves run under
:func:`torchmetrics_tpu.obs.scope.migration`, so ``/healthz`` answers
``degraded`` with the migrating tenant *named* (``tenants_migrating``) for the
handoff window. With the persistent compile cache wired
(``TM_TPU_COMPILE_CACHE`` shared between hosts), the restored session's warmup
is disk reads — the restart cost a rolling deploy pays is the bundle I/O, not
recompilation.

Zero-loss contract (asserted by the test suite and the rolling-deploy chaos
scenario): a session checkpointed mid-stream, restored elsewhere, tail
replayed, then fed the remainder of the stream computes values **bit-identical**
to an unmigrated control.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import uuid
from dataclasses import replace
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

import numpy as np

import torchmetrics_tpu.obs.scope as _scope
import torchmetrics_tpu.obs.trace as _trace
import torchmetrics_tpu.obs.values as _values
from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.engine.pipeline import MetricPipeline, PipelineConfig, _normalize_batch
from torchmetrics_tpu.utils import checkpoint as _checkpoint
from torchmetrics_tpu.utils.checkpoint import CheckpointIntegrityError

__all__ = [
    "SESSION_SCHEMA",
    "SessionBundleError",
    "checkpoint_session",
    "restore_session",
    "verify_bundle",
]

# wire-format version of a session bundle; bump on any structural change —
# restores REJECT other versions (a silently reinterpreted session would
# break the bit-identity promise without saying so)
SESSION_SCHEMA = 1
_BUNDLE_KIND = "tm_tpu_session"

_MANIFEST_NAME = "MANIFEST.json"
_INTEGRITY_NAME = "INTEGRITY.json"
_STATE_NAME = "state.npz"
_TAIL_NAME = "tail.npz"

# PipelineConfig knobs that serialize into the manifest (everything except
# live objects: device handles, alert engines, admission controllers — those
# are the restoring host's to supply)
_CONFIG_FIELDS = (
    "fuse",
    "max_in_flight",
    "prefetch",
    "fuse_buckets",
    "flight_records",
    "flight_max_dumps",
    "alert_every",
    "max_deferred",
    "tenant",
)


class SessionBundleError(CheckpointIntegrityError):
    """The session bundle on disk cannot be trusted (truncated, tampered,
    half-written, or written by an incompatible schema)."""


# ------------------------------------------------------------------ internals


def _encode_tree(tree: Any) -> Tuple[Any, Dict[str, np.ndarray]]:
    """Split a host-state pytree (nested dicts, numpy leaves) into a JSON
    skeleton + an npz array payload.

    Leaves become ``{"__leaf__": "s<N>"}`` placeholders; the skeleton keeps
    empty containers (unlike orbax, which drops them — and unlike orbax, the
    writer involves no multihost barrier, so one host can checkpoint while
    its peers keep serving).
    """
    arrays: Dict[str, np.ndarray] = {}
    counter = [0]

    def walk(node: Any) -> Any:
        if isinstance(node, dict):
            return {key: walk(value) for key, value in node.items()}
        key = f"s{counter[0]}"
        counter[0] += 1
        arrays[key] = np.asarray(node)
        return {"__leaf__": key}

    return walk(tree), arrays


def _decode_tree(skeleton: Any, arrays: Dict[str, np.ndarray]) -> Any:
    def walk(node: Any) -> Any:
        if (
            isinstance(node, dict)
            and set(node) == {"__leaf__"}
            and isinstance(node["__leaf__"], str)
        ):
            return arrays[node["__leaf__"]]
        return {key: walk(value) for key, value in node.items()}

    return walk(skeleton)


def _driven_metrics(target: Union[Metric, MetricCollection]) -> List[Tuple[str, Metric]]:
    """(label, metric) pairs the session drives — collections flatten by name."""
    if isinstance(target, MetricCollection):
        return list(target._modules.items())
    return [("", target)]


def _serialize_tail(
    tail: List[Tuple[tuple, dict]]
) -> Tuple[List[Dict[str, Any]], Dict[str, np.ndarray]]:
    """Split tail batches into a JSON structure + an array payload (npz keys)."""
    structure: List[Dict[str, Any]] = []
    arrays: Dict[str, np.ndarray] = {}
    for bi, (args, kwargs) in enumerate(tail):
        a_desc: List[Dict[str, Any]] = []
        for ai, leaf in enumerate(args):
            if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
                key = f"b{bi}_a{ai}"
                arrays[key] = np.asarray(leaf)
                a_desc.append({"array": key})
            else:
                a_desc.append({"value": leaf})
        k_desc: Dict[str, Dict[str, Any]] = {}
        for name, leaf in kwargs.items():
            if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
                key = f"b{bi}_k_{name}"
                arrays[key] = np.asarray(leaf)
                k_desc[name] = {"array": key}
            else:
                k_desc[name] = {"value": leaf}
        structure.append({"args": a_desc, "kwargs": k_desc})
    return structure, arrays


def _deserialize_tail(
    structure: List[Dict[str, Any]], arrays: Dict[str, np.ndarray]
) -> List[Tuple[tuple, dict]]:
    import jax.numpy as jnp

    def leaf(desc: Dict[str, Any]) -> Any:
        if "array" in desc:
            return jnp.asarray(arrays[desc["array"]])
        return desc.get("value")

    batches: List[Tuple[tuple, dict]] = []
    for entry in structure or []:
        args = tuple(leaf(d) for d in entry.get("args") or [])
        kwargs = {name: leaf(d) for name, d in (entry.get("kwargs") or {}).items()}
        batches.append((args, kwargs))
    return batches


def _session_values(
    log: Any, tenant: Optional[str], inst_pairs: set
) -> List[Dict[str, Any]]:
    """The value-timeline series belonging to this session: its tenant's
    series plus the driven metric instances' untenanted ones."""
    rows = []
    for row in log.series():
        owns = (tenant is not None and row.get("tenant") == tenant) or (
            (row.get("metric"), row.get("inst")) in inst_pairs
        )
        if owns:
            rows.append(row)
    return rows


def _resolve_value_log(value_log: Any, alert_engine: Any) -> Any:
    """The value log a session actually used: explicit > engine's > global."""
    if value_log is not None:
        return value_log
    log_hook = getattr(alert_engine, "_log", None)
    if callable(log_hook):
        return log_hook()
    return _values.get_log()


# ---------------------------------------------------------------- checkpoint


def checkpoint_session(
    pipe: MetricPipeline,
    path: str,
    tail: Iterable[Any] = (),
    alert_engine: Any = None,
    value_log: Any = None,
) -> Dict[str, Any]:
    """Atomically checkpoint a *live* session to a bundle at ``path``.

    Drains the pipeline first (open chunk dispatched, in-flight window blocked
    — the **cursor**: metric state is now exactly the fold of every dispatched
    batch), then persists the full session: metric state (orbax pytree, the
    ``__robust__``-aware ``state_dict``), the replay tail (the drained
    admission-deferred backlog plus any ``tail`` batches the caller buffered
    while draining — each item a positional tuple, a kwargs dict, or a single
    array), the flight-recorder ring, the pipeline report, the tenant registry
    row, the session's value timelines, and the alert engine's live state
    machines + history.

    ``alert_engine`` defaults to the pipeline's configured engine, else the
    process-global one; ``value_log`` to the engine's log, else the global.
    Runs under ``scope.migration(tenant, "checkpoint")`` so ``/healthz`` names
    the tenant while the drain+write is in flight. Returns the manifest.
    """
    target = pipe.metric
    tenant = pipe.config.tenant
    engine = alert_engine if alert_engine is not None else pipe.config.alert_engine
    if engine is None:
        import torchmetrics_tpu.obs.alerts as _alerts

        engine = _alerts.get_engine()
    log = _resolve_value_log(value_log, engine)

    ctx = _scope.migration(tenant, "checkpoint") if tenant is not None else None
    if ctx is not None:
        ctx.__enter__()
    try:
        drained = pipe.drain()
        tail_batches = list(drained) + [_normalize_batch(b) for b in tail]
        report = pipe.report()
        members = _driven_metrics(target)
        robust = {
            label: {"sync_degraded": bool(getattr(m, "sync_degraded", False))}
            for label, m in members
        }
        cursor = {
            "batches_ingested": report.batches,
            "tail_batches": len(tail_batches),
            # the first this-many tail batches are the origin's admission-
            # deferred backlog (drain() hands it back first): the restore
            # counts them toward deferred_replayed so the accounting balances
            "deferred_tail": len(drained),
            "update_counts": {label: int(m.update_count) for label, m in members},
        }
        inst_pairs = {
            (type(m).__name__, str(getattr(m, "_obs_instance", "0"))) for _, m in members
        }
        registry_row = None
        if tenant is not None:
            effective = pipe._tenant
            for row in _scope.get_registry().rows():
                if row["tenant"] == effective:
                    registry_row = row
                    break
        tail_structure, tail_arrays = _serialize_tail(tail_batches)
        state_skeleton, state_arrays = _encode_tree(_checkpoint._tree_of(target))
        config_fields = {name: getattr(pipe.config, name) for name in _CONFIG_FIELDS}
        if config_fields["fuse_buckets"] is not None:
            config_fields["fuse_buckets"] = list(config_fields["fuse_buckets"])
        manifest = {
            "kind": _BUNDLE_KIND,
            "schema_version": SESSION_SCHEMA,
            "tenant": tenant,
            "metric_class": type(target).__name__,
            "collection": isinstance(target, MetricCollection),
            "members": [label for label, _ in members if label],
            "config": config_fields,
            "cursor": cursor,
            "state_skeleton": state_skeleton,
            "tail": tail_structure,
            "report": {k: v for k, v in report.asdict().items()},
            "robust": robust,
            "flight": pipe.flight_snapshot(),
            "values": _session_values(log, pipe._tenant, inst_pairs),
            "alerts": engine.export_state() if engine is not None else None,
            "registry": registry_row,
            "ts_unix": time.time(),
        }
        try:
            manifest_text = json.dumps(manifest, sort_keys=True, indent=2)
        except TypeError as err:
            raise TypeError(
                "Session state carries a non-JSON-serializable leaf (a tail batch's"
                f" static argument, most likely): {err}. Only plain scalars/strings"
                " may ride the tail outside arrays."
            ) from err

        path = os.path.abspath(path)
        tag = f"{os.getpid()}.{uuid.uuid4().hex[:8]}"
        tmp = f"{path}.tmp.{tag}"
        try:
            os.makedirs(tmp, exist_ok=True)
            np.savez(os.path.join(tmp, _STATE_NAME), **state_arrays)
            if tail_arrays:
                np.savez(os.path.join(tmp, _TAIL_NAME), **tail_arrays)
            with open(os.path.join(tmp, _MANIFEST_NAME), "w", encoding="utf-8") as fh:
                fh.write(manifest_text)
            digest = _checkpoint.file_tree_digest(tmp, exclude=(_INTEGRITY_NAME,))
            with open(os.path.join(tmp, _INTEGRITY_NAME), "w", encoding="utf-8") as fh:
                json.dump({"version": 1, "schema": SESSION_SCHEMA, "sha256": digest}, fh)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        _checkpoint.atomic_install_dir(tmp, path, tag)
        if _trace.ENABLED:
            _trace.event(
                "engine.session_checkpoint",
                pipeline=type(target).__name__,
                tenant=tenant,
                batches=report.batches,
                tail=len(tail_batches),
                path=path,
            )
        return manifest
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)


# ------------------------------------------------------------------- restore


def verify_bundle(path: str) -> Dict[str, Any]:
    """Verify a session bundle's integrity + schema; returns its manifest.

    Loud by design: a missing bundle, a missing/unreadable integrity record, a
    file-tree digest mismatch (truncation, tampering, a half-copied rsync), an
    unreadable manifest, or a schema/kind mismatch each raise
    :class:`SessionBundleError` **before any state is touched** — restoring
    from a bad bundle must never poison the restoring process.
    """
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise SessionBundleError(f"No session bundle at {path}")
    integrity_path = os.path.join(path, _INTEGRITY_NAME)
    if not os.path.isfile(integrity_path):
        raise SessionBundleError(
            f"Session bundle at {path} has no {_INTEGRITY_NAME} — bundles are always"
            " written with an integrity record, so this is a partial copy or a"
            " directory that is not a session bundle; refusing to restore from it."
        )
    try:
        with open(integrity_path, encoding="utf-8") as fh:
            recorded = json.load(fh)
    except (OSError, ValueError) as err:
        raise SessionBundleError(
            f"Session bundle at {path} has an unreadable {_INTEGRITY_NAME} ({err}) —"
            " the record itself is truncated or tampered; restore from another bundle."
        ) from err
    digest = _checkpoint.file_tree_digest(path, exclude=(_INTEGRITY_NAME,))
    if digest != recorded.get("sha256"):
        raise SessionBundleError(
            f"Session bundle at {path} failed its integrity check (recorded"
            f" {str(recorded.get('sha256'))[:12]}…, recomputed {digest[:12]}…) —"
            " the bundle was corrupted after the checkpoint; restore from another one."
        )
    try:
        with open(os.path.join(path, _MANIFEST_NAME), encoding="utf-8") as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as err:
        raise SessionBundleError(
            f"Session bundle at {path} has an unreadable {_MANIFEST_NAME} ({err})"
        ) from err
    if not isinstance(manifest, dict) or manifest.get("kind") != _BUNDLE_KIND:
        raise SessionBundleError(
            f"Directory at {path} verifies but is not a session bundle"
            f" (kind={manifest.get('kind') if isinstance(manifest, dict) else None!r})"
        )
    if manifest.get("schema_version") != SESSION_SCHEMA:
        raise SessionBundleError(
            f"Session bundle at {path} carries schema"
            f" {manifest.get('schema_version')!r} but this build speaks"
            f" {SESSION_SCHEMA} — re-checkpoint with a matching build (a silently"
            " reinterpreted session would break the zero-loss contract)."
        )
    return manifest


def restore_session(
    metric: Union[Metric, MetricCollection],
    path: str,
    config: Optional[PipelineConfig] = None,
    alert_engine: Any = None,
    value_log: Any = None,
    replay: bool = True,
    restore_registry: bool = True,
    **overrides: Any,
) -> Tuple[MetricPipeline, Dict[str, Any]]:
    """Restore a checkpointed session onto ``metric`` (freshly constructed with
    the same spec — the ``load_checkpoint`` contract); returns ``(pipeline,
    manifest)``.

    The second half of drain→checkpoint→restore→replay-tail: the bundle is
    verified (:func:`verify_bundle`, loud), metric state is restored (update
    counts, robust counters and ``sync_degraded`` included), a new
    :class:`MetricPipeline` is built from the bundled config (``config=`` or
    keyword ``overrides`` adjust host-local knobs: ``flight_dump_dir``,
    ``device``, ...; ``alert_engine`` attaches the restoring host's engine and
    receives the bundled alert machines with dwell clocks intact), the flight
    ring / report / value timelines / registry row are re-installed, and the
    replay tail is re-fed in order (admission bypassed — it was admitted
    before the checkpoint). With ``TM_TPU_COMPILE_CACHE`` shared between
    hosts, the restored pipeline's :meth:`~MetricPipeline.warmup` is
    persistent-cache reads, so warmup after a restore is ~free.

    Runs under ``scope.migration(tenant, "restore")`` — ``/healthz`` stays
    degraded-not-dead with the tenant named until the tail has replayed.
    """
    manifest = verify_bundle(path)
    path = os.path.abspath(path)

    if type(metric).__name__ != manifest.get("metric_class"):
        raise SessionBundleError(
            f"Session bundle at {path} was checkpointed from a"
            f" {manifest.get('metric_class')!r} but the restore target is a"
            f" {type(metric).__name__!r} — the target must be constructed with the"
            " checkpointed session's spec."
        )
    is_collection = isinstance(metric, MetricCollection)
    if bool(manifest.get("collection")) != is_collection:
        raise SessionBundleError(
            f"Session bundle at {path} and the restore target disagree on being a"
            " MetricCollection."
        )
    members = _driven_metrics(metric)
    if is_collection:
        want = set(manifest.get("members") or [])
        have = {label for label, _ in members}
        if want != have:
            raise SessionBundleError(
                f"Session bundle at {path} names members {sorted(want)} but the"
                f" restore target holds {sorted(have)} — same-spec restore only."
            )

    try:
        with np.load(os.path.join(path, _STATE_NAME)) as payload:
            state_arrays = {key: payload[key] for key in payload.files}
        tree = _decode_tree(manifest.get("state_skeleton") or {}, state_arrays)
    except SessionBundleError:
        raise
    except Exception as err:
        raise SessionBundleError(
            f"Session bundle at {path} verifies but its state tree is unreadable:"
            f" {err}"
        ) from err

    tenant = manifest.get("tenant")
    ctx = _scope.migration(tenant, "restore") if tenant is not None else None
    if ctx is not None:
        ctx.__enter__()
    try:
        if is_collection:
            for label, m in members:
                _checkpoint._restore_states(m, tree[label])
        else:
            _checkpoint._restore_states(metric, tree)
        robust = manifest.get("robust") or {}
        for label, m in members:
            flags = robust.get(label) or {}
            if flags.get("sync_degraded"):
                m.sync_degraded = True

        if config is None:
            cfg_kwargs = dict(manifest.get("config") or {})
            if cfg_kwargs.get("fuse_buckets") is not None:
                cfg_kwargs["fuse_buckets"] = tuple(cfg_kwargs["fuse_buckets"])
            cfg_kwargs.update(overrides)
            if alert_engine is not None:
                cfg_kwargs["alert_engine"] = alert_engine
            config = PipelineConfig(**cfg_kwargs)
        else:
            if config.tenant is None and tenant is not None:
                overrides = {"tenant": tenant, **overrides}
            if alert_engine is not None:
                overrides = {**overrides, "alert_engine": alert_engine}
            if overrides:
                config = replace(config, **overrides)

        pipe = MetricPipeline(metric, config)
        pipe._restore_report(manifest.get("report") or {})
        pipe._restore_flight(manifest.get("flight") or {})

        engine = config.alert_engine
        if engine is None:
            import torchmetrics_tpu.obs.alerts as _alerts

            engine = _alerts.get_engine()
        if engine is not None and manifest.get("alerts"):
            engine.restore_state(manifest["alerts"])
        log = _resolve_value_log(value_log, engine)
        log.restore_series(manifest.get("values") or [])

        row = manifest.get("registry")
        if restore_registry and row and pipe._tenant is not None:
            _scope.get_registry().restore_row(
                pipe._tenant,
                updates=row.get("updates", 0),
                computes=row.get("computes", 0),
                first_seen_unix=row.get("first_seen_unix"),
            )

        if replay:
            arrays: Dict[str, np.ndarray] = {}
            tail_path = os.path.join(path, _TAIL_NAME)
            if os.path.isfile(tail_path):
                with np.load(tail_path) as payload:
                    arrays = {key: payload[key] for key in payload.files}
            batches = _deserialize_tail(manifest.get("tail") or [], arrays)
            pipe.replay_tail(
                batches, deferred=int((manifest.get("cursor") or {}).get("deferred_tail", 0) or 0)
            )
        if _trace.ENABLED:
            _trace.event(
                "engine.session_restore",
                pipeline=type(metric).__name__,
                tenant=tenant,
                batches=(manifest.get("cursor") or {}).get("batches_ingested", 0),
                tail=(manifest.get("cursor") or {}).get("tail_batches", 0),
                path=path,
            )
        return pipe, manifest
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)
