"""Live-session checkpoint/restore: a running pipeline as a migratable object.

PR 1's atomic SHA-256 metric checkpoints froze *state*; PR 8's tenant sessions
made a :class:`~torchmetrics_tpu.engine.pipeline.MetricPipeline` a live,
attributed serving object. This module composes them: a **session bundle**
captures everything a running session *is* — not just its metric state — and
restores it on another host with nothing lost:

- **metric state**, mid-stream, via the existing ``__robust__``-aware
  ``state_dict`` machinery (update counts, quarantine counters and
  ``sync_degraded`` ride along), written as a plain ``state.npz`` payload +
  JSON skeleton — deliberately **not** orbax: orbax's multihost save barrier
  would deadlock exactly the asymmetric one-host-checkpoints-while-the-other-
  serves handoff this module exists for;
- the **replay tail**: the fusion/prefetch plane is drained to a cursor
  (:meth:`MetricPipeline.drain` dispatches the open chunk and blocks the
  in-flight window, so state is exactly the fold of every dispatched batch)
  and the batches *behind* the cursor — the admission-deferred backlog plus
  any caller-buffered arrivals — are persisted verbatim and re-fed after
  restore;
- the **flight-recorder ring** (a restored session's first fault dump still
  carries pre-migration lineage), the **pipeline report** (accounting keeps
  counting, not restarting), the **tenant registry row** (lifetime
  updates/computes merge onto the restoring host), the session's **value
  timelines** (step anchors intact) and its **alert state machines**
  (``pending``/``firing`` resume with their dwell clocks).

Since the continuous-checkpointing PR the one-shot migration bundle is also a
**periodic, crash-consistent checkpoint stream**:

- **Delta bundles** — every ``state.npz`` entry (large leaves split into
  fixed-size segments, so an append-only ``MaskedBuffer`` only rewrites the
  segments its appends touched) is content-hashed into the manifest; a delta
  bundle names its base (``base.name`` + ``base.bundle_id``) and writes only
  the entries whose hash changed. :func:`verify_bundle` walks and verifies the
  **whole chain** (per-link file-tree digest, schema, base-id linkage, full
  entry resolvability); restores re-check every loaded entry's content hash.
- **Continuous cadence** — a :class:`CheckpointPolicy` on
  ``PipelineConfig.checkpoint`` (and ``MuxConfig.checkpoint``) writes bundles
  every N batches / T seconds **at chunk-commit boundaries**: no drain, no
  stall — the state at a commit boundary is already exactly the fold of every
  dispatched batch, so every periodic bundle is chunk-consistent by
  construction. Batches sitting in the open fusion chunk when a host dies are
  the *replay gap*, bounded by the cadence. Every ``full_every``-th bundle is
  a full compaction point; a bounded retention sweep (:func:`sweep_bundles`)
  removes superseded bundles but never a link a kept chain depends on.
- **Unplanned-death recovery** — :func:`latest_valid_bundle` scans a bundle
  directory, loudly skips mid-write temp dirs and corrupt/truncated links,
  and returns the newest bundle whose whole chain verifies; restore from it,
  then re-feed the gap from the deterministic traffic source. The
  ``host_crash`` chaos scenario (``bench.py --chaos-scenario host_crash``)
  proves the loop end to end with bit-identity against a shadow control.
- **Mux tenant slices** — :func:`checkpoint_session` on a live
  :class:`~torchmetrics_tpu.engine.mux.TenantMultiplexer` extracts ONE
  tenant's slice (state, deferred backlog, tenant-local flight records,
  registry row, values, alerts) directly into a pipeline-restorable bundle.
- **Observability** — ``checkpoint.*`` gauges (last-success age per tenant,
  full-vs-delta bundle bytes, write seconds) flow through
  :mod:`~torchmetrics_tpu.obs.scope` to ``/metrics`` and ``/tenants``; a
  tenant whose policy declares ``stale_after_seconds`` and misses it flips
  ``/healthz`` degraded with the tenant named, and
  :func:`checkpoint_staleness_rule` turns the same signal into a firing
  alert.

Durability is the hardened PR-1 writer: the whole bundle is materialized under
a temp directory, digested file-by-file into ``INTEGRITY.json``, and swapped
into place with the displace-then-rename loop
(:func:`~torchmetrics_tpu.utils.checkpoint.atomic_install_dir`) — preemption
mid-checkpoint leaves the old bundle or the new one, never a hybrid. Restores
verify the digest and the schema-versioned manifest **before touching the
target**: a truncated, tampered or schema-mismatched bundle raises
:class:`SessionBundleError` loudly and the restoring process is untouched.
``file_tree_digest`` additionally rejects symlinks and root-escaping entries,
so a crafted bundle cannot make a verifier or restorer read outside its root.

The cooperative protocol is **drain → checkpoint → restore → replay-tail**,
and it is degraded-not-dead while in flight: both halves run under
:func:`torchmetrics_tpu.obs.scope.migration`, so ``/healthz`` answers
``degraded`` with the migrating tenant *named* (``tenants_migrating``) for the
handoff window. Continuous periodic checkpoints deliberately do NOT announce a
migration — a healthy cadence must not flap ``/healthz``.

Zero-loss contract (asserted by the test suite and the rolling-deploy chaos
scenario): a session checkpointed mid-stream, restored elsewhere, tail
replayed, then fed the remainder of the stream computes values **bit-identical**
to an unmigrated control. The crash contract (the ``host_crash`` scenario) is
the same modulo the replay gap: restore + gap re-feed is bit-identical too.

Operator CLI::

    python -m torchmetrics_tpu.engine.migrate verify <bundle>

chain-aware verification; exit 0 = intact, 1 = corrupt, 2 = cannot run.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import sys
import time
import uuid
from dataclasses import dataclass, replace
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Union

import numpy as np

import torchmetrics_tpu.obs.audit as _audit
import torchmetrics_tpu.obs.lineage as _lineage
import torchmetrics_tpu.obs.scope as _scope
import torchmetrics_tpu.obs.trace as _trace
import torchmetrics_tpu.obs.values as _values
from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.engine.pipeline import MetricPipeline, PipelineConfig, _normalize_batch
from torchmetrics_tpu.utils import checkpoint as _checkpoint
from torchmetrics_tpu.utils.checkpoint import CheckpointIntegrityError
from torchmetrics_tpu.utils.prints import rank_zero_warn

__all__ = [
    "SESSION_SCHEMA",
    "CheckpointPolicy",
    "ContinuousCheckpointer",
    "FencedBundleError",
    "SessionBundleError",
    "checkpoint_session",
    "checkpoint_staleness_rule",
    "compact_chain",
    "fence_epoch",
    "fenced_epochs",
    "latest_valid_bundle",
    "restore_session",
    "sweep_bundles",
    "verify_bundle",
]

# wire-format version of a session bundle; bump on any structural change —
# restores REJECT unknown versions (a silently reinterpreted session would
# break the bit-identity promise without saying so). 2: delta bundles
# (bundle_id / base linkage / per-entry content hashes / segmented leaves).
# 3: lease stamp (holder id, session epoch, expiry) in the manifest — the
# fencing token. Schema-2 bundles stay restorable: every field 3 adds is
# additive, and a pre-lease session simply mints its lease on restore.
SESSION_SCHEMA = 3
_COMPAT_SCHEMAS = (2, 3)
_BUNDLE_KIND = "tm_tpu_session"

_MANIFEST_NAME = "MANIFEST.json"
_INTEGRITY_NAME = "INTEGRITY.json"
_STATE_NAME = "state.npz"
_TAIL_NAME = "tail.npz"
# durable fence marker, sibling of the bundle stream: epoch -> fence record
# ({holder, by, target, fenced_unix, known}). `known` snapshots the bundle
# names present at fence time — the rejection rule is "fenced epoch AND not
# in known", so pre-fence bundles stay restorable and the zombie's later
# writes are dead on arrival, with no cross-host clock comparison anywhere.
_FENCE_NAME = "FENCED.json"

# leaves larger than this are split into fixed segments, each content-hashed
# independently — an append-only MaskedBuffer's delta only rewrites the
# segments its appends touched instead of the whole capacity buffer
DEFAULT_SEGMENT_BYTES = 1 << 16

# PipelineConfig knobs that serialize into the manifest (everything except
# live objects: device handles, alert engines, admission controllers — those
# are the restoring host's to supply)
_CONFIG_FIELDS = (
    "fuse",
    "max_in_flight",
    "prefetch",
    "fuse_buckets",
    "flight_records",
    "flight_max_dumps",
    "alert_every",
    "max_deferred",
    "tenant",
    "lease_seconds",
)


class SessionBundleError(CheckpointIntegrityError):
    """The session bundle on disk cannot be trusted (truncated, tampered,
    half-written, chain-broken, or written by an incompatible schema)."""


class FencedBundleError(SessionBundleError):
    """The bundle was written under a fenced-out session epoch *after* the
    fence landed — a zombie host's late write. Counted, never restored."""


@dataclass
class CheckpointPolicy:
    """Continuous-checkpointing cadence for a live session.

    Attach to ``PipelineConfig.checkpoint`` (or ``MuxConfig.checkpoint``) and
    the session writes crash-consistent bundles into ``directory`` every
    ``every_batches`` committed batches and/or ``every_seconds`` wall seconds,
    checked only at chunk-commit boundaries — so every bundle is
    chunk-consistent with zero drain. The replay gap an unplanned death pays
    is the batches committed since the last cadence trigger plus the open
    fusion chunk: worst case ``every_batches + fuse - 2`` (exactly the
    cadence when ``fuse <= 2``; size the cadence ≥ the fusion depth to keep
    the bound tight).

    Args:
        directory: where the bundle stream lands (``bundle-000000``,
            ``bundle-000001``, ...). One session per directory.
        every_batches: write after this many committed batches since the last
            bundle (``0`` disables the batch cadence).
        every_seconds: write when this much wall time elapsed since the last
            bundle, checked at commit boundaries (``0`` disables).
        full_every: every Nth bundle is a **full** compaction point; the
            bundles between are deltas against their predecessor (so a restore
            chain is at most ``full_every`` links).
        keep: retention — the sweep after each write keeps the newest ``keep``
            bundles plus every chain link they depend on, and removes the
            rest.
        stale_after_seconds: operator SLO on checkpoint freshness — a tenant
            session whose last successful bundle is older than this flips
            ``/healthz`` degraded with the tenant named (and feeds
            :func:`checkpoint_staleness_rule`). ``None`` disables.
        segment_bytes: leaves larger than this are split into fixed segments
            for per-segment delta hashing.
    """

    directory: str
    every_batches: int = 0
    every_seconds: float = 0.0
    full_every: int = 8
    keep: int = 4
    stale_after_seconds: Optional[float] = None
    segment_bytes: int = DEFAULT_SEGMENT_BYTES

    def __post_init__(self) -> None:
        if not self.directory or not isinstance(self.directory, str):
            raise ValueError(f"Expected a bundle `directory`, got {self.directory!r}")
        if self.every_batches < 0:
            raise ValueError(f"Expected `every_batches` >= 0, got {self.every_batches}")
        if self.every_seconds < 0:
            raise ValueError(f"Expected `every_seconds` >= 0, got {self.every_seconds}")
        if not self.every_batches and not self.every_seconds:
            raise ValueError(
                "CheckpointPolicy needs a cadence: set `every_batches` and/or"
                " `every_seconds`"
            )
        if self.full_every < 1:
            raise ValueError(f"Expected `full_every` >= 1, got {self.full_every}")
        if self.keep < 1:
            raise ValueError(f"Expected `keep` >= 1, got {self.keep}")
        if self.segment_bytes < 1024:
            raise ValueError(f"Expected `segment_bytes` >= 1024, got {self.segment_bytes}")
        if self.stale_after_seconds is not None and self.stale_after_seconds <= 0:
            raise ValueError(
                f"Expected positive `stale_after_seconds` (or None), got"
                f" {self.stale_after_seconds}"
            )


# ------------------------------------------------------------------ internals


def _entry_hash(arr: Any) -> str:
    """Content hash of one state entry: dtype + shape + bytes."""
    arr = np.asarray(arr)
    digest = hashlib.sha256()
    digest.update(str(arr.dtype).encode())
    digest.update(str(arr.shape).encode())
    digest.update(np.ascontiguousarray(arr).tobytes())
    return digest.hexdigest()


def _encode_tree(
    tree: Any, segment_bytes: int = DEFAULT_SEGMENT_BYTES
) -> Tuple[Any, Dict[str, np.ndarray]]:
    """Split a host-state pytree (nested dicts, numpy leaves) into a JSON
    skeleton + an npz array payload.

    Leaves become ``{"__leaf__": "s<N>"}`` placeholders; the skeleton keeps
    empty containers (unlike orbax, which drops them — and unlike orbax, the
    writer involves no multihost barrier, so one host can checkpoint while
    its peers keep serving). Leaves larger than ``segment_bytes`` are split
    into fixed 1-D segments (``s<N>.p0``, ``s<N>.p1``, ...) so the delta
    writer can skip the segments an append-only state did not touch; their
    placeholder carries ``segments``/``dtype``/``shape`` for reassembly.
    """
    arrays: Dict[str, np.ndarray] = {}
    counter = [0]

    def walk(node: Any) -> Any:
        if isinstance(node, dict):
            return {key: walk(value) for key, value in node.items()}
        arr = np.asarray(node)
        key = f"s{counter[0]}"
        counter[0] += 1
        if segment_bytes and arr.dtype != object and arr.nbytes > segment_bytes:
            flat = np.ascontiguousarray(arr).reshape(-1)
            per = max(1, segment_bytes // max(1, arr.itemsize))
            n_seg = (flat.size + per - 1) // per
            for i in range(n_seg):
                arrays[f"{key}.p{i}"] = flat[i * per : (i + 1) * per]
            return {
                "__leaf__": key,
                "segments": n_seg,
                "dtype": str(arr.dtype),
                "shape": [int(s) for s in arr.shape],
            }
        arrays[key] = arr
        return {"__leaf__": key}

    return walk(tree), arrays


def _decode_tree(skeleton: Any, arrays: Dict[str, np.ndarray]) -> Any:
    def walk(node: Any) -> Any:
        if (
            isinstance(node, dict)
            and isinstance(node.get("__leaf__"), str)
            and (set(node) == {"__leaf__"} or "segments" in node)
        ):
            key = node["__leaf__"]
            if "segments" in node:
                parts = [arrays[f"{key}.p{i}"] for i in range(int(node["segments"]))]
                flat = np.concatenate(parts) if len(parts) > 1 else parts[0]
                return flat.reshape(tuple(node.get("shape") or ()))
            return arrays[key]
        return {key: walk(value) for key, value in node.items()}

    return walk(skeleton)


def _driven_metrics(target: Union[Metric, MetricCollection]) -> List[Tuple[str, Metric]]:
    """(label, metric) pairs the session drives — collections flatten by name."""
    if isinstance(target, MetricCollection):
        return list(target._modules.items())
    return [("", target)]


def _serialize_tail(
    tail: List[tuple]
) -> Tuple[List[Dict[str, Any]], Dict[str, np.ndarray]]:
    """Split tail batches into a JSON structure + an array payload (npz keys).

    Items are ``(args, kwargs)`` or ``(args, kwargs, trace_id)`` — the batch's
    lineage id (:mod:`torchmetrics_tpu.obs.lineage`) persists verbatim so the
    restoring host's ``replay_tail`` re-feeds it under the identity it was
    originally fed with.
    """
    structure: List[Dict[str, Any]] = []
    arrays: Dict[str, np.ndarray] = {}
    for bi, item in enumerate(tail):
        args, kwargs = item[0], item[1]
        trace_id = item[2] if len(item) > 2 else None
        a_desc: List[Dict[str, Any]] = []
        for ai, leaf in enumerate(args):
            if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
                key = f"b{bi}_a{ai}"
                arrays[key] = np.asarray(leaf)
                a_desc.append({"array": key})
            else:
                a_desc.append({"value": leaf})
        k_desc: Dict[str, Dict[str, Any]] = {}
        for name, leaf in kwargs.items():
            if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
                key = f"b{bi}_k_{name}"
                arrays[key] = np.asarray(leaf)
                k_desc[name] = {"array": key}
            else:
                k_desc[name] = {"value": leaf}
        entry: Dict[str, Any] = {"args": a_desc, "kwargs": k_desc}
        if trace_id is not None:
            entry["trace_id"] = str(trace_id)
        structure.append(entry)
    return structure, arrays


def _deserialize_tail(
    structure: List[Dict[str, Any]], arrays: Dict[str, np.ndarray]
) -> List[tuple]:
    import jax.numpy as jnp

    def leaf(desc: Dict[str, Any]) -> Any:
        if "array" in desc:
            return jnp.asarray(arrays[desc["array"]])
        return desc.get("value")

    batches: List[tuple] = []
    for entry in structure or []:
        args = tuple(leaf(d) for d in entry.get("args") or [])
        kwargs = {name: leaf(d) for name, d in (entry.get("kwargs") or {}).items()}
        batches.append((args, kwargs, entry.get("trace_id")))
    return batches


def _session_values(
    log: Any, tenant: Optional[str], inst_pairs: set
) -> List[Dict[str, Any]]:
    """The value-timeline series belonging to this session: its tenant's
    series plus the driven metric instances' untenanted ones."""
    rows = []
    for row in log.series():
        owns = (tenant is not None and row.get("tenant") == tenant) or (
            (row.get("metric"), row.get("inst")) in inst_pairs
        )
        if owns:
            rows.append(row)
    return rows


def _resolve_value_log(value_log: Any, alert_engine: Any) -> Any:
    """The value log a session actually used: explicit > engine's > global."""
    if value_log is not None:
        return value_log
    log_hook = getattr(alert_engine, "_log", None)
    if callable(log_hook):
        return log_hook()
    return _values.get_log()


def _resolve_engine(explicit: Any, config_engine: Any) -> Any:
    if explicit is not None:
        return explicit
    if config_engine is not None:
        return config_engine
    import torchmetrics_tpu.obs.alerts as _alerts

    return _alerts.get_engine()


def _registry_row(effective_tenant: Optional[str]) -> Optional[Dict[str, Any]]:
    if effective_tenant is None:
        return None
    for row in _scope.get_registry().rows():
        if row["tenant"] == effective_tenant:
            return row
    return None


def _dir_bytes(path: str) -> int:
    total = 0
    for dirpath, _dirnames, filenames in os.walk(path):
        for fname in filenames:
            try:
                total += os.path.getsize(os.path.join(dirpath, fname))
            except OSError:
                pass
    return total


# ---------------------------------------------------------------- bundle write


def _write_bundle(
    path: str,
    core: Dict[str, Any],
    state_tree: Any,
    tail_batches: List[Tuple[tuple, dict]],
    delta_base: Optional[Tuple[str, str, Dict[str, str]]] = None,
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
) -> Dict[str, Any]:
    """Materialize + atomically install one bundle; returns its manifest.

    ``delta_base`` is ``(base_name, base_bundle_id, base_entries)``: entries
    whose content hash matches the base's resolvable set are omitted from this
    bundle's ``state.npz`` and resolved through the chain at restore time.
    """
    state_skeleton, state_arrays = _encode_tree(state_tree, segment_bytes)
    tail_structure, tail_arrays = _serialize_tail(tail_batches)
    entries = {key: _entry_hash(arr) for key, arr in state_arrays.items()}
    if delta_base is not None:
        base_name, base_id, base_entries = delta_base
        written = sorted(key for key, h in entries.items() if base_entries.get(key) != h)
        base_field: Optional[Dict[str, Any]] = {"name": base_name, "bundle_id": base_id}
    else:
        written = sorted(entries)
        base_field = None
    manifest = {
        **core,
        "kind": _BUNDLE_KIND,
        "schema_version": SESSION_SCHEMA,
        "bundle_id": uuid.uuid4().hex,
        "base": base_field,
        "entries": entries,
        "written": written,
        "state_skeleton": state_skeleton,
        "tail": tail_structure,
        "ts_unix": time.time(),
    }
    try:
        manifest_text = json.dumps(manifest, sort_keys=True, indent=2)
    except TypeError as err:
        raise TypeError(
            "Session state carries a non-JSON-serializable leaf (a tail batch's"
            f" static argument, most likely): {err}. Only plain scalars/strings"
            " may ride the tail outside arrays."
        ) from err

    _materialize_bundle(
        path, manifest_text, {key: state_arrays[key] for key in written}, tail_arrays
    )
    return manifest


def _materialize_bundle(
    path: str,
    manifest_text: str,
    state_arrays: Dict[str, np.ndarray],
    tail_arrays: Dict[str, np.ndarray],
) -> str:
    """The low-level bundle writer: temp dir → npz payloads → manifest →
    integrity digest → atomic install. Shared by :func:`_write_bundle` and
    :func:`compact_chain` so the durability discipline has one home."""
    path = os.path.abspath(path)
    tag = f"{os.getpid()}.{uuid.uuid4().hex[:8]}"
    tmp = f"{path}.tmp.{tag}"
    try:
        os.makedirs(tmp, exist_ok=True)
        np.savez(os.path.join(tmp, _STATE_NAME), **state_arrays)
        if tail_arrays:
            np.savez(os.path.join(tmp, _TAIL_NAME), **tail_arrays)
        with open(os.path.join(tmp, _MANIFEST_NAME), "w", encoding="utf-8") as fh:
            fh.write(manifest_text)
        digest = _checkpoint.file_tree_digest(tmp, exclude=(_INTEGRITY_NAME,))
        with open(os.path.join(tmp, _INTEGRITY_NAME), "w", encoding="utf-8") as fh:
            json.dump({"version": 1, "schema": SESSION_SCHEMA, "sha256": digest}, fh)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return _checkpoint.atomic_install_dir(tmp, path, tag)


# ------------------------------------------------------------------ capture


def _capture_pipeline(
    pipe: MetricPipeline,
    path: str,
    drain: bool,
    tail: Iterable[Any] = (),
    alert_engine: Any = None,
    value_log: Any = None,
    delta_base: Optional[Tuple[str, str, Dict[str, str]]] = None,
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
) -> Dict[str, Any]:
    """Capture one pipeline session into a bundle at ``path``.

    ``drain=True`` is the cooperative migration path (open chunk dispatched,
    in-flight window blocked, deferred backlog handed over as the tail).
    ``drain=False`` is the continuous path: the session keeps running — the
    bundle holds exactly the committed (chunk-consistent) state, the deferred
    backlog rides as a *copied* tail, and batches in the open fusion chunk are
    deliberately NOT captured (they are the bounded replay gap an unplanned
    death pays).
    """
    target = pipe.metric
    tenant = pipe.config.tenant
    engine = _resolve_engine(alert_engine, pipe.config.alert_engine)
    log = _resolve_value_log(value_log, engine)

    if drain:
        drained = pipe.drain()
        tail_batches = list(drained) + [_normalize_batch(b) for b in tail]
        deferred_tail = len(drained)
    else:
        tail_batches = [(tuple(a), dict(k), t) for a, k, t in pipe._deferred]
        tail_batches += [_normalize_batch(b) for b in tail]
        deferred_tail = len(tail_batches)
    report = pipe.report()
    # the cursor is the PROCESSED count — batches the state (or its guarded
    # replay) actually consumed. The ingest counter would overcount: batches
    # in the open fusion chunk, and the batch mid-ingest when a signature
    # flush triggers this capture, are not folded yet — claiming them would
    # make the crash-recovery gap re-feed skip real data
    committed = report.fused_batches + report.eager_batches + report.replayed_batches
    report_dict = report.asdict()
    if report_dict["batches"] != committed:
        report_dict["batches"] = committed
    members = _driven_metrics(target)
    robust = {
        label: {"sync_degraded": bool(getattr(m, "sync_degraded", False))}
        for label, m in members
    }
    cursor = {
        "batches_ingested": committed,
        "tail_batches": len(tail_batches),
        # the first this-many tail batches are the origin's admission-
        # deferred backlog: the restore counts them toward deferred_replayed
        # so the accounting balances
        "deferred_tail": deferred_tail,
        "update_counts": {label: int(m.update_count) for label, m in members},
        # the fusion-chunk ordinal continues across a restore so post-restore
        # dispatch spans can never collide with restored flight records'
        # chunk ids (the trace id stays the canonical correlation key)
        "chunk_seq": int(pipe._chunk_seq),
        # batch-lineage identity (obs/lineage.py): restored mints continue the
        # origin's id space. A drained (cooperative) capture hands over the
        # arrival counter verbatim — the tail already carries its pre-minted
        # ids, and fresh batches must not collide with them. A continuous
        # (no-drain) capture hands over the PROCESSED count instead — the open
        # chunk's batches are the crash replay gap, and re-feeding them must
        # re-mint exactly the ordinals they originally carried — but ONLY on a
        # detour-free stream: once any batch was shed or deferred, arrival
        # ordinals and the processed count no longer line up, and a
        # processed-count seq would re-issue ids that already name OTHER
        # batches. Such sessions hand over the arrival counter instead:
        # collision-safety is the invariant, gap-id stability the
        # clean-stream optimization.
        "lineage": {
            "epoch": pipe._lineage_epoch,
            "seq": int(pipe._lineage_seq)
            if (drain or report.shed_batches or report.deferred_batches)
            else committed,
        },
    }
    inst_pairs = {
        (type(m).__name__, str(getattr(m, "_obs_instance", "0"))) for _, m in members
    }
    config_fields = {name: getattr(pipe.config, name) for name in _CONFIG_FIELDS}
    if config_fields["fuse_buckets"] is not None:
        config_fields["fuse_buckets"] = list(config_fields["fuse_buckets"])
    core = {
        "tenant": tenant,
        "metric_class": type(target).__name__,
        "collection": isinstance(target, MetricCollection),
        "members": [label for label, _ in members if label],
        "config": config_fields,
        "cursor": cursor,
        "report": report_dict,
        "robust": robust,
        "flight": pipe.flight_snapshot(),
        "values": _session_values(log, pipe._tenant, inst_pairs),
        "alerts": engine.export_state() if engine is not None else None,
        "registry": _registry_row(pipe._tenant),
        # the lease stamp: holder id, session epoch (the fencing token),
        # expiry. Every bundle write doubles as a cross-host lease renewal —
        # the snapshot refreshes the lease before stamping it.
        "lease": pipe.lease_snapshot(),
    }
    manifest = _write_bundle(
        path, core, _checkpoint._tree_of(target), tail_batches, delta_base, segment_bytes
    )
    if _trace.ENABLED:
        _trace.event(
            "engine.session_checkpoint",
            pipeline=type(target).__name__,
            tenant=tenant,
            batches=committed,
            tail=len(tail_batches),
            delta=manifest.get("base") is not None,
            path=os.path.abspath(path),
        )
    return manifest


def _capture_mux_slice(
    mux: Any,
    tenant: str,
    path: str,
    flush_pending: bool,
    alert_engine: Any = None,
    value_log: Any = None,
    delta_base: Optional[Tuple[str, str, Dict[str, str]]] = None,
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
) -> Dict[str, Any]:
    """Extract ONE tenant's slice of a live multiplexer into a bundle.

    The bundle is pipeline-restorable: :func:`restore_session` builds a
    :class:`MetricPipeline` session for the tenant on the restoring host. With
    ``flush_pending`` (the cooperative extraction path) the tenant's open mux
    row is dispatched first and its deferred backlog leaves with the session;
    the continuous path copies the backlog without disturbing the stream.
    """
    effective = mux._aliases.get(tenant, tenant)
    if effective not in mux._metrics:
        raise ValueError(f"Tenant {tenant!r} is not multiplexed")
    if flush_pending:
        mux._flush_pending(effective)
    target = mux._metrics[effective]
    engine = _resolve_engine(alert_engine, mux.config.alert_engine)
    log = _resolve_value_log(value_log, engine)
    if flush_pending:
        backlog = mux._deferred.pop(effective, None) or []
        if _audit.ENABLED and backlog:
            # the backlog leaves with the bundle: conserved as handed-off
            # work, completed by the restoring session under its own ledger
            _audit.note_handed_off(mux, "mux", effective, len(backlog))
    else:
        backlog = list(mux._deferred.get(effective) or [])
    tail_batches = [(tuple(a), dict(k), t) for a, k, t in backlog]
    # the PROCESSED count (fused commits + eager + replays) — a row pending in
    # an open group is deliberately not claimed (commit-consistency)
    committed = int(mux._tenant_folded.get(effective, 0))
    members = _driven_metrics(target)
    robust = {
        label: {"sync_degraded": bool(getattr(m, "sync_degraded", False))}
        for label, m in members
    }
    cursor = {
        "batches_ingested": committed,
        "tail_batches": len(tail_batches),
        "deferred_tail": len(tail_batches),
        "update_counts": {label: int(m.update_count) for label, m in members},
        # lineage identity: the restored pipeline session keeps minting in the
        # mux's id space for this tenant. The tenant-local ARRIVAL counter
        # carries over on the cooperative (flushed) path and whenever THIS
        # tenant ever shed or deferred — arrival and processed ordinals no
        # longer line up then, and a processed-count seq would re-issue ids
        # that already name other rows. Only a detour-free continuous capture
        # hands over the processed count, so a crash gap re-feed re-mints the
        # lost pending row's exact id (the pipeline capture's rule, mirrored
        # per tenant).
        "lineage": {
            "epoch": mux._lineage_epoch,
            "seq": int(mux._tenant_arrivals.get(effective, 0))
            if (flush_pending or mux._tenant_detours.get(effective, 0))
            else committed,
        },
    }
    inst_pairs = {
        (type(m).__name__, str(getattr(m, "_obs_instance", "0"))) for _, m in members
    }
    # the tenant's slice of the shared mux flight ring: tenant-local ordinals,
    # exactly the lineage a restored pipeline session should dump as context
    records = [dict(r) for r in mux.flight_records() if r.get("tenant") == effective]
    defaults = PipelineConfig.__dataclass_fields__
    config_fields = {
        "fuse": defaults["fuse"].default,
        "max_in_flight": defaults["max_in_flight"].default,
        "prefetch": defaults["prefetch"].default,
        "fuse_buckets": None,
        "flight_records": mux.config.flight_records,
        "flight_max_dumps": mux.config.flight_max_dumps,
        "alert_every": mux.config.alert_every,
        "max_deferred": mux.config.max_deferred,
        "tenant": effective,
        "lease_seconds": mux.config.lease_seconds,
    }
    core = {
        "tenant": effective,
        "metric_class": type(target).__name__,
        "collection": isinstance(target, MetricCollection),
        "members": [label for label, _ in members if label],
        "config": config_fields,
        "cursor": cursor,
        # a mux slice has no per-tenant pipeline report; the restored session
        # continues from the tenant-local ingest count
        "report": {"batches": committed, "deferred_batches": len(tail_batches)},
        "robust": robust,
        "flight": {"records": records, "dumps_written": 0, "dumps_suppressed": 0},
        "values": _session_values(log, effective, inst_pairs),
        "alerts": engine.export_state() if engine is not None else None,
        "registry": _registry_row(effective),
        "mux_slice": True,
        # the mux holds ONE lease (one session epoch) covering every tenant;
        # each slice stamps it, renewed, so any slice write renews cross-host
        "lease": mux.lease_snapshot(effective),
    }
    manifest = _write_bundle(
        path, core, _checkpoint._tree_of(target), tail_batches, delta_base, segment_bytes
    )
    if _trace.ENABLED:
        _trace.event(
            "engine.session_checkpoint",
            pipeline=f"Mux[{type(target).__name__}]",
            tenant=effective,
            batches=committed,
            tail=len(tail_batches),
            delta=manifest.get("base") is not None,
            path=os.path.abspath(path),
        )
    return manifest


def _is_mux(obj: Any) -> bool:
    return hasattr(obj, "_aliases") and hasattr(obj, "_tenant_batch_index")


# ---------------------------------------------------------------- checkpoint


def checkpoint_session(
    pipe: Any,
    path: str,
    tail: Iterable[Any] = (),
    alert_engine: Any = None,
    value_log: Any = None,
    tenant: Optional[str] = None,
    delta_base: Optional[str] = None,
) -> Dict[str, Any]:
    """Atomically checkpoint a *live* session to a bundle at ``path``.

    ``pipe`` is a :class:`MetricPipeline` — drained first (open chunk
    dispatched, in-flight window blocked — the **cursor**: metric state is now
    exactly the fold of every dispatched batch) — or a live
    :class:`~torchmetrics_tpu.engine.mux.TenantMultiplexer`, in which case
    ``tenant`` names the ONE tenant whose slice is extracted (its pending mux
    row dispatched, its deferred backlog handed over as the tail) into a
    pipeline-restorable bundle.

    Persists the full session: metric state (the ``__robust__``-aware
    ``state_dict``), the replay tail (the drained admission-deferred backlog
    plus any ``tail`` batches the caller buffered while draining — each item a
    positional tuple, a kwargs dict, or a single array), the flight-recorder
    ring, the accounting report, the tenant registry row, the session's value
    timelines, and the alert engine's live state machines + history.

    ``delta_base`` names an existing bundle to delta against: unchanged state
    entries (per-leaf/per-segment content hash) are resolved through the chain
    instead of rewritten. ``alert_engine`` defaults to the session's
    configured engine, else the process-global one; ``value_log`` to the
    engine's log, else the global. Runs under ``scope.migration(tenant,
    "checkpoint")`` so ``/healthz`` names the tenant while the drain+write is
    in flight. Returns the manifest.
    """
    base: Optional[Tuple[str, str, Dict[str, str]]] = None
    if delta_base is not None:
        base_path = os.path.abspath(delta_base)
        # writer's view: a fenced session may keep spooling (its bundles land
        # and recovery rejects them), so the base verify skips the fence check
        base_manifest = verify_bundle(base_path, check_fence=False)
        if os.path.dirname(base_path) != os.path.dirname(os.path.abspath(path)):
            raise SessionBundleError(
                f"Delta base {base_path} must be a sibling of the new bundle"
                f" {os.path.abspath(path)} — chains resolve base links by sibling"
                " name so a bundle directory migrates as one unit."
            )
        base = (
            os.path.basename(base_path),
            base_manifest["bundle_id"],
            dict(base_manifest.get("entries") or {}),
        )

    if _is_mux(pipe):
        if tenant is None:
            raise ValueError(
                "checkpoint_session on a TenantMultiplexer needs `tenant=` — a mux"
                " bundle is one tenant's pipeline-restorable slice"
            )
        effective = pipe._aliases.get(tenant, tenant)
        with _scope.migration(effective, "checkpoint"):
            return _capture_mux_slice(
                pipe,
                tenant,
                path,
                flush_pending=True,
                alert_engine=alert_engine,
                value_log=value_log,
                delta_base=base,
            )
    if tenant is not None:
        raise ValueError("`tenant=` applies only to TenantMultiplexer checkpoints")

    session_tenant = pipe.config.tenant
    ctx = _scope.migration(session_tenant, "checkpoint") if session_tenant is not None else None
    if ctx is not None:
        ctx.__enter__()
    try:
        return _capture_pipeline(
            pipe,
            path,
            drain=True,
            tail=tail,
            alert_engine=alert_engine,
            value_log=value_log,
            delta_base=base,
        )
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)


# ------------------------------------------------------------------ fencing


def _fence_path(directory: str) -> str:
    return os.path.join(os.path.abspath(directory), _FENCE_NAME)


def _bundle_epoch(manifest: Dict[str, Any]) -> Optional[str]:
    """The session epoch a bundle was written under — its fencing token.

    Schema-3 bundles carry it in the lease stamp; schema-2 bundles fall back
    to the lineage cursor's epoch, so even pre-lease sessions can be fenced.
    """
    lease = manifest.get("lease")
    if isinstance(lease, dict) and lease.get("epoch"):
        return str(lease["epoch"])
    lineage = (manifest.get("cursor") or {}).get("lineage") or {}
    epoch = lineage.get("epoch")
    return str(epoch) if epoch else None


def fenced_epochs(directory: str) -> Dict[str, Dict[str, Any]]:
    """Read the durable fence records under ``directory``: ``{epoch: record}``.

    Missing or unreadable markers read as "nothing fenced" — fencing must
    never make an intact, unfenced bundle stream unrestorable. Records found
    on disk are mirrored into the scope fence registry, so any process that
    scans the directory can name the fenced tenant on ``/healthz`` and
    attribute post-fence trace ids.
    """
    try:
        with open(_fence_path(directory), encoding="utf-8") as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        return {}
    records = payload.get("fences") if isinstance(payload, dict) else None
    if not isinstance(records, dict):
        return {}
    out: Dict[str, Dict[str, Any]] = {}
    for epoch, record in records.items():
        if not isinstance(record, dict):
            continue
        out[str(epoch)] = record
        _scope.note_fence(
            str(epoch),
            tenant=record.get("tenant"),
            holder=record.get("holder"),
            by=record.get("by"),
            target=record.get("target"),
            fenced_unix=record.get("fenced_unix"),
        )
    return out


def fence_epoch(
    directory: str,
    epoch: str,
    *,
    tenant: Optional[str] = None,
    holder: Optional[str] = None,
    by: Optional[str] = None,
    target: Optional[str] = None,
) -> Dict[str, Any]:
    """Durably fence session ``epoch`` out of ``directory``'s bundle stream.

    Writes (atomically) a fence record into ``FENCED.json`` next to the
    bundles. The record snapshots the bundle names present *now* (``known``):
    those stay restorable; any bundle the fenced holder writes later carries
    the fenced epoch but is not in ``known``, so every recovery-path verify
    rejects it (:class:`FencedBundleError`) — the failover must therefore
    fence FIRST and only then select its restore bundle. Idempotent per
    epoch: the first record (and its ``known`` snapshot) wins. Returns the
    record and mirrors it into the scope fence registry.
    """
    from torchmetrics_tpu.utils.fileio import atomic_write_text

    directory = os.path.abspath(directory)
    existing = fenced_epochs(directory)
    if str(epoch) in existing:
        return existing[str(epoch)]
    known = sorted(
        name
        for name in (os.listdir(directory) if os.path.isdir(directory) else ())
        if os.path.isdir(os.path.join(directory, name))
        and ".tmp." not in name
        and ".old." not in name
    )
    record = {
        "epoch": str(epoch),
        "tenant": tenant,
        "holder": holder,
        "by": by,
        "target": target,
        "fenced_unix": time.time(),
        "known": known,
    }
    records = {**existing, str(epoch): record}
    os.makedirs(directory, exist_ok=True)
    atomic_write_text(
        _fence_path(directory),
        json.dumps({"version": 1, "fences": records}, sort_keys=True, indent=2),
    )
    _scope.note_fence(
        str(epoch),
        tenant=tenant,
        holder=holder,
        by=by,
        target=target,
        fenced_unix=record["fenced_unix"],
    )
    if _trace.ENABLED:
        _trace.event(
            "engine.fence",
            tenant=tenant,
            epoch=str(epoch),
            holder=holder,
            by=by,
            target=target,
            known=len(known),
        )
    return record


def _check_fence(path: str, manifest: Dict[str, Any]) -> None:
    """Reject ``path`` if it was written under a fenced epoch after the fence."""
    fences = fenced_epochs(os.path.dirname(os.path.abspath(path)))
    if not fences:
        return
    epoch = _bundle_epoch(manifest)
    record = fences.get(epoch) if epoch else None
    if record is None:
        return
    if os.path.basename(os.path.abspath(path)) in (record.get("known") or ()):
        return  # written before the fence: stays restorable
    raise FencedBundleError(
        f"Session bundle at {path} was written under fenced-out epoch {epoch}"
        f" (holder {record.get('holder')!r}, fenced by {record.get('by')!r}) AFTER"
        " the fence landed — a zombie host's late write; refusing to restore"
        " from it."
    )


# ------------------------------------------------------------------- verify


def _verify_one(path: str, check_fence: bool = True) -> Dict[str, Any]:
    """Verify ONE bundle directory (digest + schema + kind); returns its manifest."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise SessionBundleError(f"No session bundle at {path}")
    integrity_path = os.path.join(path, _INTEGRITY_NAME)
    if not os.path.isfile(integrity_path):
        raise SessionBundleError(
            f"Session bundle at {path} has no {_INTEGRITY_NAME} — bundles are always"
            " written with an integrity record, so this is a partial copy or a"
            " directory that is not a session bundle; refusing to restore from it."
        )
    try:
        with open(integrity_path, encoding="utf-8") as fh:
            recorded = json.load(fh)
    except (OSError, ValueError) as err:
        raise SessionBundleError(
            f"Session bundle at {path} has an unreadable {_INTEGRITY_NAME} ({err}) —"
            " the record itself is truncated or tampered; restore from another bundle."
        ) from err
    try:
        digest = _checkpoint.file_tree_digest(path, exclude=(_INTEGRITY_NAME,))
    except SessionBundleError:
        raise
    except CheckpointIntegrityError as err:
        # the path-traversal guard: symlinks / root-escaping entries
        raise SessionBundleError(str(err)) from err
    if digest != recorded.get("sha256"):
        raise SessionBundleError(
            f"Session bundle at {path} failed its integrity check (recorded"
            f" {str(recorded.get('sha256'))[:12]}…, recomputed {digest[:12]}…) —"
            " the bundle was corrupted after the checkpoint; restore from another one."
        )
    try:
        with open(os.path.join(path, _MANIFEST_NAME), encoding="utf-8") as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as err:
        raise SessionBundleError(
            f"Session bundle at {path} has an unreadable {_MANIFEST_NAME} ({err})"
        ) from err
    if not isinstance(manifest, dict) or manifest.get("kind") != _BUNDLE_KIND:
        raise SessionBundleError(
            f"Directory at {path} verifies but is not a session bundle"
            f" (kind={manifest.get('kind') if isinstance(manifest, dict) else None!r})"
        )
    if manifest.get("schema_version") not in _COMPAT_SCHEMAS:
        raise SessionBundleError(
            f"Session bundle at {path} carries schema"
            f" {manifest.get('schema_version')!r} but this build speaks"
            f" {sorted(_COMPAT_SCHEMAS)} — re-checkpoint with a matching build (a"
            " silently reinterpreted session would break the zero-loss contract)."
        )
    if check_fence:
        _check_fence(path, manifest)
    return manifest


def _chain_manifests(
    path: str, manifest: Dict[str, Any], check_fence: bool = True
) -> List[Tuple[str, Dict[str, Any]]]:
    """Verify + return the whole delta chain, newest first.

    Each link is digest-verified, its ``bundle_id`` must match what the delta
    above it recorded (a *substituted* base — valid on its own but not the one
    the delta was written against — is rejected), and every state entry of the
    top manifest must resolve to some link that wrote it with the same content
    hash.
    """
    path = os.path.abspath(path)
    chain: List[Tuple[str, Dict[str, Any]]] = [(path, manifest)]
    seen = {path}
    current_path, current = path, manifest
    while current.get("base"):
        base = current["base"] or {}
        name = base.get("name")
        if (
            not isinstance(name, str)
            or not name
            or "/" in name
            or os.sep in name
            or name in (".", "..")
        ):
            raise SessionBundleError(
                f"Session bundle at {current_path} names an unusable delta base"
                f" {name!r} — base links are plain sibling directory names."
            )
        base_path = os.path.join(os.path.dirname(current_path), name)
        if base_path in seen:
            raise SessionBundleError(
                f"Session bundle chain at {path} is cyclic (revisits {base_path})."
            )
        base_manifest = _verify_one(base_path, check_fence=check_fence)
        if base_manifest.get("bundle_id") != base.get("bundle_id"):
            raise SessionBundleError(
                f"Session bundle at {current_path} was written against base"
                f" bundle_id {base.get('bundle_id')!r} but {base_path} carries"
                f" {base_manifest.get('bundle_id')!r} — the base was replaced after"
                " the delta was written; the chain cannot be trusted."
            )
        chain.append((base_path, base_manifest))
        seen.add(base_path)
        current_path, current = base_path, base_manifest
    needed = dict(chain[0][1].get("entries") or {})
    for _link_path, link_manifest in chain:
        link_entries = link_manifest.get("entries") or {}
        for key in link_manifest.get("written") or []:
            if key in needed and link_entries.get(key) == needed[key]:
                needed.pop(key)
        if not needed:
            break
    if needed:
        raise SessionBundleError(
            f"Session bundle at {path} cannot resolve state entries"
            f" {sorted(needed)} anywhere in its {len(chain)}-link chain — a link"
            " was removed or truncated; restore from another bundle."
        )
    return chain


def verify_bundle(path: str, chain: bool = True, check_fence: bool = True) -> Dict[str, Any]:
    """Verify a session bundle's integrity + schema; returns its manifest.

    Loud by design: a missing bundle, a missing/unreadable integrity record, a
    file-tree digest mismatch (truncation, tampering, a half-copied rsync), a
    symlinked or root-escaping entry, an unreadable manifest, or a schema/kind
    mismatch each raise :class:`SessionBundleError` **before any state is
    touched** — restoring from a bad bundle must never poison the restoring
    process. With ``chain=True`` (the default) a delta bundle's whole base
    chain is walked and verified the same way, including base-id linkage and
    full entry resolvability. With ``check_fence=True`` (the default) a bundle
    written under a fenced-out session epoch *after* the fence landed raises
    :class:`FencedBundleError` — recovery paths must never trust a zombie
    host's late writes. ``check_fence=False`` is the *writer's* view: a fenced
    session may keep spooling bundles locally (they land, and every recovery
    scan rejects them), so the fence guards restores, not writes.
    """
    manifest = _verify_one(path, check_fence=check_fence)
    if chain and manifest.get("base"):
        _chain_manifests(path, manifest, check_fence=check_fence)
    return manifest


def _load_state_arrays(
    path: str,
    manifest: Dict[str, Any],
    chain: Optional[List[Tuple[str, Dict[str, Any]]]] = None,
) -> Dict[str, np.ndarray]:
    """Resolve every state entry through the (verified) chain, hash-checked.

    ``chain`` reuses an already-verified :func:`_chain_manifests` walk so a
    caller that just verified the bundle does not re-digest every link."""
    if chain is None:
        chain = _chain_manifests(os.path.abspath(path), manifest)
    needed = dict(manifest.get("entries") or {})
    arrays: Dict[str, np.ndarray] = {}
    for link_path, link_manifest in chain:
        if not needed:
            break
        link_entries = link_manifest.get("entries") or {}
        want = [
            key
            for key in (link_manifest.get("written") or [])
            if key in needed and link_entries.get(key) == needed[key]
        ]
        if not want:
            continue
        state_path = os.path.join(link_path, _STATE_NAME)
        with np.load(state_path) as payload:
            for key in want:
                arr = payload[key]
                if _entry_hash(arr) != needed[key]:
                    raise SessionBundleError(
                        f"State entry {key!r} loaded from {link_path} does not match"
                        " the content hash the manifest recorded — the chain was"
                        " tampered with after verification; restore from another"
                        " bundle."
                    )
                arrays[key] = arr
                needed.pop(key)
    if needed:  # pragma: no cover - _chain_manifests already proved resolvability
        raise SessionBundleError(
            f"Session bundle at {path} is missing state entries {sorted(needed)}"
        )
    return arrays


# ------------------------------------------------------------------- recovery


def latest_valid_bundle(directory: str) -> Optional[str]:
    """Newest bundle under ``directory`` whose whole chain verifies, or None.

    The unplanned-death restore point: a SIGKILL'd host's bundle directory may
    end with a half-written ``.tmp.*`` sibling or a corrupted link — those are
    skipped **loudly** (one ``RuntimeWarning`` naming every skipped entry and
    why, plus the ``checkpoint.torn_bundles`` gauge counting every torn/corrupt
    skip) and the newest intact bundle wins. A bundle written under a
    fenced-out epoch after its fence landed (a zombie host's late write) is
    likewise never selected — rejected with its own warning and counted into
    ``fence.bundles_rejected``. Bundles are ordered by their manifest
    ``ts_unix`` (name as tie-break), not directory mtime — a restore must
    never prefer a stale bundle a copy touched last.
    """
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    candidates: List[Tuple[float, str, str]] = []
    skipped: List[Tuple[str, str]] = []
    torn = 0
    fenced: List[Tuple[str, str]] = []
    for name in sorted(os.listdir(directory)):
        full = os.path.join(directory, name)
        if not os.path.isdir(full):
            continue
        if ".tmp." in name or ".old." in name:
            skipped.append((name, "mid-write temp/displaced sibling"))
            continue
        try:
            manifest = verify_bundle(full)
        except FencedBundleError as err:
            fenced.append((name, str(err).split("\n")[0][:160]))
            continue
        except SessionBundleError as err:
            skipped.append((name, str(err).split("\n")[0][:160]))
            torn += 1
            continue
        candidates.append((float(manifest.get("ts_unix") or 0.0), name, full))
    if skipped:
        detail = "; ".join(f"{name}: {reason}" for name, reason in skipped)
        rank_zero_warn(
            f"Skipped {len(skipped)} invalid or mid-write bundle(s) under"
            f" {directory} while scanning for the latest restore point — {detail}",
            RuntimeWarning,
        )
    if torn:
        _scope.note_torn_bundles(torn)
    if fenced:
        _scope.note_fenced_bundle_rejected(len(fenced))
        detail = "; ".join(f"{name}: {reason}" for name, reason in fenced)
        rank_zero_warn(
            f"Rejected {len(fenced)} post-fence zombie bundle(s) under {directory}"
            f" — written under a fenced-out epoch after its fence landed; never"
            f" selected as a restore point — {detail}",
            RuntimeWarning,
        )
    if not candidates:
        return None
    candidates.sort()
    return candidates[-1][2]


def compact_chain(path: str, out_path: str) -> Dict[str, Any]:
    """Merge a delta chain into ONE standalone full bundle at ``out_path``.

    Restoring the compacted bundle is bit-equivalent to restoring the chain:
    the resolved entry set is re-written whole (same content hashes), the
    manifest's session payload (cursor, report, values, alerts, tail, ...) is
    the top link's, and the new bundle names no base. ``compacted_from``
    records the source ``bundle_id`` for provenance. Returns the new manifest.
    """
    path = os.path.abspath(path)
    manifest = _verify_one(path)
    arrays = _load_state_arrays(path, manifest, chain=_chain_manifests(path, manifest))
    tail_arrays: Dict[str, np.ndarray] = {}
    tail_path = os.path.join(os.path.abspath(path), _TAIL_NAME)
    if os.path.isfile(tail_path):
        with np.load(tail_path) as payload:
            tail_arrays = {key: payload[key] for key in payload.files}

    core = {
        key: value
        for key, value in manifest.items()
        if key
        not in (
            "kind",
            "schema_version",
            "bundle_id",
            "base",
            "entries",
            "written",
            "state_skeleton",
            "tail",
            "ts_unix",
        )
    }
    core["compacted_from"] = manifest["bundle_id"]
    new_manifest = {
        **core,
        "kind": _BUNDLE_KIND,
        "schema_version": SESSION_SCHEMA,
        "bundle_id": uuid.uuid4().hex,
        "base": None,
        "entries": dict(manifest.get("entries") or {}),
        "written": sorted(manifest.get("entries") or {}),
        "state_skeleton": manifest.get("state_skeleton"),
        "tail": manifest.get("tail"),
        "ts_unix": time.time(),
    }
    _materialize_bundle(
        out_path, json.dumps(new_manifest, sort_keys=True, indent=2), arrays, tail_arrays
    )
    return new_manifest


def sweep_bundles(directory: str, keep: int, gc_fenced: bool = True) -> List[str]:
    """Retention sweep: keep the newest ``keep`` bundles **plus every chain
    link they depend on**; remove the rest. Returns removed bundle paths.

    A delta bundle is only as durable as its chain, so the kept set is closed
    over base links — the sweep can never delete a link a live chain resolves
    through. Directories whose manifest cannot be read are left alone (they
    may be a concurrent writer's mid-install state; ``latest_valid_bundle``
    skips them loudly either way).

    ``gc_fenced`` adds the zombie-GC mode: a bundle whose epoch is fenced AND
    whose name is not in the fence-time ``known`` snapshot is a zombie host's
    post-fence write — every recovery scan already rejects it
    (:class:`FencedBundleError`), so retention garbage-collects it regardless
    of recency instead of letting rejected garbage crowd the ``keep`` window.
    Zombies never count toward the kept window, and a kept live chain's base
    closure is never touched even if a link looks fenced. Each zombie GC'd is
    counted into the ``fence.bundles_swept`` gauge.
    """
    if keep < 1:
        raise ValueError(f"Expected `keep` >= 1, got {keep}")
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    manifests: Dict[str, Dict[str, Any]] = {}
    for name in sorted(os.listdir(directory)):
        full = os.path.join(directory, name)
        if not os.path.isdir(full) or ".tmp." in name or ".old." in name:
            continue
        try:
            with open(os.path.join(full, _MANIFEST_NAME), encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, ValueError):
            continue
        if isinstance(manifest, dict) and manifest.get("kind") == _BUNDLE_KIND:
            manifests[name] = manifest
    zombies: set = set()
    if gc_fenced:
        fences = fenced_epochs(directory)
        if fences:
            for name, manifest in manifests.items():
                epoch = _bundle_epoch(manifest)
                record = fences.get(epoch) if epoch else None
                if record is not None and name not in (record.get("known") or ()):
                    zombies.add(name)
    ordered = sorted(
        manifests, key=lambda name: (float(manifests[name].get("ts_unix") or 0.0), name)
    )
    # zombies are unrestorable garbage: they must not occupy the keep window
    # (a wedged host's late writes would otherwise evict the real stream)
    live_ordered = [name for name in ordered if name not in zombies]
    kept = set(live_ordered[-keep:])
    # close over chain dependencies: a kept delta keeps its whole base chain —
    # even through a link the fence ledger flags, the live chain wins
    frontier = list(kept)
    while frontier:
        name = frontier.pop()
        base = (manifests.get(name) or {}).get("base") or {}
        base_name = base.get("name")
        if base_name and base_name in manifests and base_name not in kept:
            kept.add(base_name)
            frontier.append(base_name)
    removed = []
    swept_zombies = 0
    for name in ordered:
        if name in kept:
            continue
        full = os.path.join(directory, name)
        shutil.rmtree(full, ignore_errors=True)
        removed.append(full)
        if name in zombies:
            swept_zombies += 1
    if swept_zombies:
        _scope.note_fenced_bundle_swept(swept_zombies)
        if _trace.ENABLED:
            _trace.event(
                "engine.fence_sweep", directory=directory, swept=swept_zombies
            )
    return removed


# --------------------------------------------------------------- continuous


class ContinuousCheckpointer:
    """One session's periodic bundle stream under a :class:`CheckpointPolicy`.

    Owned by a :class:`MetricPipeline` (``PipelineConfig.checkpoint``) or, per
    tenant, by a :class:`~torchmetrics_tpu.engine.mux.TenantMultiplexer`
    (``MuxConfig.checkpoint``). Tracks the cadence, names the bundles
    (``bundle-%06d``), keeps the delta base (name + entry hashes) in memory so
    a delta write never re-reads its base, writes every ``full_every``-th
    bundle full (the compaction point), runs the retention sweep, feeds the
    ``checkpoint.*`` telemetry, and **never lets a failing write break the
    stream** (warn once, count, keep serving).
    """

    def __init__(
        self, policy: CheckpointPolicy, tenant: Optional[str] = None, label: str = "session"
    ) -> None:
        self.policy = policy
        self.tenant = tenant
        self.label = label
        self._seq = 0
        self._seq_seeded = False
        self._last_batches = 0
        self._last_time = time.monotonic()
        self._base: Optional[Tuple[str, str, Dict[str, str]]] = None
        self._warned_failure = False
        self.failures = 0
        self.last_path: Optional[str] = None
        self.stats = {
            "full": {"count": 0, "bytes": 0},
            "delta": {"count": 0, "bytes": 0},
        }

    def due(self, committed_batches: int) -> bool:
        policy = self.policy
        if policy.every_batches and committed_batches - self._last_batches >= policy.every_batches:
            return True
        if policy.every_seconds and time.monotonic() - self._last_time >= policy.every_seconds:
            return True
        return False

    def write(
        self,
        capture: Callable[[str, Optional[Tuple[str, str, Dict[str, str]]], int], Dict[str, Any]],
        committed_batches: int,
        coverage_exact: bool = True,
    ) -> Optional[str]:
        """Write one bundle via ``capture(path, delta_base, segment_bytes)``.

        ``coverage_exact`` says whether ``committed_batches`` also bounds the
        session's ARRIVAL ordinals (a detour-free stream) — only then is the
        bundle noted into the lineage index's /trace covering-checkpoint join.
        """
        policy = self.policy
        if not self._seq_seeded:
            # a restored session continuing an existing directory (crash
            # recovery) must extend the stream, never overwrite a bundle an
            # existing chain still resolves through
            self._seq_seeded = True
            if os.path.isdir(policy.directory):
                taken = [
                    int(name[len("bundle-") :])
                    for name in os.listdir(policy.directory)
                    if name.startswith("bundle-") and name[len("bundle-") :].isdigit()
                ]
                if taken:
                    self._seq = max(taken) + 1
        name = f"bundle-{self._seq:06d}"
        path = os.path.join(policy.directory, name)
        delta_base = (
            self._base if (self._base is not None and self._seq % policy.full_every != 0) else None
        )
        start = time.perf_counter()
        try:
            os.makedirs(policy.directory, exist_ok=True)
            manifest = capture(path, delta_base, policy.segment_bytes)
        except Exception as err:
            self.failures += 1
            if self.tenant is not None:
                _scope.note_checkpoint_failure(self.tenant)
            if _trace.ENABLED:
                _trace.inc("checkpoint.failures", pipeline=self.label)
            if not self._warned_failure:
                self._warned_failure = True
                rank_zero_warn(
                    f"Continuous checkpoint of {self.label!r} could not be written to"
                    f" {path!r}: {type(err).__name__}: {err}. The stream keeps flowing"
                    " and further attempts continue on cadence, but the last-success"
                    " age is growing (checkpoint.last_success_age_seconds /"
                    " /healthz staleness); this warning fires once per session.",
                    RuntimeWarning,
                )
            return None
        seconds = time.perf_counter() - start
        kind = "delta" if manifest.get("base") else "full"
        nbytes = _dir_bytes(path)
        self.stats[kind]["count"] += 1
        self.stats[kind]["bytes"] += nbytes
        self._seq += 1
        self._last_batches = committed_batches
        self._last_time = time.monotonic()
        self._base = (name, manifest["bundle_id"], dict(manifest.get("entries") or {}))
        self.last_path = path
        if self.tenant is not None:
            _scope.note_checkpoint(
                self.tenant,
                path=path,
                nbytes=nbytes,
                kind=kind,
                seconds=seconds,
                stale_after_seconds=policy.stale_after_seconds,
            )
        # batch lineage: this bundle covers the session's first
        # `committed_batches` processed batches — GET /trace/<id> joins a
        # batch against the newest bundle whose cursor is past its ordinal.
        # Only noted on detour-free streams (see note_checkpoint): once a
        # batch was shed/deferred, arrival ordinals and the processed count
        # no longer line up and the join would name the wrong bundle.
        if coverage_exact:
            _lineage.note_checkpoint(self.tenant, path, committed_batches)
        if _trace.ENABLED:
            _trace.inc("checkpoint.bundles", pipeline=self.label, kind=kind)
            _trace.set_gauge("checkpoint.bundle_bytes", float(nbytes), pipeline=self.label, kind=kind)
            _trace.set_gauge("checkpoint.write_seconds", float(seconds), pipeline=self.label)
        try:
            # the writer's own cadence sweep is recency-only: a fenced writer
            # GC'ing its own just-landed bundle would erase the zombie-write
            # evidence recovery scans reject and count. Zombie GC belongs to
            # explicit sweeps — the survivor's failover cleanup, an operator's
            # retention pass — where gc_fenced defaults on.
            sweep_bundles(policy.directory, policy.keep, gc_fenced=False)
        except Exception:  # retention must never cost the stream
            pass
        return path

    def covered(self, committed_batches: int) -> bool:
        """True when the last successful bundle already covers this count —
        the clean-close path skips a byte-identical duplicate write."""
        return self._seq > 0 and committed_batches == self._last_batches

    def maybe_pipeline(
        self,
        pipe: MetricPipeline,
        force: bool = False,
        skip_if_covered: bool = False,
    ) -> Optional[str]:
        """The pipeline's commit-boundary hook: write if the cadence is due.

        ``committed`` counts only processed batches (fused + eager + replayed)
        — never the open fusion chunk or a batch mid-ingest — which is what
        makes every bundle chunk-consistent without a drain.
        """
        report = pipe._report
        committed = report.fused_batches + report.eager_batches + report.replayed_batches
        if skip_if_covered and self.covered(committed):
            return None
        if not force and not self.due(committed):
            return None

        def capture(path: str, delta_base: Any, segment_bytes: int) -> Dict[str, Any]:
            return _capture_pipeline(
                pipe, path, drain=False, delta_base=delta_base, segment_bytes=segment_bytes
            )

        return self.write(
            capture,
            committed,
            coverage_exact=not (report.shed_batches or report.deferred_batches),
        )

    def maybe_mux_slice(
        self,
        mux: Any,
        tenant: str,
        force: bool = False,
        skip_if_covered: bool = False,
    ) -> Optional[str]:
        """One tenant's slice on cadence (the mux gates the sweep; see
        ``TenantMultiplexer._maybe_checkpoint``)."""
        effective = mux._aliases.get(tenant, tenant)
        committed = int(mux._tenant_folded.get(effective, 0))
        if skip_if_covered and self.covered(committed):
            return None
        if not force and not self.due(committed):
            return None

        def capture(path: str, delta_base: Any, segment_bytes: int) -> Dict[str, Any]:
            return _capture_mux_slice(
                mux,
                tenant,
                path,
                flush_pending=False,
                delta_base=delta_base,
                segment_bytes=segment_bytes,
            )

        return self.write(
            capture,
            committed,
            coverage_exact=not mux._tenant_detours.get(effective, 0),
        )


def checkpoint_staleness_rule(
    max_age_seconds: float,
    tenant: str = "*",
    name: str = "checkpoint_stale",
    severity: str = "critical",
    for_seconds: float = 0.0,
) -> Any:
    """An absent-style watchdog over checkpoint freshness.

    A ``threshold`` rule on the ``checkpoint.last_success_age_seconds`` gauge
    (refreshed per ``/metrics`` scrape by :func:`obs.scope.record_gauges`):
    fires when a tenant session's last successful periodic bundle is older
    than ``max_age_seconds`` — the alert-engine twin of the ``/healthz``
    staleness reason, for fleets that page on alerts rather than probes.
    """
    from torchmetrics_tpu.obs.alerts import AlertRule

    return AlertRule(
        name=name,
        kind="threshold",
        series="checkpoint.last_success_age_seconds",
        above=float(max_age_seconds),
        tenant=tenant,
        severity=severity,
        for_seconds=for_seconds,
    )


# ------------------------------------------------------------------- restore


def restore_session(
    metric: Union[Metric, MetricCollection],
    path: str,
    config: Optional[PipelineConfig] = None,
    alert_engine: Any = None,
    value_log: Any = None,
    replay: bool = True,
    restore_registry: bool = True,
    fresh_epoch: bool = False,
    **overrides: Any,
) -> Tuple[MetricPipeline, Dict[str, Any]]:
    """Restore a checkpointed session onto ``metric`` (freshly constructed with
    the same spec — the ``load_checkpoint`` contract); returns ``(pipeline,
    manifest)``.

    The second half of drain→checkpoint→restore→replay-tail (and the whole
    second half of crash recovery): the bundle is verified chain-aware
    (:func:`verify_bundle`, loud), state entries are resolved through the
    delta chain with their content hashes re-checked, metric state is restored
    (update counts, robust counters and ``sync_degraded`` included), a new
    :class:`MetricPipeline` is built from the bundled config (``config=`` or
    keyword ``overrides`` adjust host-local knobs: ``flight_dump_dir``,
    ``device``, ``checkpoint`` policy, ...; ``alert_engine`` attaches the
    restoring host's engine and receives the bundled alert machines with dwell
    clocks intact), the flight ring / report / value timelines / registry row
    are re-installed, and the replay tail is re-fed in order (admission
    bypassed — it was admitted before the checkpoint). With
    ``TM_TPU_COMPILE_CACHE`` shared between hosts, the restored pipeline's
    :meth:`~MetricPipeline.warmup` is persistent-cache reads, so warmup after
    a restore is ~free.

    Runs under ``scope.migration(tenant, "restore")`` — ``/healthz`` stays
    degraded-not-dead with the tenant named until the tail has replayed.
    """
    path = os.path.abspath(path)
    manifest = _verify_one(path)
    # one chain walk serves both verification and entry resolution — every
    # link is digest-checked exactly once per restore
    chain = _chain_manifests(path, manifest)

    if type(metric).__name__ != manifest.get("metric_class"):
        raise SessionBundleError(
            f"Session bundle at {path} was checkpointed from a"
            f" {manifest.get('metric_class')!r} but the restore target is a"
            f" {type(metric).__name__!r} — the target must be constructed with the"
            " checkpointed session's spec."
        )
    is_collection = isinstance(metric, MetricCollection)
    if bool(manifest.get("collection")) != is_collection:
        raise SessionBundleError(
            f"Session bundle at {path} and the restore target disagree on being a"
            " MetricCollection."
        )
    members = _driven_metrics(metric)
    if is_collection:
        want = set(manifest.get("members") or [])
        have = {label for label, _ in members}
        if want != have:
            raise SessionBundleError(
                f"Session bundle at {path} names members {sorted(want)} but the"
                f" restore target holds {sorted(have)} — same-spec restore only."
            )

    try:
        state_arrays = _load_state_arrays(path, manifest, chain=chain)
        tree = _decode_tree(manifest.get("state_skeleton") or {}, state_arrays)
    except SessionBundleError:
        raise
    except Exception as err:
        raise SessionBundleError(
            f"Session bundle at {path} verifies but its state tree is unreadable:"
            f" {err}"
        ) from err

    tenant = manifest.get("tenant")
    ctx = _scope.migration(tenant, "restore") if tenant is not None else None
    if ctx is not None:
        ctx.__enter__()
    try:
        if is_collection:
            for label, m in members:
                _checkpoint._restore_states(m, tree[label])
        else:
            _checkpoint._restore_states(metric, tree)
        robust = manifest.get("robust") or {}
        for label, m in members:
            flags = robust.get(label) or {}
            if flags.get("sync_degraded"):
                m.sync_degraded = True

        if config is None:
            cfg_kwargs = dict(manifest.get("config") or {})
            if cfg_kwargs.get("fuse_buckets") is not None:
                cfg_kwargs["fuse_buckets"] = tuple(cfg_kwargs["fuse_buckets"])
            cfg_kwargs.update(overrides)
            if alert_engine is not None:
                cfg_kwargs["alert_engine"] = alert_engine
            config = PipelineConfig(**cfg_kwargs)
        else:
            if config.tenant is None and tenant is not None:
                overrides = {"tenant": tenant, **overrides}
            if alert_engine is not None:
                overrides = {**overrides, "alert_engine": alert_engine}
            if overrides:
                config = replace(config, **overrides)

        pipe = MetricPipeline(metric, config)
        pipe._restore_report(manifest.get("report") or {})
        pipe._restore_flight(manifest.get("flight") or {})
        # fresh_epoch=True is the FAILOVER restore: the session continues the
        # origin's id sequence but under a brand-new epoch — the new fencing
        # token — so the fenced origin's late writes stay distinguishable
        # from (and rejectable against) everything this session produces. The
        # lease is re-minted either way: a schema-2 (pre-lease) bundle simply
        # gets its first lease here.
        pipe._restore_lineage(manifest.get("cursor") or {}, fresh_epoch=fresh_epoch)

        engine = config.alert_engine
        if engine is None:
            import torchmetrics_tpu.obs.alerts as _alerts

            engine = _alerts.get_engine()
        if engine is not None and manifest.get("alerts"):
            engine.restore_state(manifest["alerts"])
        log = _resolve_value_log(value_log, engine)
        log.restore_series(manifest.get("values") or [])

        row = manifest.get("registry")
        if restore_registry and row and pipe._tenant is not None:
            _scope.get_registry().restore_row(
                pipe._tenant,
                updates=row.get("updates", 0),
                computes=row.get("computes", 0),
                first_seen_unix=row.get("first_seen_unix"),
            )

        if replay:
            arrays: Dict[str, np.ndarray] = {}
            tail_path = os.path.join(path, _TAIL_NAME)
            if os.path.isfile(tail_path):
                with np.load(tail_path) as payload:
                    arrays = {key: payload[key] for key in payload.files}
            batches = _deserialize_tail(manifest.get("tail") or [], arrays)
            pipe.replay_tail(
                batches, deferred=int((manifest.get("cursor") or {}).get("deferred_tail", 0) or 0)
            )
        if _trace.ENABLED:
            _trace.event(
                "engine.session_restore",
                pipeline=type(metric).__name__,
                tenant=tenant,
                batches=(manifest.get("cursor") or {}).get("batches_ingested", 0),
                tail=(manifest.get("cursor") or {}).get("tail_batches", 0),
                path=path,
            )
        return pipe, manifest
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)


# ------------------------------------------------------------------------ CLI


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m torchmetrics_tpu.engine.migrate`` — the operator CLI.

    Mirrors the ``obs.regress`` CLI conventions: one-line verdicts on stdout,
    diagnostics on stderr, exit 0 = intact, 1 = corrupt, 2 = cannot run.
    """
    parser = argparse.ArgumentParser(
        prog="python -m torchmetrics_tpu.engine.migrate",
        description=(
            "Operate on live-session bundles. `verify <bundle>` walks and verifies"
            " the bundle's whole delta chain (per-link file-tree digest, schema,"
            " base-id linkage, entry resolvability). Exit codes: 0 = intact,"
            " 1 = corrupt, 2 = cannot run."
        ),
    )
    sub = parser.add_subparsers(dest="command")
    verify_parser = sub.add_parser(
        "verify", help="chain-aware verification of one session bundle"
    )
    verify_parser.add_argument("bundle", help="path of the bundle directory")
    verify_parser.add_argument(
        "--quiet", action="store_true", help="suppress the summary line on success"
    )
    args = parser.parse_args(argv)
    if args.command != "verify":
        parser.print_usage(sys.stderr)
        return 2
    path = os.path.abspath(args.bundle)
    if not os.path.isdir(path):
        sys.stderr.write(f"cannot run: no directory at {path}\n")
        return 2
    try:
        manifest = verify_bundle(path)
        chain = _chain_manifests(path, manifest) if manifest.get("base") else [(path, manifest)]
    except SessionBundleError as err:
        sys.stderr.write(f"CORRUPT: {err}\n")
        return 1
    except Exception as err:  # unexpected environment failure, not a verdict
        sys.stderr.write(f"cannot run: {type(err).__name__}: {err}\n")
        return 2
    if not args.quiet:
        entries = manifest.get("entries") or {}
        written = manifest.get("written") or []
        print(
            f"OK: {path} — {'delta' if manifest.get('base') else 'full'} bundle,"
            f" chain depth {len(chain)}, tenant {manifest.get('tenant')!r},"
            f" {len(written)}/{len(entries)} entries written locally,"
            f" {(manifest.get('cursor') or {}).get('batches_ingested', 0)} batches"
            " folded"
        )
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess tests
    sys.exit(main())
