"""Streaming evaluation pipeline: prefetch, bounded async dispatch, fused scan chunks.

The stateful ``Metric`` API pays one host dispatch per ``update`` call. That is
already jit-cached and async, but a long evaluation stream still spends host time
issuing thousands of small dispatches, and nothing overlaps the host→device copy
of batch *k+1* with the device compute of batch *k*. :class:`MetricPipeline` sits
between a user's batch stream and the existing ``Metric`` / ``MetricCollection``
machinery and turns the hot loop into what XLA wants:

- **Micro-batch fusion** — up to ``fuse`` same-signature batches are accumulated
  into a chunk, stacked along a leading step axis, and folded into the state with
  ONE ``lax.scan`` dispatch (driving the same ``pure_update`` transitions the
  per-step path uses, so results are bit-identical). Chunk *lengths* are padded
  up to a small set of buckets (powers of two up to ``fuse``) with the padded
  tail masked out of the state inside the scan — a flush of 5 batches and a
  flush of 8 batches share compiled programs instead of each compiling their own,
  so the compiled-variant count feeds the jit layer's recompile-storm guard
  instead of fighting it. A batch whose shapes/statics differ from the open chunk
  flushes it first, preserving stream order exactly.
- **Prefetch** — :meth:`run` keeps ``prefetch`` upcoming batches device-resident
  (``jax.device_put`` issued ahead of use), overlapping host→device transfer with
  device compute.
- **Bounded in-flight dispatch** — the pipeline never calls
  ``block_until_ready`` per step; it holds tickets for up to ``max_in_flight``
  dispatched chunks and only blocks on the oldest when the window is full, so
  the host stays ahead of the device without unbounded queueing.
- **Fault isolation per chunk** — when an error policy is configured
  (``torchmetrics_tpu.robust``), each chunk is screened once for non-finite
  inputs (one host sync per chunk instead of per batch); a poisoned or failing
  chunk degrades to a per-batch replay through the metric's own guarded
  ``update``, so exactly the poisoned batches are skipped/quarantined and the
  rest of the chunk still lands.
- **AOT warmup** — :meth:`warmup` precompiles every (shape-bucket, static-config)
  variant from abstract specs before the loop and wires JAX's persistent
  compilation cache (``TM_TPU_COMPILE_CACHE``, see
  :mod:`torchmetrics_tpu.engine.warmup`), recording a manifest of what was
  compiled and for how long.

- **Flight recorder** — a bounded ring of per-batch lineage records (batch
  index, input signature, fused-chunk id, per-stage timings: prefetch wait /
  device put / dispatch / commit / blocked-on-inflight). When a chunk degrades
  to replay or a batch is quarantined, the ring is dumped as JSONL (atomic,
  ``utils/fileio``) with the poisoned batch named — a fault in production
  arrives with its last-K-batch context, not a bare counter increment.
  ``PipelineConfig.flight_records=0`` disables it; dumps land in
  ``flight_dump_dir`` / ``$TM_TPU_FLIGHT_DIR`` / ``<tempdir>/tm_tpu_flight``.

- **Value-health seam** — with ``PipelineConfig.alert_engine`` set
  (:mod:`torchmetrics_tpu.obs.alerts`), every committed chunk samples the
  target's values sync-free (``obs.values.sample_local``) and evaluates the
  declarative watchdogs; a value rule newly firing mid-stream triggers a
  flight-recorder dump (reason ``value_alert:<rules>``) so a NaN or frozen
  metric arrives with the batch lineage that produced it.

Telemetry (``torchmetrics_tpu.obs``, off by default): ``engine.dispatch`` spans
(carrying numeric ``batch_index``/``chunk_id`` attrs correlatable with the
flight records and Perfetto tracks), queue-depth / in-flight / fused-chunk-size
/ flight-ring gauges, prefetch hit/miss and padded-step counters,
degrade-to-replay and flight-dump events. :meth:`report` returns the same
accounting as plain ints, available without tracing.

Semantics: the pipeline drives **update-only** accumulation (the epoch pattern —
N updates, one ``compute``). Per-batch ``forward`` values are inherently
per-step; streams that need them should call the metric directly.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import time
from collections import deque
from contextlib import nullcontext
from dataclasses import dataclass, fields, replace
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

import torchmetrics_tpu.obs.audit as _audit
import torchmetrics_tpu.obs.lineage as _lineage
import torchmetrics_tpu.obs.scope as _scope
import torchmetrics_tpu.obs.trace as _trace
import torchmetrics_tpu.obs.values as _values
from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.core.jit import (
    StaticLeafJit,
    _ArraySlot,
    _aval_signature,
    jit_with_static_leaves,
    partition_static_leaves,
    signature_str,
)
from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.engine import warmup as _warmup
from torchmetrics_tpu.robust import faults as _faults
from torchmetrics_tpu.robust import fence as _fence
from torchmetrics_tpu.robust.policy import effective_policy, nonfinite_step_indices
from torchmetrics_tpu.utils.fileio import atomic_write_text
from torchmetrics_tpu.utils.prints import rank_zero_warn

__all__ = ["FLIGHT_DIR_ENV", "FLIGHT_SCHEMA", "MetricPipeline", "PipelineConfig", "PipelineReport"]

_SLOT = _ArraySlot()

# where flight-recorder dumps land when the config does not name a directory
FLIGHT_DIR_ENV = "TM_TPU_FLIGHT_DIR"
# wire format of a dump file (meta line `schema` field); bump on structure change
FLIGHT_SCHEMA = 1


@dataclass
class PipelineConfig:
    """Tuning knobs for :class:`MetricPipeline`.

    Args:
        fuse: max batches fused into one ``lax.scan`` dispatch. ``1`` disables
            fusion (per-batch updates, still prefetched and in-flight-bounded).
        max_in_flight: max dispatched-but-unawaited chunks before the pipeline
            blocks on the oldest.
        prefetch: how many upcoming batches :meth:`MetricPipeline.run` keeps
            device-resident ahead of use.
        fuse_buckets: explicit chunk-length buckets (ascending). Default: powers
            of two up to ``fuse`` — a partial flush pads up to the next bucket
            with a masked tail so compiled-variant count stays ``O(log fuse)``
            per batch signature.
        device: target device for prefetched batches (``None``: default device).
        flight_records: flight-recorder ring capacity — the last this-many
            batches keep their lineage (per-stage timings, chunk membership)
            for a dump-on-fault. ``0`` disables the recorder entirely.
        flight_dump_dir: where fault dumps land. ``None``: the
            ``TM_TPU_FLIGHT_DIR`` environment variable, else
            ``<tempdir>/tm_tpu_flight``.
        flight_max_dumps: hard cap on dump files one pipeline writes — a stream
            where *every* chunk degrades must not fill the disk; suppressed
            dumps are counted (``flight.dumps_suppressed``).
        tenant: name this pipeline a **tenant session**
            (:mod:`torchmetrics_tpu.obs.scope`). Every dispatch, commit,
            flight record and value sample runs under ``scope(tenant)``, so
            spans/counters/timelines/alerts/cost entries carry the tenant
            label automatically; the driven metrics adopt the tenant for their
            eager paths, and the registry tracks the session's liveness
            (``active_pipelines``). ``None`` (default) keeps the untenanted
            single-session behavior, one branch of overhead.
        alert_engine: an :class:`~torchmetrics_tpu.obs.alerts.AlertEngine` to
            evaluate per committed chunk — the mid-stream value-health seam.
            The pipeline samples the target's values **sync-free**
            (``obs.values.sample_local``: ``pure_compute`` over local state, no
            collectives, no cache pollution), runs the rules, and triggers a
            flight-recorder dump when a *value* watchdog newly fires. ``None``
            (default) disables the seam entirely.
        alert_every: evaluate the alert engine every Nth committed chunk
            (``close()`` always runs a final evaluation).
        admission: an :class:`~torchmetrics_tpu.obs.scope.AdmissionController`
            consulted per ingested batch when the pipeline is a **tenant
            session**: over-quota batches are shed (dropped, counted) or
            deferred (held, drained at ``close()`` or when the tenant falls
            back under quota), per the tenant's quota policy. ``None`` falls
            back to the process-wide controller
            (:func:`~torchmetrics_tpu.obs.scope.get_admission`); untenanted
            pipelines never consult admission.
        max_deferred: cap on the deprioritized backlog (deferred batches hold
            real device arrays); past it, defer decisions degrade to shed.
        checkpoint: a :class:`~torchmetrics_tpu.engine.migrate.CheckpointPolicy`
            — the **continuous checkpointing** seam. Bundles are written every
            N batches / T seconds at chunk-commit boundaries (no drain, no
            stall; the committed state is chunk-consistent by construction),
            delta-encoded against their predecessor, compacted every
            ``full_every``-th write, retention-swept, and scanned back with
            :func:`~torchmetrics_tpu.engine.migrate.latest_valid_bundle` after
            an unplanned death. ``None`` (default) disables — zero overhead.
        lease_seconds: TTL of the session's renewable wall-clock **lease**
            (:mod:`torchmetrics_tpu.robust.fence`). The lease — holder id,
            session epoch, expiry — is minted per session epoch, renewed on
            ingest/commit/checkpoint (throttled to ~TTL/4), and stamped into
            every checkpoint bundle manifest, making the session epoch a
            fencing token: a watchdog that observes the lease expire without
            renewal fails the tenant over elsewhere under a fresh epoch and
            fences this one, after which this session's bundle writes are
            rejected by every recovery scan. Default 30 s.
    """

    fuse: int = 8
    max_in_flight: int = 4
    prefetch: int = 2
    fuse_buckets: Optional[Tuple[int, ...]] = None
    device: Any = None
    flight_records: int = 64
    flight_dump_dir: Optional[str] = None
    flight_max_dumps: int = 16
    tenant: Optional[str] = None
    alert_engine: Any = None
    alert_every: int = 1
    admission: Any = None
    max_deferred: int = 1024
    checkpoint: Any = None
    lease_seconds: float = 30.0

    def __post_init__(self) -> None:
        if self.tenant is not None:
            _scope.validate_tenant(self.tenant)
        if self.fuse < 1:
            raise ValueError(f"Expected `fuse` >= 1, got {self.fuse}")
        if self.lease_seconds <= 0:
            raise ValueError(f"Expected `lease_seconds` > 0, got {self.lease_seconds}")
        if self.max_in_flight < 1:
            raise ValueError(f"Expected `max_in_flight` >= 1, got {self.max_in_flight}")
        if self.prefetch < 0:
            raise ValueError(f"Expected `prefetch` >= 0, got {self.prefetch}")
        if self.flight_records < 0:
            raise ValueError(f"Expected `flight_records` >= 0, got {self.flight_records}")
        if self.flight_max_dumps < 0:
            raise ValueError(f"Expected `flight_max_dumps` >= 0, got {self.flight_max_dumps}")
        if self.alert_every < 1:
            raise ValueError(f"Expected `alert_every` >= 1, got {self.alert_every}")
        if self.max_deferred < 1:
            raise ValueError(f"Expected `max_deferred` >= 1, got {self.max_deferred}")
        if self.fuse_buckets is not None:
            buckets = tuple(sorted(set(int(b) for b in self.fuse_buckets)))
            if not buckets or buckets[0] < 1:
                raise ValueError(f"Expected positive `fuse_buckets`, got {self.fuse_buckets}")
            if buckets[-1] < self.fuse:
                buckets = buckets + (self.fuse,)
            self.fuse_buckets = buckets

    def buckets(self) -> Tuple[int, ...]:
        if self.fuse_buckets is not None:
            return self.fuse_buckets
        return _warmup.pow2_buckets(self.fuse)


@dataclass
class PipelineReport:
    """Plain-int accounting of one pipeline's work (no obs tracing required)."""

    batches: int = 0  # batches ingested
    fused_batches: int = 0  # batches that landed via a fused scan dispatch
    eager_batches: int = 0  # batches driven through per-batch `update`
    replayed_batches: int = 0  # per-batch replays after a chunk degraded
    dispatches: int = 0  # fused scan dispatches issued
    eager_dispatches: int = 0  # per-batch update dispatches (incl. replays)
    chunks_replayed: int = 0  # chunks degraded to per-batch replay
    padded_steps: int = 0  # masked tail steps added by bucket padding
    shape_flushes: int = 0  # chunks flushed early by a signature change
    max_chunk: int = 0
    last_chunk: int = 0
    prefetch_hits: int = 0
    prefetch_misses: int = 0
    inflight_waits: int = 0
    flight_dumps: int = 0  # flight-recorder fault dumps written
    shed_batches: int = 0  # admission: over-quota batches dropped (tenant sessions)
    deferred_batches: int = 0  # admission: batches deprioritized (held)
    deferred_replayed: int = 0  # deferred batches later ingested

    def host_dispatches(self) -> int:
        """Total host dispatches that advanced metric state."""
        return self.dispatches + self.eager_dispatches

    def dispatches_per_batch(self) -> Optional[float]:
        """Host dispatches per ingested batch (< 1.0 once fusion engages)."""
        if not self.batches:
            return None
        return self.host_dispatches() / self.batches

    def processed_batches(self) -> int:
        """Canonical processed count: every batch that reached a dispatch."""
        return self.fused_batches + self.eager_batches + self.replayed_batches

    def asdict(self) -> Dict[str, Any]:
        out = {f.name: getattr(self, f.name) for f in fields(self)}
        out["host_dispatches"] = self.host_dispatches()
        out["dispatches_per_batch"] = self.dispatches_per_batch()
        out["processed_batches"] = self.processed_batches()
        return out


def _normalize_batch(batch: Any) -> Tuple[tuple, dict]:
    """Accept ``(args...)`` tuples, ``{kwarg: value}`` dicts, or a single array."""
    if isinstance(batch, tuple):
        return batch, {}
    if isinstance(batch, dict):
        return (), dict(batch)
    return (batch,), {}


class _Chunk:
    """One open fusion chunk: same-signature batches awaiting a fused dispatch."""

    __slots__ = (
        "sig",
        "treedef",
        "template",
        "traced",
        "originals",
        "records",
        "trace_ids",
        "first_index",
    )

    def __init__(self, sig: tuple, treedef: Any, template: tuple, first_index: int) -> None:
        self.sig = sig
        self.treedef = treedef
        self.template = template
        self.traced: List[list] = []  # per batch: traced leaves, template order
        self.originals: List[Tuple[tuple, dict]] = []  # per batch: (args, kwargs)
        self.records: List[dict] = []  # per batch: flight-recorder record (flight on only)
        self.trace_ids: List[Optional[str]] = []  # per batch: lineage id (None when disabled)
        self.first_index = first_index  # ingest ordinal of the chunk's first batch

    def __len__(self) -> int:
        return len(self.traced)


class _FlightRecorder:
    """Bounded per-batch lineage ring with atomic JSONL dump-on-fault.

    One record per ingested batch (drop-oldest past ``capacity``): batch index,
    input signature, fused-chunk id, dispatch path, per-stage timings
    (prefetch wait / device put / dispatch / commit / blocked-on-inflight) and,
    after a fault, which batch was poisoned. When a chunk degrades to replay or
    a batch is quarantined, the whole ring is dumped as JSONL — a poisoned
    batch in production arrives with its last-K-batch context instead of a bare
    counter increment. Dumping never raises into the pipeline: an unwritable
    dump directory warns once and the stream keeps flowing.
    """

    _STAGES = ("prefetch_wait", "device_put", "dispatch", "commit", "blocked_on_inflight")

    def __init__(self, pipeline: str, inst: str, capacity: int, dump_dir: str, max_dumps: int) -> None:
        self.pipeline = pipeline
        self.inst = inst
        self.tenant: Optional[str] = None  # set when the pipeline is a tenant session
        self.dump_dir = dump_dir
        self.max_dumps = max_dumps
        self._ring: deque = deque(maxlen=capacity)
        self.dump_paths: List[str] = []
        self.dumps_suppressed = 0
        self._warned_unwritable = False

    def __len__(self) -> int:
        return len(self._ring)

    def open_record(
        self,
        batch_index: int,
        stages: Optional[Dict[str, float]] = None,
        trace_id: Optional[str] = None,
    ) -> dict:
        record = {
            "batch_index": batch_index,
            # the canonical correlation key (obs/lineage.py): batch_index and
            # chunk_id ordinals restart per process across a restore, the
            # trace id does not — dump readers should join on it when present
            "trace_id": trace_id,
            "chunk_id": None,
            "signature": None,
            "path": None,
            "fault": None,
            "stages": dict.fromkeys(self._STAGES),
        }
        if stages:
            record["stages"].update(stages)
        self._ring.append(record)
        return record

    def records(self) -> List[dict]:
        """Copies of the live ring, oldest first (safe to mutate/serialize)."""
        return [{**r, "stages": dict(r["stages"])} for r in self._ring]

    def restore_records(self, records: List[dict]) -> None:
        """Refill the ring from serialized records (oldest first, bounded).

        The migration path: a restored session's first fault dump should still
        carry the pre-migration batch lineage as context, not start from an
        empty ring.
        """
        for record in records or []:
            restored = {**record, "stages": dict(record.get("stages") or {})}
            self._ring.append(restored)

    def dump(
        self,
        reason: str,
        poisoned: List[int],
        config: Dict[str, Any],
        tenant: Optional[str] = None,
        poisoned_trace_ids: Optional[List[str]] = None,
    ) -> Optional[str]:
        """Write the ring as JSONL (meta line first, then batches oldest-first).

        Atomic via :func:`~torchmetrics_tpu.utils.fileio.atomic_write_text` — a
        crash mid-dump never leaves a truncated file masquerading as evidence.
        Returns the path, or ``None`` when suppressed (cap) or unwritable.
        ``tenant`` overrides the recorder-level tenant on the meta line — the
        multiplexer's ring is shared across tenants, but each fault dump names
        the ONE tenant whose batches it attributes (``poisoned`` indices are
        that tenant's tenant-local ordinals).
        """
        if len(self.dump_paths) >= self.max_dumps:
            self.dumps_suppressed += 1
            if _trace.ENABLED:
                _trace.inc("flight.dumps_suppressed", pipeline=self.pipeline)
            return None
        meta = {
            "type": "meta",
            "schema": FLIGHT_SCHEMA,
            "pipeline": self.pipeline,
            "inst": self.inst,
            "tenant": tenant if tenant is not None else self.tenant,
            "reason": reason,
            "poisoned_batches": sorted(set(poisoned)),
            # the cross-restore-stable naming of the same batches (may be
            # empty: lineage off, or a fault with no batch to name)
            "poisoned_trace_ids": sorted(set(poisoned_trace_ids or [])),
            "records": len(self._ring),
            "ts_unix": time.time(),
            "config": config,
        }
        lines = [json.dumps(meta, sort_keys=True, default=str)]
        for record in self.records():
            lines.append(json.dumps({"type": "batch", **record}, sort_keys=True, default=str))
        name = (
            f"flight_{self.pipeline}_{os.getpid()}_{self.inst}_{len(self.dump_paths):03d}.jsonl"
        )
        path = os.path.join(self.dump_dir, name)
        try:
            atomic_write_text(path, "\n".join(lines) + "\n")
        except OSError as err:
            if not self._warned_unwritable:
                self._warned_unwritable = True
                rank_zero_warn(
                    f"Flight-recorder dump could not be written to {path!r}:"
                    f" {type(err).__name__}: {err}. Faults keep their counters but lose"
                    " their batch-lineage dumps; point `PipelineConfig.flight_dump_dir`"
                    f" (or ${FLIGHT_DIR_ENV}) at a writable directory.",
                    RuntimeWarning,
                )
            return None
        self.dump_paths.append(path)
        return path


class MetricPipeline:
    """Drive a ``Metric`` or ``MetricCollection`` from a batch stream with
    prefetch, bounded async dispatch and fused scan chunks.

    Usage::

        pipe = MetricPipeline(metric, PipelineConfig(fuse=8, prefetch=2))
        pipe.warmup(example_preds, example_target)   # optional AOT precompile
        report = pipe.run(batch_iterator)            # or pipe.feed(...) per batch
        value = metric.compute()                     # pipe.run/close flushed already

    Metrics with ragged list states (or ``jit_update=False``) cannot ride the
    fused scan; the pipeline degrades them to per-batch updates automatically
    (collections: per compute-group leader, so fusable groups still fuse).
    """

    _instance_seq = itertools.count()

    def __init__(
        self,
        metric: Union[Metric, MetricCollection],
        config: Optional[PipelineConfig] = None,
        **overrides: Any,
    ) -> None:
        if config is None:
            config = PipelineConfig(**overrides)
        elif overrides:
            config = replace(config, **overrides)
        if not isinstance(metric, (Metric, MetricCollection)):
            raise ValueError(
                f"MetricPipeline drives a Metric or MetricCollection, got {type(metric).__name__}"
            )
        self.config = config
        self._target = metric
        self._is_collection = isinstance(metric, MetricCollection)
        self._label = type(metric).__name__
        self._instance = str(next(MetricPipeline._instance_seq))
        if self._is_collection:
            self._fused_leaders, self._eager_leaders = metric._engine_fusable_leaders()
        else:
            self._fused_leaders, self._eager_leaders = ([], [])
            if metric._engine_fusable():
                self._fused_leaders = [None]  # sentinel: the metric itself fuses
        self._fusable = bool(self._fused_leaders) and config.fuse > 1
        self._buckets = config.buckets()
        self._chunk: Optional[_Chunk] = None
        self._fused_fns: Dict[tuple, StaticLeafJit] = {}
        self._inflight: deque = deque()
        self._ingested = 0
        self._chunk_seq = 0
        # batch lineage (obs/lineage.py): the session epoch + arrival counter
        # minting one stable trace id per fed batch. Both are persisted in
        # session bundles and restored, so the same logical batch keeps its id
        # across migration and crash-recovery re-feeds; with lineage disabled
        # the counter never moves (one branch per ingest).
        self._lineage_epoch = _lineage.new_epoch()
        self._lineage_seq = 0
        self._report = PipelineReport()
        self._warmup_manifest: Optional[Dict[str, Any]] = None
        if config.flight_records > 0:
            dump_dir = (
                config.flight_dump_dir
                or os.environ.get(FLIGHT_DIR_ENV)
                or os.path.join(tempfile.gettempdir(), "tm_tpu_flight")
            )
            self._flight: Optional[_FlightRecorder] = _FlightRecorder(
                self._label,
                self._instance,
                config.flight_records,
                dump_dir,
                config.flight_max_dumps,
            )
        else:
            self._flight = None
        self._alert_engine = config.alert_engine
        self._alert_commits = 0
        self._alert_warned = False
        # admission-deprioritized batches as (args, kwargs, trace_id) — the id
        # was minted at first arrival, so defer → re-admission keeps identity
        self._deferred: List[Tuple[tuple, dict, Optional[str]]] = []
        self._shed_warned = False
        self._tenant: Optional[str] = None
        self._tenant_closed = False
        if config.tenant is not None:
            # a tenant-scoped pipeline IS a session: register liveness, and
            # adopt the tenant onto the driven metrics so their eager paths
            # (direct compute, robust counters, memory gauges) stay attributed
            self._tenant = _scope.adopt(config.tenant)
            _scope.get_registry().pipeline_started(self._tenant)
            targets: List[Any] = [self._target]
            if self._is_collection:
                targets += list(self._target._modules.values())
            for m in targets:
                if getattr(m, "_obs_tenant", None) is None:
                    m._obs_tenant = self._tenant
            if self._flight is not None:
                self._flight.tenant = self._tenant
        self._checkpointer = None
        if config.checkpoint is not None:
            # lazy import: migrate.py imports this module at load time
            from torchmetrics_tpu.engine.migrate import ContinuousCheckpointer

            self._checkpointer = ContinuousCheckpointer(
                config.checkpoint, tenant=self._tenant, label=self._label
            )
        # the session lease (robust/fence.py): minted per session epoch — the
        # fencing token — renewed on ingest/commit/checkpoint (throttled to
        # ~TTL/4) and stamped into every checkpoint bundle. A restore that
        # adopts a bundled epoch re-mints under it (_restore_lineage).
        self._lease = _fence.mint_lease(
            self._tenant, epoch=self._lineage_epoch, ttl_seconds=config.lease_seconds
        )
        self._lease_renew_at = time.time() + config.lease_seconds / 4.0
        if _audit.ENABLED:
            _audit.track(self, "pipeline", self._label)
        # wiring the persistent compile cache is part of engine startup: no-op
        # unless TM_TPU_COMPILE_CACHE (or an earlier explicit call) set a dir
        _warmup.configure_compile_cache()

    def _renew_lease(self, force: bool = False) -> None:
        """Renew the session lease, throttled to ~TTL/4 unless forced."""
        now = time.time()
        if not force and now < self._lease_renew_at:
            return
        _fence.renew_lease(self._lease, self._tenant, now=now)
        self._lease_renew_at = now + self._lease["ttl_seconds"] / 4.0

    def lease_snapshot(self) -> Dict[str, Any]:
        """The lease stamp a checkpoint bundle carries, freshly renewed —
        every bundle write doubles as a cross-host lease renewal."""
        self._renew_lease(force=True)
        return {
            "holder": self._lease["holder"],
            "epoch": self._lease["epoch"],
            "ttl_seconds": self._lease["ttl_seconds"],
            "expires_unix": self._lease["expires_unix"],
            "renewed_unix": self._lease["renewed_unix"],
        }

    def _maybe_checkpoint(self, force: bool = False) -> Optional[str]:
        """Continuous-checkpoint hook, called at chunk-commit boundaries only —
        so every periodic bundle is chunk-consistent without a drain."""
        self._renew_lease()
        if self._checkpointer is None:
            return None
        return self._checkpointer.maybe_pipeline(self, force=force)

    def checkpoint_now(self) -> Optional[str]:
        """Force one continuous-checkpoint bundle (cadence bypassed); returns
        its path, or ``None`` without a configured ``CheckpointPolicy``."""
        with self._tenant_ctx():
            return self._maybe_checkpoint(force=True)

    def _tenant_ctx(self):
        """The session scope every public entry point runs under (no-op when
        the pipeline is untenanted). ``scope.session`` sets only the
        contextvar — registration happened once at construction via
        ``adopt()``, so the hot path pays no registry lock per call."""
        return _scope.session(self._tenant) if self._tenant is not None else nullcontext()

    # ------------------------------------------------------------------ public API

    @property
    def metric(self) -> Union[Metric, MetricCollection]:
        return self._target

    def report(self) -> PipelineReport:
        """Copy of the accounting so far (safe to keep across further feeds)."""
        return replace(self._report)

    @property
    def warmup_manifest(self) -> Optional[Dict[str, Any]]:
        return self._warmup_manifest

    @property
    def lineage_epoch(self) -> str:
        """The session epoch trace ids are minted under (bundle-persisted)."""
        return self._lineage_epoch

    def trace_id_for(self, ordinal: int) -> str:
        """The (deterministic) trace id of this session's ``ordinal``-th fed
        batch — the ``GET /trace/<id>`` key a driver can compute without
        having observed the ingest."""
        return _lineage.mint(self._tenant, self._lineage_epoch, ordinal)

    def flight_records(self) -> List[dict]:
        """Copies of the flight-recorder ring (empty when ``flight_records=0``)."""
        return self._flight.records() if self._flight is not None else []

    @property
    def flight_dumps(self) -> List[str]:
        """Paths of the fault dumps this pipeline has written."""
        return list(self._flight.dump_paths) if self._flight is not None else []

    def flight_snapshot(self) -> Dict[str, Any]:
        """Serializable flight-recorder state (the session-bundle seam)."""
        if self._flight is None:
            return {"records": [], "dumps_written": 0, "dumps_suppressed": 0}
        return {
            "records": self._flight.records(),
            "dumps_written": len(self._flight.dump_paths),
            "dumps_suppressed": self._flight.dumps_suppressed,
        }

    def _restore_flight(self, snapshot: Dict[str, Any]) -> None:
        """Refill the flight ring from a session bundle (restore path).

        Dump *files* stay on the origin host — only the ring (the lineage
        context a future fault dump ships) and the suppressed count migrate;
        the written-dump total lives on in the restored report.
        """
        if self._flight is None or not snapshot:
            return
        self._flight.restore_records(snapshot.get("records") or [])
        self._flight.dumps_suppressed += int(snapshot.get("dumps_suppressed", 0) or 0)

    def _restore_report(self, totals: Dict[str, Any]) -> None:
        """Adopt a checkpointed session's accounting (restore path): the
        restored pipeline keeps counting from the origin host's totals."""
        for f in fields(PipelineReport):
            if f.name in totals:
                setattr(self._report, f.name, int(totals[f.name]))
        # the ingest ordinal continues too, so flight-record batch indices
        # stay the session's (not the process's) ordinals
        self._ingested = max(self._ingested, int(totals.get("batches", 0) or 0))

    def _restore_lineage(self, cursor: Dict[str, Any], fresh_epoch: bool = False) -> None:
        """Adopt the bundled session's lineage identity + chunk ordinal.

        The epoch + arrival counter make post-restore mints continue the
        origin session's id space (a crash-recovery gap re-feed reproduces
        the lost batches' exact ids); ``chunk_seq`` continues too, so a
        post-restore dispatch span's ``chunk_id`` can never collide with a
        restored flight record's — the ordinal half of the span↔record
        correlation fix (the trace id is the canonical key either way).

        ``fresh_epoch=True`` is the **failover** variant: the arrival counter
        still continues, but under a newly minted epoch — the new fencing
        token — so nothing this session produces can be confused with (or
        rejected alongside) the fenced origin's writes. Either way the lease
        is re-minted under the session's final epoch, so the stamp a future
        bundle carries names the identity it was actually written under.
        """
        lineage_row = cursor.get("lineage") or {}
        if lineage_row.get("epoch"):
            if not fresh_epoch:
                self._lineage_epoch = str(lineage_row["epoch"])
            self._lineage_seq = max(
                self._lineage_seq, int(lineage_row.get("seq", 0) or 0)
            )
        if cursor.get("chunk_seq") is not None:
            self._chunk_seq = max(self._chunk_seq, int(cursor["chunk_seq"]))
        if self._lease["epoch"] != self._lineage_epoch:
            self._lease = _fence.mint_lease(
                self._tenant,
                epoch=self._lineage_epoch,
                ttl_seconds=self.config.lease_seconds,
            )
            self._lease_renew_at = time.time() + self.config.lease_seconds / 4.0

    def feed(self, *args: Any, **kwargs: Any) -> None:
        """Ingest one batch (positional/keyword update arguments)."""
        with self._tenant_ctx():
            self._ingest(args, kwargs)

    def run(self, batches: Iterable[Any]) -> PipelineReport:
        """Consume a stream of batches with device prefetch; flushes at the end.

        Each item is a tuple of positional update args, a dict of keyword args,
        or a single array. Returns the accumulated :class:`PipelineReport`.
        """
        with self._tenant_ctx():
            return self._run(batches)

    def _run(self, batches: Iterable[Any]) -> PipelineReport:
        lookahead = max(1, self.config.prefetch)
        it = iter(batches)
        pending: deque = deque()  # (args, kwargs, ingested-count at enqueue, stage timings)
        exhausted = False
        timed = self._flight is not None
        while pending or not exhausted:
            while not exhausted and len(pending) < lookahead:
                start = time.perf_counter() if timed else 0.0
                try:
                    raw = next(it)
                except StopIteration:
                    exhausted = True
                    break
                produced = time.perf_counter() if timed else 0.0
                args, kwargs = _normalize_batch(raw)
                args, kwargs = self._device_put(args, kwargs)
                stages = None
                if timed:
                    # prefetch_wait: host time the source iterator took to yield
                    # (the producer-bound stall); device_put: transfer issue time
                    stages = {
                        "prefetch_wait": round(produced - start, 6),
                        "device_put": round(time.perf_counter() - produced, 6),
                    }
                pending.append((args, kwargs, self._ingested, stages))
            if pending:
                args, kwargs, stamp, stages = pending.popleft()
                if stamp < self._ingested:
                    # its transfer was issued before the previous batch was even
                    # ingested — the copy overlapped compute
                    self._report.prefetch_hits += 1
                    if _trace.ENABLED:
                        _trace.inc("engine.prefetch_hit", pipeline=self._label)
                else:
                    self._report.prefetch_misses += 1
                    if _trace.ENABLED:
                        _trace.inc("engine.prefetch_miss", pipeline=self._label)
                self._ingest(args, kwargs, stages)
        self.flush()
        return self.report()

    def flush(self) -> None:
        """Dispatch the open partial chunk (padded up to its bucket).

        Also runs the wall-clock re-admission check: a deferred backlog whose
        tenant has fallen back under quota drains here too, so an
        idle-but-deferred tenant is not starved until ``close()``.
        """
        with self._tenant_ctx():
            self._maybe_readmit()
            if self._chunk is not None and len(self._chunk):
                self._dispatch_chunk()
            self._check_buffer_overflow()

    def poll_admission(self) -> int:
        """Wall-clock re-admission check for the deferred backlog.

        A tenant whose batches were deferred drains them on its next feed once
        the quota window rolls — but an *idle* tenant never feeds again, so its
        backlog used to wait for ``close()``. An external ticker (or any
        housekeeping loop) calls this instead: when the admission controller's
        read-only probe (:meth:`~torchmetrics_tpu.obs.scope.AdmissionController.would_admit`)
        says the tenant is back under quota, the backlog drains in order (and
        is billed). Returns the number of batches drained.
        """
        with self._tenant_ctx():
            return self._maybe_readmit()

    def _maybe_readmit(self) -> int:
        """Drain the deferred backlog if the tenant is back under quota."""
        if self._tenant is None or not self._deferred:
            return 0
        controller = (
            self.config.admission if self.config.admission is not None else _scope.get_admission()
        )
        if controller is None:
            # the controller was uninstalled mid-stream: nothing meters this
            # tenant anymore, so the backlog drains unconditionally
            n = len(self._deferred)
            self._drain_deferred(None)
            return n
        probe = getattr(controller, "would_admit", None)
        if not callable(probe):
            # a controller without the read-only probe cannot be asked safely:
            # stay conservative (the backlog still drains at close(), exactly
            # the pre-probe behavior) rather than bypassing a live quota
            return 0
        if not probe(self._tenant):
            return 0
        n = len(self._deferred)
        self._drain_deferred(controller)
        return n

    def drain(self) -> List[Tuple[tuple, dict, Optional[str]]]:
        """Quiesce the pipeline for a checkpoint; returns the **replay tail**.

        The first step of the drain→checkpoint→restore→replay-tail migration
        protocol (:mod:`torchmetrics_tpu.engine.migrate`): the open fusion
        chunk is dispatched, the in-flight async window is blocked to
        completion — after which the metric state is exactly the fold of every
        dispatched batch — and the admission-deferred backlog (batches
        ingested but never folded) is handed back, cleared, as the tail to
        persist and replay after restore. Each tail item is ``(args, kwargs,
        trace_id)`` — the third element is the batch's lineage id
        (:mod:`~torchmetrics_tpu.obs.lineage`; ``None`` with lineage off),
        exactly what :meth:`replay_tail` re-ingests. The session stays open
        (``close()`` still owes the registry its ``pipeline_finished``).
        """
        with self._tenant_ctx():
            if self._chunk is not None and len(self._chunk):
                self._dispatch_chunk()
            while self._inflight:
                jax.block_until_ready(self._inflight.popleft())
            if _trace.ENABLED:
                _trace.set_gauge("engine.in_flight", 0, pipeline=self._label, inst=self._instance)
            tail, self._deferred = self._deferred, []
            if _audit.ENABLED:
                # drained tail batches leave with the bundle: conserved as
                # handed-off work, completed by the restoring session
                _audit.note_handed_off(self, "pipeline", self._tenant, len(tail))
            return tail

    def replay_tail(self, batches: Iterable[tuple], deferred: int = 0) -> int:
        """Re-ingest checkpointed tail batches on the restored host, in order.

        Each item is ``(args, kwargs)`` or ``(args, kwargs, trace_id)`` — the
        third element is the batch's bundle-persisted lineage id, re-adopted
        so the replayed batch keeps the identity it was fed under on the
        origin host (``GET /trace/<id>`` keeps resolving across the
        migration).

        Admission *decisions* are bypassed — these batches were accepted by
        the origin host before the checkpoint; replaying them is completing
        accepted work, not new traffic — but the executed updates ARE billed
        to the restoring host's controller (deferred batches are never charged
        at defer time; the work burns quota where it actually runs, exactly
        like :meth:`_drain_deferred`). The first ``deferred`` batches are the
        origin's admission-deferred backlog and count toward
        ``deferred_replayed`` so the restored report's deferred accounting
        balances. Returns the number of batches replayed.
        """
        controller = None
        if self._tenant is not None:
            controller = (
                self.config.admission
                if self.config.admission is not None
                else _scope.get_admission()
            )
        n = 0
        with self._tenant_ctx():
            for item in batches:
                args, kwargs = item[0], item[1]
                trace_id = item[2] if len(item) > 2 else None
                if n < deferred:
                    self._report.deferred_replayed += 1
                if controller is not None:
                    controller.charge(self._tenant, updates=1)
                self._ingest(
                    tuple(args), dict(kwargs), bypass_admission=True, trace_id=trace_id
                )
                n += 1
        return n

    def close(self) -> PipelineReport:
        """Flush (deferred backlog included), drain the in-flight window, and
        return the final report."""
        try:
            with self._tenant_ctx():
                # admission-deprioritized batches land now, after in-quota
                # traffic — deprioritized, never silently lost
                if self._tenant is not None:
                    self._drain_deferred(
                        self.config.admission
                        if self.config.admission is not None
                        else _scope.get_admission()
                    )
                self.flush()
                while self._inflight:
                    jax.block_until_ready(self._inflight.popleft())
                if _trace.ENABLED:
                    _trace.set_gauge("engine.in_flight", 0, pipeline=self._label, inst=self._instance)
                # the bundle stream ends complete: a clean close leaves a
                # restore point covering every batch the session ever folded
                # (skipped when the cadence already covered the final commit —
                # no byte-identical duplicate bundle on shutdown)
                if self._checkpointer is not None and self._report.batches:
                    self._checkpointer.maybe_pipeline(
                        self, force=True, skip_if_covered=True
                    )
                self._evaluate_alerts(force=True)
        finally:
            # the session ends exactly once, however many times close() runs —
            # INCLUDING when a raise-policy flush or a deferred XLA error
            # propagates, else the registry leaks active_pipelines=1 forever
            if self._tenant is not None and not self._tenant_closed:
                self._tenant_closed = True
                _scope.get_registry().pipeline_finished(self._tenant)
                if self._checkpointer is not None:
                    # the freshness promise ends WITH the session: a closed
                    # session must not age into /healthz staleness or a
                    # firing checkpoint_stale alert
                    _scope.note_checkpoint_closed(self._tenant)
            # a cleanly released lease is not a hung host: it must never age
            # into the watchdog's stale set and trigger a failover
            if _scope.lease_status().get(
                self._tenant if self._tenant is not None else "__local__", {}
            ).get("epoch") == self._lease["epoch"]:
                _scope.note_lease_released(self._tenant)
            if _audit.ENABLED:
                # freeze this generation's final ledger rows — they keep
                # feeding the per-tenant merge after the object dies
                _audit.note_close(self)
        return self.report()

    def compute(self) -> Any:
        """Flush then compute the target — the epoch-end convenience."""
        with self._tenant_ctx():
            self.flush()
            return self._target.compute()

    def __enter__(self) -> "MetricPipeline":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # ---------------------------------------------------------------------- warmup

    def warmup(
        self, *args: Any, manifest_path: Optional[str] = None, **kwargs: Any
    ) -> Dict[str, Any]:
        """AOT-precompile every (shape-bucket, static-config) variant for an example
        batch, before the loop runs.

        ``args``/``kwargs`` are one example batch — concrete arrays or abstract
        ``jax.ShapeDtypeStruct`` specs. Compiles the fused scan program for every
        chunk-length bucket plus the per-batch update path (the replay/eager
        fallback), through :meth:`StaticLeafJit.warmup`, so the hot loop's first
        steps are pure cache hits. With the persistent compilation cache wired
        (``TM_TPU_COMPILE_CACHE``), a restarted process's warmup turns into disk
        reads. Returns (and stores) the warmup manifest; ``manifest_path`` also
        writes it as JSON.
        """
        with self._tenant_ctx():
            return self._warmup_scoped(args, kwargs, manifest_path)

    def _warmup_scoped(
        self, args: tuple, kwargs: dict, manifest_path: Optional[str]
    ) -> Dict[str, Any]:
        # runs under the tenant scope so the cost ledger bills this session's
        # AOT compiles (including every fused-scan bucket variant) to its tenant
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        traced, template, unhashable = partition_static_leaves(leaves)
        if unhashable is not None:
            raise TypeError(
                f"MetricPipeline.warmup received an unhashable static argument of type"
                f" {type(unhashable).__name__}; such batches dispatch per-batch/eagerly"
                " and cannot be precompiled."
            )
        traced_specs = []
        for leaf in traced:
            if isinstance(leaf, jax.ShapeDtypeStruct):
                traced_specs.append(leaf)
            else:
                dtype = leaf.dtype if hasattr(leaf, "dtype") else np.asarray(leaf).dtype
                traced_specs.append(jax.ShapeDtypeStruct(np.shape(leaf), dtype))
        entries: List[Dict[str, Any]] = []
        shapes = [list(map(int, s.shape)) for s in traced_specs]
        if self._fusable:
            state = self._current_fused_state()
            fused = self._get_fused_fn(treedef, tuple(template))
            for bucket in self._buckets:
                stacked = [
                    jax.ShapeDtypeStruct((bucket, *spec.shape), spec.dtype) for spec in traced_specs
                ]
                valid = jax.ShapeDtypeStruct((bucket,), np.bool_)
                info = fused.warmup(state, stacked, valid)
                entries.append({**info, "kind": "fused", "bucket": bucket, "shapes": shapes})
        # the per-batch path (replay fallback for degraded chunks, eager group
        # leaders, and the whole path when fusion is off) — the metrics' own
        # jitted updates
        it = iter(traced_specs)
        abstract_full = [next(it) if isinstance(t, _ArraySlot) else t for t in template]
        a_args, a_kwargs = jax.tree_util.tree_unflatten(treedef, abstract_full)
        per_batch = list(self._per_batch_metrics())
        if self._is_collection:
            # unfusable leaders still dispatch per batch through their own
            # jitted update when they have one (e.g. jit forced on a list-state
            # metric) — the zero-compiles-in-the-loop promise covers them too
            per_batch += [self._target._modules[name] for name in self._eager_leaders]
        for m in per_batch:
            if not m._jit_enabled():
                continue
            if m._jitted_update is None:
                m._jitted_update = jit_with_static_leaves(m.pure_update)
            filtered = m._filter_kwargs(**a_kwargs) if self._is_collection else a_kwargs
            info = m._jitted_update.warmup(dict(m._state_values), *a_args, **filtered)
            entries.append({**info, "kind": "per_batch", "bucket": None, "shapes": shapes})
        manifest = _warmup.build_manifest(entries, cache_dir=_warmup.configured_cache_dir())
        self._warmup_manifest = manifest
        if _trace.ENABLED:
            _trace.event(
                "engine.warmup",
                pipeline=self._label,
                variants=manifest["variants"],
                fresh=manifest["fresh_compiles"],
                seconds=manifest["total_compile_seconds"],
            )
        if manifest_path is not None:
            _warmup.save_manifest(manifest, manifest_path)
        return manifest

    # ------------------------------------------------------------------- ingestion

    def _device_put(self, args: tuple, kwargs: dict) -> Tuple[tuple, dict]:
        def _put(x: Any) -> Any:
            if isinstance(x, (jax.Array, np.ndarray)):
                return jax.device_put(x, self.config.device)
            return x

        return jax.tree_util.tree_map(_put, (args, kwargs))

    def _drain_deferred(self, controller: Any) -> None:
        """Re-ingest the deprioritized backlog in order (admission decisions
        bypassed — the work executes regardless — but executed updates are
        still billed). Shared by the back-under-quota path and close()."""
        while self._deferred:
            args, kwargs, trace_id = self._deferred.pop(0)
            self._report.deferred_replayed += 1
            if controller is not None:
                controller.charge(self._tenant, updates=1)
            self._ingest(args, kwargs, bypass_admission=True, trace_id=trace_id)

    def _ingest(
        self,
        args: tuple,
        kwargs: dict,
        stages: Optional[Dict[str, float]] = None,
        bypass_admission: bool = False,
        trace_id: Optional[str] = None,
    ) -> None:
        self._renew_lease()  # throttled: a live ingest stream keeps the lease warm
        if _lineage.ENABLED and trace_id is None:
            # identity is assigned at FIRST arrival — before the admission
            # decision — so a deferred batch re-admitted later (or persisted
            # as a migration tail) keeps the id it arrived with
            ordinal = self._lineage_seq
            self._lineage_seq += 1
            trace_id = self.trace_id_for(ordinal)
            _lineage.get_index().open(trace_id, self._tenant, ordinal)
        elif trace_id is not None and _lineage.ENABLED:
            # a pre-minted id (deferred re-admission, tail replay, crash gap
            # re-feed): idempotent re-open — a record already live keeps its
            # original stamps, a restored-host replay recreates it
            _lineage.get_index().open(
                trace_id, self._tenant, _lineage.ordinal_of(trace_id)
            )
        if self._tenant is not None and not bypass_admission:
            # cost-aware admission (obs/scope.py): only tenant SESSIONS are
            # metered — an untenanted pipeline never consults the controller,
            # so the default path stays one branch
            controller = (
                self.config.admission
                if self.config.admission is not None
                else _scope.get_admission()
            )
            if controller is not None:
                decision = controller.admit(self._tenant)
                if decision == _scope.DEFER and len(self._deferred) >= self.config.max_deferred:
                    # a full backlog holds real device arrays: degrade to
                    # shed instead of growing memory without bound — and tell
                    # the controller, whose admit() counted this as deferred
                    controller.note_degraded_shed(self._tenant)
                    decision = _scope.SHED
                if decision == _scope.SHED:
                    self._report.shed_batches += 1
                    if trace_id is not None:
                        _lineage.get_index().update(trace_id, outcome="shed")
                    if not self._shed_warned:
                        self._shed_warned = True
                        rank_zero_warn(
                            f"Tenant {self._tenant!r} is over quota: this pipeline's"
                            " batches are being SHED (dropped, counted in"
                            " tenant.quota_shed). This warning fires once per pipeline;"
                            " the burn state is on GET /tenants.",
                            RuntimeWarning,
                        )
                    if _trace.ENABLED:
                        _trace.inc("engine.shed_batches", pipeline=self._label)
                    return
                if decision == _scope.DEFER:
                    self._deferred.append((args, kwargs, trace_id))
                    self._report.deferred_batches += 1
                    if trace_id is not None:
                        _lineage.get_index().update(trace_id, outcome="deferred")
                    if _trace.ENABLED:
                        _trace.inc("engine.deferred_batches", pipeline=self._label)
                    return
                # back under quota: the deferred backlog drains first so the
                # tenant's stream order is preserved
                self._drain_deferred(controller)
                controller.charge(self._tenant, updates=1)
        if _faults.update_faults_active():
            # injected faults apply ONCE per ingested batch, at the pipeline
            # seam; downstream metric.update calls are told not to re-apply
            args, kwargs = _faults.apply_update_fault(args, kwargs)
        batch_index = self._ingested
        self._ingested += 1
        self._report.batches += 1
        record = None
        if self._flight is not None:
            record = self._flight.open_record(batch_index, stages, trace_id=trace_id)
        if trace_id is not None and _trace.ENABLED:
            # the lineage flow's first anchor: a (near-zero) ingest span
            # carrying the trace id plus the prefetch/device_put stage
            # timings, so Perfetto draws prefetch → dispatch as one arrow
            # chain per batch (numeric attrs never become histogram labels)
            ingest_attrs: Dict[str, Any] = {"pipeline": self._label, "trace_id": trace_id}
            if stages:
                ingest_attrs.update(
                    {k: v for k, v in stages.items() if v is not None}
                )
            with _trace.span("engine.ingest", **ingest_attrs):
                pass
        if _trace.ENABLED:
            _trace.inc("engine.batches", pipeline=self._label)
            if record is not None:
                _trace.set_gauge(
                    "flight.records", len(self._flight), pipeline=self._label, inst=self._instance
                )
        if not self._fusable:
            self._drive_per_batch(args, kwargs, record, trace_id)
            return
        if self._eager_leaders:
            # unfusable group leaders advance per batch, in stream order
            self._drive_eager_leaders(args, kwargs)
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        traced, template, unhashable = partition_static_leaves(leaves)
        if unhashable is not None:
            # unhashable statics cannot key a chunk signature: flush and fall
            # through to the per-batch path for this batch
            if self._chunk is not None and len(self._chunk):
                self._dispatch_chunk()
            self._drive_fused_leaders_eagerly(args, kwargs, record, trace_id)
            return
        sig = (treedef, tuple(template), _aval_signature(traced))
        if record is not None or trace_id is not None:
            sig_str = signature_str(sig[2])
            if record is not None:
                record["signature"] = sig_str
            if trace_id is not None:
                _lineage.get_index().update(trace_id, signature=sig_str)
        if self._chunk is not None and self._chunk.sig != sig:
            self._report.shape_flushes += 1
            if _trace.ENABLED:
                _trace.inc("engine.shape_flush", pipeline=self._label)
            self._dispatch_chunk()
        if self._chunk is None:
            self._chunk = _Chunk(sig, treedef, tuple(template), batch_index)
        self._chunk.traced.append(traced)
        self._chunk.originals.append((args, kwargs))
        self._chunk.trace_ids.append(trace_id)
        if record is not None:
            self._chunk.records.append(record)
        if _trace.ENABLED:
            _trace.set_gauge(
                "engine.queue_depth", len(self._chunk), pipeline=self._label, inst=self._instance
            )
        if len(self._chunk) >= self.config.fuse:
            self._dispatch_chunk()

    # ------------------------------------------------------------------ fused path

    def _per_batch_metrics(self) -> List[Metric]:
        """The metrics the per-batch (eager/replay) path drives directly."""
        if not self._is_collection:
            return [self._target]
        return [self._target._modules[name] for name in self._fused_leaders if name is not None]

    def _current_fused_state(self) -> Any:
        if not self._is_collection:
            return dict(self._target._state_values)
        return {
            name: dict(self._target._modules[name]._state_values) for name in self._fused_leaders
        }

    def _get_fused_fn(self, treedef: Any, template: tuple) -> StaticLeafJit:
        key = (treedef, template)
        fused = self._fused_fns.get(key)
        if fused is not None:
            return fused
        if self._is_collection:
            leaders = [(name, self._target._modules[name]) for name in self._fused_leaders]
        else:
            leaders = None
        target = self._target

        def fused_update(state, stacked, valid):
            def body(st, xs):
                step_leaves, ok = xs
                it = iter(step_leaves)
                full = [next(it) if isinstance(t, _ArraySlot) else t for t in template]
                a, kw = jax.tree_util.tree_unflatten(treedef, full)
                if leaders is None:
                    new = target.pure_update(st, *a, **kw)
                else:
                    new = {
                        name: m.pure_update(st[name], *a, **m._filter_kwargs(**kw))
                        for name, m in leaders
                    }
                # masked tail: padded steps pass the state through unchanged, so
                # a partial chunk padded up to its bucket stays bit-identical to
                # the unpadded per-batch run
                merged = jax.tree_util.tree_map(lambda n, o: jnp.where(ok, n, o), new, st)
                return merged, None

            out, _ = jax.lax.scan(body, state, (stacked, valid))
            return out

        fused_update.__name__ = "fused_update"
        fused_update.__qualname__ = f"{self._label}.fused_update"
        fused = jit_with_static_leaves(fused_update)
        self._fused_fns[key] = fused
        return fused

    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    def _chunk_policy(self):
        """The error policy guarding this chunk (any fused metric's, else global)."""
        for m in self._per_batch_metrics():
            policy = effective_policy(m.error_policy)
            if policy is not None:
                return policy
        return None

    def _stack_rows(self, rows: list, n_cols: int) -> list:
        # a named function, not an inline comprehension: host-side row
        # stacking is one of the seams the sampling profiler
        # (obs/hostprof.py) attributes, and it needs a stable frame name to
        # classify these samples as "stack-unstack" instead of folding them
        # into the surrounding dispatch
        return [jnp.stack([row[i] for row in rows]) for i in range(n_cols)]

    def _dispatch_chunk(self) -> None:
        chunk, self._chunk = self._chunk, None
        cid = self._chunk_seq
        self._chunk_seq += 1
        n = len(chunk.traced)
        bucket = self._bucket_for(n)
        pad = bucket - n
        rows = chunk.traced + [chunk.traced[-1]] * pad  # repeat-last padding, masked out
        stacked = self._stack_rows(rows, len(chunk.traced[0]))
        valid = jnp.asarray(np.arange(bucket) < n)
        policy = self._chunk_policy()
        if policy is not None:
            # one host sync per CHUNK (the guarded eager path pays one per batch)
            bad_steps = [i for i in nonfinite_step_indices(stacked) if i < n]
            if bad_steps:
                if _trace.ENABLED:
                    _trace.event(
                        "engine.chunk_degraded",
                        pipeline=self._label,
                        reason="nonfinite",
                        steps=",".join(map(str, bad_steps)),
                        chunk=n,
                        chunk_id=cid,
                    )
                self._replay_chunk(chunk, cid)
                return
        fused = self._get_fused_fn(chunk.treedef, chunk.template)
        state = self._current_fused_state()
        timed = bool(chunk.records)
        start = time.perf_counter() if timed else 0.0
        chunk_ids = [t for t in chunk.trace_ids if t is not None]
        try:
            if _trace.ENABLED:
                # batch_index/chunk_id are numeric attrs: they land on the span
                # (correlatable with flight-recorder records and Perfetto) but
                # never become histogram labels, so cardinality stays bounded.
                # trace_id/trace_ids are string attrs EXCLUDED from labels by
                # the recorder (unbounded ids must never mint series); the
                # ambient lineage context makes the dispatch histogram's
                # exemplar reference the chunk's lead batch
                span_attrs: Dict[str, Any] = {
                    "pipeline": self._label,
                    "path": "fused",
                    "chunk_id": cid,
                    "batch_index": chunk.first_index,
                }
                if chunk_ids:
                    span_attrs["trace_id"] = chunk_ids[0]
                    span_attrs["trace_ids"] = ",".join(chunk_ids)
                with _lineage.trace(chunk_ids[0] if chunk_ids else None):
                    with _trace.span("engine.dispatch", **span_attrs):
                        new_state = fused(state, stacked, valid)
            else:
                new_state = fused(state, stacked, valid)
        except Exception as err:
            if policy is None:
                raise
            # state was never committed; the guarded per-batch replay isolates
            # exactly the failing batches
            if _trace.ENABLED:
                _trace.event(
                    "engine.chunk_degraded",
                    pipeline=self._label,
                    reason=f"{type(err).__name__}",
                    chunk=n,
                    chunk_id=cid,
                )
            self._replay_chunk(chunk, cid)
            return
        dispatch_seconds = (time.perf_counter() - start) if timed else 0.0
        commit_start = time.perf_counter() if timed else 0.0
        self._commit(new_state, n)
        commit_seconds = (time.perf_counter() - commit_start) if timed else 0.0
        self._report.dispatches += 1
        self._report.fused_batches += n
        self._report.padded_steps += pad
        self._report.max_chunk = max(self._report.max_chunk, n)
        self._report.last_chunk = n
        if _audit.ENABLED:
            for tid in chunk.trace_ids:
                _audit.note_fold(self, "pipeline", self._tenant, self._lineage_epoch, tid)
        if _trace.ENABLED:
            _trace.inc("engine.dispatches", pipeline=self._label)
            _trace.inc("engine.fused_batches", n, pipeline=self._label)
            if pad:
                _trace.inc("engine.padded_steps", pad, pipeline=self._label)
            _trace.set_gauge(
                "engine.fused_chunk_size", n, pipeline=self._label, inst=self._instance
            )
            _trace.set_gauge(
                "engine.queue_depth", 0, pipeline=self._label, inst=self._instance
            )
        waited = self._ticket(new_state)
        for record in chunk.records:
            record["chunk_id"] = cid
            record["path"] = "fused"
            record["stages"]["dispatch"] = round(dispatch_seconds, 6)
            record["stages"]["commit"] = round(commit_seconds, 6)
            record["stages"]["blocked_on_inflight"] = round(waited, 6)
        if chunk_ids:
            index = _lineage.get_index()
            for tid in chunk_ids:
                index.update(tid, chunk_id=cid, path="fused", outcome="ok")
        self._maybe_checkpoint()
        self._evaluate_alerts(trace_ids=chunk_ids)

    def _commit(self, new_state: Any, n: int) -> None:
        if self._is_collection:
            self._target._engine_commit(
                {name: new_state[name] for name in self._fused_leaders}, n
            )
        else:
            self._target._engine_commit_state(new_state, n)

    # ------------------------------------------------------------- per-batch paths

    def _suppressing_refault(self, fn: Callable[[], Any]) -> Any:
        """Run a downstream ``update`` without re-applying an armed fault plan
        (the pipeline already applied it at ingestion)."""
        if not _faults.update_faults_active():
            return fn()
        metrics = (
            list(self._target._modules.values()) if self._is_collection else [self._target]
        )
        previous = [m.__dict__.get("_fault_applied", False) for m in metrics]
        for m in metrics:
            m.__dict__["_fault_applied"] = True
        try:
            return fn()
        finally:
            for m, prev in zip(metrics, previous):
                m.__dict__["_fault_applied"] = prev

    def _all_metrics(self) -> List[Metric]:
        """Every metric the target holds (fault attribution walks them all)."""
        if self._is_collection:
            return list(self._target._modules.values())
        return [self._target]

    def _robust_counts(self) -> Tuple[int, int]:
        """(quarantined, skipped) totals across the driven metrics — diffed
        around an update to attribute a fault to the batch that caused it."""
        quarantined = skipped = 0
        for m in self._all_metrics():
            quarantined += int(getattr(m, "updates_quarantined", 0) or 0)
            skipped += int(getattr(m, "updates_skipped", 0) or 0)
        return quarantined, skipped

    def _mark_fault(
        self,
        record: Optional[dict],
        before: Tuple[int, int],
        trace_id: Optional[str] = None,
    ) -> Optional[str]:
        """Stamp a flight record (and the lineage record) with the fault its
        update triggered, if any."""
        if record is None and trace_id is None:
            return None
        quarantined, skipped = self._robust_counts()
        fault: Optional[str] = None
        if quarantined > before[0]:
            fault = "quarantined"
        elif skipped > before[1]:
            fault = "skipped"
        if record is not None:
            record["fault"] = fault
        if trace_id is not None and fault is not None:
            _lineage.get_index().update(trace_id, outcome=fault)
        return fault

    def _dump_flight(
        self, reason: str, poisoned: List[int], trace_ids: Optional[List[str]] = None
    ) -> Optional[str]:
        """Dump the flight ring on a fault; telemetry rides along when tracing."""
        if self._flight is None:
            return None
        config = {
            "fuse": self.config.fuse,
            "max_in_flight": self.config.max_in_flight,
            "prefetch": self.config.prefetch,
            "buckets": list(self._buckets),
            "tenant": self._tenant,
        }
        path = self._flight.dump(reason, poisoned, config, poisoned_trace_ids=trace_ids)
        if path is not None:
            self._report.flight_dumps += 1
            _lineage.note_dump(trace_ids or [], path)
            if _trace.ENABLED:
                _trace.inc("flight.dumps", pipeline=self._label)
                _trace.event(
                    "engine.flight_dump",
                    pipeline=self._label,
                    reason=reason,
                    path=path,
                    poisoned=",".join(map(str, sorted(set(poisoned)))),
                    trace_ids=",".join(sorted(set(trace_ids or []))),
                )
        return path

    def _drive_per_batch(
        self,
        args: tuple,
        kwargs: dict,
        record: Optional[dict] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        """Whole-target per-batch update (fusion off or target unfusable)."""
        attributed = record is not None or trace_id is not None
        before = self._robust_counts() if attributed else (0, 0)
        start = time.perf_counter() if record is not None else 0.0
        with _lineage.trace(trace_id):
            if _trace.ENABLED:
                span_attrs: Dict[str, Any] = {
                    "pipeline": self._label,
                    "path": "eager",
                    "batch_index": self._ingested - 1,
                }
                if trace_id is not None:
                    span_attrs["trace_id"] = trace_id
                with _trace.span("engine.dispatch", **span_attrs):
                    self._suppressing_refault(lambda: self._target.update(*args, **kwargs))
            else:
                self._suppressing_refault(lambda: self._target.update(*args, **kwargs))
        self._report.eager_batches += 1
        self._report.eager_dispatches += 1
        if _audit.ENABLED:
            _audit.note_fold(self, "pipeline", self._tenant, self._lineage_epoch, trace_id)
        if _trace.ENABLED:
            _trace.inc("engine.eager_batches", pipeline=self._label)
        waited = self._ticket(self._current_any_state())
        if attributed:
            if trace_id is not None:
                _lineage.get_index().update(trace_id, path="eager", outcome="ok")
            if record is not None:
                record["path"] = "eager"
                record["stages"]["dispatch"] = round(time.perf_counter() - start, 6)
                record["stages"]["blocked_on_inflight"] = round(waited, 6)
            if self._mark_fault(record, before, trace_id) == "quarantined":
                # the per-batch path has no replay step: the quarantine itself
                # is the fault event, so it dumps the lineage directly
                self._dump_flight(
                    "quarantine",
                    [record["batch_index"]] if record is not None else [],
                    trace_ids=[trace_id] if trace_id is not None else None,
                )
        self._maybe_checkpoint()
        self._evaluate_alerts(trace_ids=[trace_id] if trace_id is not None else ())

    def _drive_eager_leaders(self, args: tuple, kwargs: dict) -> None:
        def _run() -> None:
            for name in self._eager_leaders:
                m = self._target._modules[name]
                m.update(*args, **m._filter_kwargs(**kwargs))

        self._suppressing_refault(_run)
        self._report.eager_dispatches += len(self._eager_leaders)

    def _drive_fused_leaders_eagerly(
        self,
        args: tuple,
        kwargs: dict,
        record: Optional[dict] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        """Per-batch fallback for a batch that cannot join a chunk."""

        def _run() -> None:
            for m in self._per_batch_metrics():
                filtered = m._filter_kwargs(**kwargs) if self._is_collection else kwargs
                m.update(*args, **filtered)

        attributed = record is not None or trace_id is not None
        before = self._robust_counts() if attributed else (0, 0)
        start = time.perf_counter() if record is not None else 0.0
        with _lineage.trace(trace_id):
            if _trace.ENABLED:
                span_attrs: Dict[str, Any] = {
                    "pipeline": self._label,
                    "path": "eager",
                    "batch_index": self._ingested - 1,
                }
                if trace_id is not None:
                    span_attrs["trace_id"] = trace_id
                with _trace.span("engine.dispatch", **span_attrs):
                    self._suppressing_refault(_run)
            else:
                self._suppressing_refault(_run)
        if self._is_collection:
            self._target._sync_group_states()
        self._report.eager_batches += 1
        # one host dispatch per driven metric (multi-group collections issue
        # several updates per batch), matching _drive_eager_leaders' accounting
        self._report.eager_dispatches += max(1, len(self._per_batch_metrics()))
        if _audit.ENABLED:
            _audit.note_fold(self, "pipeline", self._tenant, self._lineage_epoch, trace_id)
        if attributed:
            if trace_id is not None:
                _lineage.get_index().update(trace_id, path="eager", outcome="ok")
            if record is not None:
                record["path"] = "eager"
                record["stages"]["dispatch"] = round(time.perf_counter() - start, 6)
            if self._mark_fault(record, before, trace_id) == "quarantined":
                self._dump_flight(
                    "quarantine",
                    [record["batch_index"]] if record is not None else [],
                    trace_ids=[trace_id] if trace_id is not None else None,
                )
        self._maybe_checkpoint()
        self._evaluate_alerts(trace_ids=[trace_id] if trace_id is not None else ())

    def _replay_chunk(self, chunk: _Chunk, cid: int) -> None:
        """Per-batch replay of a degraded chunk: the metrics' own guarded updates
        isolate (skip/quarantine) exactly the poisoned batches.

        The flight recorder dumps the ring exactly once per degraded chunk —
        after the replay has named the poisoned batches (or immediately when a
        ``raise`` policy propagates mid-replay), so the dump always carries the
        fault attribution alongside the preceding batches' lineage.
        """
        self._report.chunks_replayed += 1
        if _trace.ENABLED:
            _trace.inc("engine.chunks_replayed", pipeline=self._label)
        poisoned: List[int] = []
        poisoned_ids: List[str] = []
        for step, (args, kwargs) in enumerate(chunk.originals):
            record = chunk.records[step] if step < len(chunk.records) else None
            tid = chunk.trace_ids[step] if step < len(chunk.trace_ids) else None
            attributed = record is not None or tid is not None
            before = self._robust_counts() if attributed else (0, 0)
            start = time.perf_counter() if record is not None else 0.0

            def _run(args=args, kwargs=kwargs) -> None:
                for m in self._per_batch_metrics():
                    filtered = m._filter_kwargs(**kwargs) if self._is_collection else kwargs
                    m.update(*args, **filtered)

            try:
                with _lineage.trace(tid):
                    if _trace.ENABLED:
                        span_attrs: Dict[str, Any] = {
                            "pipeline": self._label,
                            "path": "replay",
                            "chunk_id": cid,
                            "batch_index": chunk.first_index + step,
                        }
                        if tid is not None:
                            span_attrs["trace_id"] = tid
                        with _trace.span("engine.dispatch", **span_attrs):
                            self._suppressing_refault(_run)
                    else:
                        self._suppressing_refault(_run)
            except BaseException:
                # raise policy (or an unguarded failure): the faulting batch is
                # named and the lineage dumped BEFORE the exception propagates
                if tid is not None:
                    poisoned_ids.append(tid)
                    _lineage.get_index().update(
                        tid, chunk_id=cid, path="replay", outcome="raised"
                    )
                if record is not None:
                    record["chunk_id"] = cid
                    record["path"] = "replay"
                    record["fault"] = "raised"
                    poisoned.append(record["batch_index"])
                if record is not None or tid is not None:
                    self._dump_flight("chunk_replay", poisoned, trace_ids=poisoned_ids)
                raise
            self._report.replayed_batches += 1
            self._report.eager_dispatches += max(1, len(self._per_batch_metrics()))
            if _audit.ENABLED:
                _audit.note_fold(self, "pipeline", self._tenant, self._lineage_epoch, tid)
            if _trace.ENABLED:
                _trace.inc("engine.replayed_batches", pipeline=self._label)
            if attributed:
                if tid is not None:
                    _lineage.get_index().update(
                        tid, chunk_id=cid, path="replay", outcome="ok"
                    )
                if record is not None:
                    record["chunk_id"] = cid
                    record["path"] = "replay"
                    record["stages"]["dispatch"] = round(time.perf_counter() - start, 6)
                if self._mark_fault(record, before, tid) is not None:
                    if record is not None:
                        poisoned.append(record["batch_index"])
                    if tid is not None:
                        poisoned_ids.append(tid)
        if self._is_collection:
            self._target._sync_group_states()
        waited = self._ticket(self._current_any_state())
        for record in chunk.records:
            record["stages"]["blocked_on_inflight"] = round(waited, 6)
        self._dump_flight("chunk_replay", poisoned, trace_ids=poisoned_ids)
        self._maybe_checkpoint()
        self._evaluate_alerts(trace_ids=[t for t in chunk.trace_ids if t is not None])

    # ------------------------------------------------------------ alerting seam

    def _evaluate_alerts(self, force: bool = False, trace_ids: Iterable[str] = ()) -> None:
        """Per-committed-chunk value-health evaluation (``config.alert_engine``).

        Samples the target's values sync-free (``pure_update`` streams must not
        trigger cross-host collectives mid-epoch), runs the rules, and — when a
        *value* watchdog newly fires — dumps the flight-recorder ring so the
        bad value arrives with the batch lineage that produced it. A broken
        engine warns once and the stream keeps flowing.
        """
        engine = self._alert_engine
        if engine is None:
            return
        self._alert_commits += 1
        if not force and self._alert_commits % self.config.alert_every:
            return
        try:
            # sample into the ENGINE's value log (an AlertEngine built with a
            # custom `value_log=` reads only that log; the global one is just
            # the default), so mid-stream samples always reach the rules
            log_hook = getattr(engine, "_log", None)
            _values.sample_local(
                self._target, log=log_hook() if callable(log_hook) else None
            )
            transitions = engine.evaluate()
        except Exception as err:
            if not self._alert_warned:
                self._alert_warned = True
                rank_zero_warn(
                    f"Alert evaluation failed on the {self._label} pipeline"
                    f" ({type(err).__name__}: {err}). The stream keeps flowing and"
                    " evaluation will keep being attempted per chunk, but further"
                    " failures are silent (this warning fires once) and value"
                    " watchdogs may be stale.",
                    RuntimeWarning,
                )
            return
        fired = [
            t for t in transitions if t["to"] == "firing" and t.get("source") == "values"
        ]
        if not fired:
            return
        rules = sorted({t["rule"] for t in fired})
        # the commit that triggered this evaluation links the fired rules to
        # the batches it folded: for an unguarded NaN (the victim-tenant
        # scenario) this is exactly "injection → value watchdog firing" on the
        # poisoned batch's own lineage record
        _lineage.note_alert(list(trace_ids), rules)
        if _trace.ENABLED:
            _trace.inc("engine.value_alerts", len(fired), pipeline=self._label)
            _trace.event(
                "engine.value_alert",
                pipeline=self._label,
                rules=",".join(rules),
                series=",".join(sorted({t["series"] for t in fired})),
            )
        # a value watchdog firing mid-stream IS a fault: ship the last-K-batch
        # lineage with the alert names attached (no poisoned batch to name —
        # the value, not an input, is what broke)
        self._dump_flight("value_alert:" + ",".join(rules), [])

    # -------------------------------------------------------------------- plumbing

    def _current_any_state(self) -> Any:
        if self._is_collection:
            return {name: m._state_values for name, m in self._target._modules.items()}
        return self._target._state_values

    def _ticket(self, state_like: Any) -> float:
        """Bound the async window: hold a leaf of each dispatched state, block on
        the oldest once more than ``max_in_flight`` are outstanding. Returns the
        seconds spent blocked (the flight recorder's ``blocked_on_inflight``)."""
        ticket = None
        for leaf in jax.tree_util.tree_leaves(state_like):
            if isinstance(leaf, jax.Array):
                ticket = leaf
                break
        if ticket is None:
            return 0.0  # host-only state (e.g. compute_on_cpu lists): nothing async
        waited = 0.0
        self._inflight.append(ticket)
        while len(self._inflight) > self.config.max_in_flight:
            oldest = self._inflight.popleft()
            is_ready = getattr(oldest, "is_ready", None)
            if is_ready is None or not is_ready():
                self._report.inflight_waits += 1
                if _trace.ENABLED:
                    _trace.inc("engine.inflight_waits", pipeline=self._label)
            start = time.perf_counter()
            jax.block_until_ready(oldest)
            waited += time.perf_counter() - start
        if _trace.ENABLED:
            _trace.set_gauge(
                "engine.in_flight", len(self._inflight), pipeline=self._label, inst=self._instance
            )
        return waited

    def _check_buffer_overflow(self) -> None:
        for m in self._per_batch_metrics():
            m._check_buffer_overflow()
        for name in self._eager_leaders:
            self._target._modules[name]._check_buffer_overflow()
