"""Cross-tenant fused dispatch: many tenants' updates in ONE compiled program.

PR 8 made a pipeline a tenant session; this module makes tenants share
*executables*. A serving process with 10k tenant sessions still issues 10k
independent dispatch streams, and — worse — compiles O(tenants × signatures)
program variants, because every tenant's metric instance owns its own jit
cache. That is exactly the compiled-program-count blowup the pjit/TPU-scaling
playbook avoids by batching work into a small set of shape-bucketed programs.
:class:`TenantMultiplexer` is that batching layer for metric serving:

- **One dispatch, many tenants** — same-signature update batches from
  *different* tenants are stacked on a leading tenant axis together with their
  per-tenant states, and folded with ONE ``jax.vmap`` of the existing
  ``pure_update`` transition (collections: per compute-group leader, exactly
  like the streaming pipeline's fused scan). Results are bit-identical to
  per-tenant eager updates — vmap batches the same program, it does not change
  it.
- **Tenant-width buckets** — a group of N tenants is padded up to the next
  power-of-two width with a masked tail (padded rows pass their state through
  unchanged), reusing the engine's shape-bucket discipline so the compiled
  program count stays **O(width-buckets × signatures)**, independent of the
  tenant population. :meth:`warmup` AOT-precompiles every (width-bucket,
  signature) variant, persistent-compile-cache included.
- **Per-tenant fault isolation** — the PR-5 robust seam survives the fusion:
  a group is screened once for non-finite inputs; a poisoned row degrades
  exactly *its* tenant's batch to that tenant's own guarded ``update``
  (skip/quarantine/raise per its policy) while the rest of the cohort still
  lands fused. A tenant never pays for its neighbor's garbage.
- **Cost-aware admission** — with an
  :class:`~torchmetrics_tpu.obs.scope.AdmissionController` configured (or
  installed process-wide), every fed batch is admitted, shed or deferred
  against the tenant's quota, and executed work is billed back priced by the
  cost ledger's per-dispatch estimates (flops/bytes per fused row, compile
  seconds split across the group that forced them). Over-quota pressure
  surfaces as ``tenant.quota_*`` gauges and the ``tenant.quota_exceeded``
  alert signal.

- **Flight recorder** — the per-row lineage ring + dump-on-fault of
  :class:`~torchmetrics_tpu.engine.pipeline.MetricPipeline`, ported to the
  cross-tenant plane: every fed row keeps (tenant, tenant-local batch index,
  signature, group id, dispatch path) in a bounded ring, and a poisoned row
  produces a named-batch JSONL dump attributed to exactly its owning tenant
  (one dump per faulted tenant, full cross-tenant ring as context) — parity
  with the per-tenant pipeline's evidence, so the chaos SLO judge reads both
  alike. ``MuxConfig.flight_records=0`` disables it.

Per-tenant stream order is preserved: a tenant feeding a second batch (or a
new signature) before its pending group dispatched flushes that group first.
Cross-tenant order inside one group is irrelevant by construction — rows fold
independent states.

Telemetry (``torchmetrics_tpu.obs``, off by default): ``engine.mux_*``
counters/gauges (dispatches, fused/eager/replayed updates, padded rows, shed
and deferred admission decisions, last/peak dispatch width), ``engine.dispatch``
spans with ``path="mux"``. :meth:`report` returns the same accounting as plain
ints.
"""

from __future__ import annotations

import itertools
import os
import tempfile
import time
from dataclasses import dataclass, fields, replace
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

import torchmetrics_tpu.obs.audit as _audit
import torchmetrics_tpu.obs.cost as _cost
import torchmetrics_tpu.obs.lineage as _lineage
import torchmetrics_tpu.obs.scope as _scope
import torchmetrics_tpu.obs.trace as _trace
import torchmetrics_tpu.obs.values as _values
from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.core.jit import (
    StaticLeafJit,
    _ArraySlot,
    _aval_signature,
    jit_with_static_leaves,
    partition_static_leaves,
    signature_str,
)
from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.engine import warmup as _warmup
from torchmetrics_tpu.engine.pipeline import FLIGHT_DIR_ENV, _FlightRecorder
from torchmetrics_tpu.robust import fence as _fence
from torchmetrics_tpu.robust.policy import effective_policy, nonfinite_step_indices
from torchmetrics_tpu.utils.prints import rank_zero_warn

__all__ = ["MuxConfig", "MuxReport", "TenantMultiplexer"]


@dataclass
class MuxConfig:
    """Tuning knobs for :class:`TenantMultiplexer`.

    Args:
        max_width: max tenants fused into one dispatch (the top width bucket).
        width_buckets: explicit tenant-width buckets (ascending). Default:
            powers of two up to ``max_width`` — a partial group pads up to the
            next bucket with a masked tail, so compiled-variant count stays
            ``O(log max_width)`` per signature.
        admission: an :class:`~torchmetrics_tpu.obs.scope.AdmissionController`
            consulted per fed batch. ``None`` falls back to the process-wide
            controller (:func:`~torchmetrics_tpu.obs.scope.get_admission`),
            which may also be ``None`` — everything admitted.
        alert_engine: an :class:`~torchmetrics_tpu.obs.alerts.AlertEngine`
            evaluated per committed group — each committed tenant's values are
            sampled sync-free under its own session, so per-tenant watchdogs
            see mid-stream state exactly as with per-tenant pipelines.
        alert_every: evaluate the alert engine every Nth committed group
            (``close()`` always runs a final evaluation).
        max_deferred: per-tenant cap on the deprioritized backlog — deferred
            batches hold real device arrays, so a tenant parked over quota
            for hours must not grow memory without bound. Past the cap,
            further defer decisions degrade to shed (counted, loud once).
        readmit_check_seconds: how often the multiplexer's per-feed sweep
            probes deferred tenants' quotas (read-only
            :meth:`~torchmetrics_tpu.obs.scope.AdmissionController.would_admit`)
            for wall-clock re-admission — an idle-but-deferred tenant drains
            on any *other* tenant's traffic once its window rolls, instead of
            starving until its own next feed or ``close()``.
        flight_records: flight-recorder ring capacity — the last this-many
            fed rows keep their lineage (tenant, tenant-local batch index,
            signature, group membership, dispatch path) for a dump-on-fault,
            exactly the :class:`~torchmetrics_tpu.engine.pipeline.MetricPipeline`
            recorder ported to the cross-tenant plane. ``0`` disables it.
        flight_dump_dir: where fault dumps land. ``None``: the
            ``TM_TPU_FLIGHT_DIR`` environment variable, else
            ``<tempdir>/tm_tpu_flight``.
        flight_max_dumps: hard cap on dump files one multiplexer writes
            (suppressed dumps are counted).
        device: target device for stacked batches (``None``: default device).
        checkpoint: a :class:`~torchmetrics_tpu.engine.migrate.CheckpointPolicy`
            — continuous checkpointing for the multiplexed plane. On cadence
            (counted over the mux's committed batches / wall clock, checked at
            group-commit boundaries) every adopted tenant's **slice** is
            written as its own pipeline-restorable bundle stream under
            ``<directory>/<tenant>/`` (delta-encoded, compacted, swept — the
            :class:`~torchmetrics_tpu.engine.pipeline.MetricPipeline` policy
            semantics per tenant). ``None`` (default) disables.
        lease_seconds: TTL of the multiplexer's renewable session **lease**
            (:mod:`torchmetrics_tpu.robust.fence`). The mux holds ONE lease —
            one session epoch, the fencing token shared by every adopted
            tenant — renewed on feed/commit (throttled to ~TTL/4), recorded
            per tenant in the scope lease registry, and stamped into every
            tenant slice bundle. Default 30 s.
    """

    max_width: int = 64
    width_buckets: Optional[Tuple[int, ...]] = None
    admission: Any = None
    alert_engine: Any = None
    alert_every: int = 1
    max_deferred: int = 1024
    readmit_check_seconds: float = 0.25
    flight_records: int = 64
    flight_dump_dir: Optional[str] = None
    flight_max_dumps: int = 16
    device: Any = None
    checkpoint: Any = None
    lease_seconds: float = 30.0

    def __post_init__(self) -> None:
        if self.max_width < 1:
            raise ValueError(f"Expected `max_width` >= 1, got {self.max_width}")
        if self.lease_seconds <= 0:
            raise ValueError(f"Expected `lease_seconds` > 0, got {self.lease_seconds}")
        if self.alert_every < 1:
            raise ValueError(f"Expected `alert_every` >= 1, got {self.alert_every}")
        if self.max_deferred < 1:
            raise ValueError(f"Expected `max_deferred` >= 1, got {self.max_deferred}")
        if self.readmit_check_seconds < 0:
            raise ValueError(
                f"Expected `readmit_check_seconds` >= 0, got {self.readmit_check_seconds}"
            )
        if self.flight_records < 0:
            raise ValueError(f"Expected `flight_records` >= 0, got {self.flight_records}")
        if self.flight_max_dumps < 0:
            raise ValueError(f"Expected `flight_max_dumps` >= 0, got {self.flight_max_dumps}")
        if self.width_buckets is not None:
            buckets = tuple(sorted(set(int(b) for b in self.width_buckets)))
            if not buckets or buckets[0] < 1:
                raise ValueError(f"Expected positive `width_buckets`, got {self.width_buckets}")
            if buckets[-1] > self.max_width:
                raise ValueError(
                    f"`width_buckets` top bucket {buckets[-1]} exceeds `max_width`"
                    f" {self.max_width} — every full group would pad (and bill) phantom"
                    " rows past the dispatch cap"
                )
            if buckets[-1] < self.max_width:
                buckets = buckets + (self.max_width,)
            self.width_buckets = buckets

    def buckets(self) -> Tuple[int, ...]:
        if self.width_buckets is not None:
            return self.width_buckets
        return _warmup.pow2_buckets(self.max_width)


@dataclass
class MuxReport:
    """Plain-int accounting of one multiplexer's work (no obs tracing needed)."""

    batches: int = 0  # batches ingested (admitted + deferred-replayed)
    fused_updates: int = 0  # tenant-updates landed via a fused vmap dispatch
    eager_updates: int = 0  # tenant-updates driven through per-tenant `update`
    replayed_updates: int = 0  # guarded per-tenant replays of poisoned rows
    dispatches: int = 0  # fused vmap dispatches issued
    eager_dispatches: int = 0  # per-tenant update dispatches (incl. replays)
    shed_batches: int = 0  # admission decisions: dropped over-quota batches
    deferred_batches: int = 0  # admission decisions: deprioritized batches
    deferred_replayed: int = 0  # deferred batches later ingested
    padded_rows: int = 0  # masked tenant rows added by width-bucket padding
    order_flushes: int = 0  # groups dispatched early to keep a tenant's order
    flight_dumps: int = 0  # flight-recorder fault dumps written
    max_width: int = 0
    last_width: int = 0

    def host_dispatches(self) -> int:
        return self.dispatches + self.eager_dispatches

    def dispatches_per_update(self) -> Optional[float]:
        """Host dispatches per landed tenant-update (< 1.0 once fusion engages)."""
        landed = self.fused_updates + self.eager_updates + self.replayed_updates
        if not landed:
            return None
        return self.host_dispatches() / landed

    def processed_batches(self) -> int:
        """Canonical processed count: every tenant-update that landed."""
        return self.fused_updates + self.eager_updates + self.replayed_updates

    def asdict(self) -> Dict[str, Any]:
        out = {f.name: getattr(self, f.name) for f in fields(self)}
        out["host_dispatches"] = self.host_dispatches()
        out["dispatches_per_update"] = self.dispatches_per_update()
        # canonical vocabulary shared with PipelineReport.asdict — the mux's
        # historical `*_updates` / `padded_rows` / `order_flushes` names stay
        # as back-compat aliases of the same quantities
        out["processed_batches"] = self.processed_batches()
        out["fused_batches"] = self.fused_updates
        out["eager_batches"] = self.eager_updates
        out["replayed_batches"] = self.replayed_updates
        out["padded_steps"] = self.padded_rows
        out["shape_flushes"] = self.order_flushes
        return out


# runtime state that legitimately differs between healthy same-config
# instances; everything else public+hashable is configuration the fused
# program bakes in (error_policy is per-tenant by design: it guards the
# eager/replay path, never the pure transition)
_RUNTIME_ATTRS = frozenset(
    {
        "updates_ok",
        "updates_skipped",
        "updates_quarantined",
        "quarantine_dropped",
        "last_update_ok",
        "sync_degraded",
        "error_policy",
    }
)


def _config_fingerprint(target: Any) -> Any:
    """Hashable-config snapshot of a metric (or collection, per member).

    The fused program traces the TEMPLATE instance's ``pure_update``, so every
    adopted target must agree on the configuration that transition bakes in —
    a same-class tenant with a different ``ignore_index`` would otherwise
    compute silently with the template's. Public scalar/tuple attributes are
    the configuration surface; runtime counters and per-tenant robust policy
    are excluded.
    """

    def one(m: Any) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k, v in vars(m).items():
            if k.startswith("_") or k in _RUNTIME_ATTRS:
                continue
            if isinstance(v, (bool, int, float, str, bytes, tuple, frozenset, type(None))):
                out[k] = v
            elif hasattr(v, "dtype") and hasattr(v, "shape"):
                # array-valued configuration (e.g. curve metrics' `thresholds`
                # buffer) is configuration too — two tenants binning on
                # different thresholds must not share a fused program
                arr = np.asarray(v)
                if arr.size <= 65536:
                    out[k] = (str(arr.dtype), arr.shape, arr.tobytes())
        return out

    modules = getattr(target, "_modules", None)
    if isinstance(modules, dict):
        return {name: (type(m).__name__, one(m)) for name, m in modules.items()}
    return one(target)


class _MuxGroup:
    """One open fusion group: same-signature rows from distinct tenants."""

    __slots__ = (
        "sig",
        "treedef",
        "template",
        "tenants",
        "traced",
        "originals",
        "records",
        "trace_ids",
    )

    def __init__(self, sig: tuple, treedef: Any, template: tuple) -> None:
        self.sig = sig
        self.treedef = treedef
        self.template = template
        self.tenants: List[str] = []
        self.traced: List[list] = []  # per row: traced leaves, template order
        self.originals: List[Tuple[tuple, dict]] = []
        self.records: List[Optional[dict]] = []  # per row: flight record (or None)
        self.trace_ids: List[Optional[str]] = []  # per row: lineage id (None when off)

    def __len__(self) -> int:
        return len(self.tenants)


class TenantMultiplexer:
    """Fold same-signature updates from many tenants with one ``vmap`` dispatch.

    Usage::

        mux = TenantMultiplexer(lambda: MulticlassAccuracy(num_classes=4),
                                MuxConfig(max_width=64))
        for tenant in tenants:
            mux.adopt(tenant)
        mux.warmup(example_preds, example_target)    # AOT every width bucket
        for tenant, batch in traffic:
            mux.feed(tenant, *batch)                 # fuses across tenants
        mux.close()
        value = mux.compute("acme-prod")

    Every tenant owns a *separate* metric instance (``factory()``) — state is
    never shared; only the compiled programs are. Targets with ragged list
    states (or ``jit_update=False``) degrade to per-tenant eager updates
    automatically, exactly like the streaming pipeline.
    """

    _instance_seq = itertools.count()

    def __init__(
        self,
        factory: Optional[Callable[[], Union[Metric, MetricCollection]]] = None,
        config: Optional[MuxConfig] = None,
        metrics: Optional[Dict[str, Union[Metric, MetricCollection]]] = None,
        **overrides: Any,
    ) -> None:
        if config is None:
            config = MuxConfig(**overrides)
        elif overrides:
            config = replace(config, **overrides)
        if factory is None and not metrics:
            raise ValueError(
                "TenantMultiplexer needs a metric factory or an initial `metrics` dict"
            )
        self.config = config
        self._factory = factory
        self._metrics: Dict[str, Union[Metric, MetricCollection]] = {}
        # raw tenant name -> effective label (identity for in-cap tenants;
        # past-cap names collapse onto the shared OVERFLOW_TENANT session)
        self._aliases: Dict[str, str] = {}
        self._template: Optional[Union[Metric, MetricCollection]] = None
        self._is_collection = False
        self._fused_leaders: List[Optional[str]] = []
        self._eager_leaders: List[str] = []
        self._fusable = False
        self._label = "TenantMultiplexer"
        self._buckets = config.buckets()
        self._groups: Dict[tuple, _MuxGroup] = {}
        self._pending: Dict[str, tuple] = {}  # tenant -> sig of its open row
        self._fused_fns: Dict[tuple, StaticLeafJit] = {}
        # per-tenant deprioritized backlogs as (args, kwargs, trace_id): the
        # id was minted at first arrival, so defer → re-admission keeps it
        self._deferred: Dict[str, List[Tuple[tuple, dict, Optional[str]]]] = {}
        self._report = MuxReport()
        self._warmup_manifest: Optional[Dict[str, Any]] = None
        self._alert_commits = 0
        self._alert_warned = False
        self._shed_warned: set = set()
        # per-tenant ingest ordinals: flight records and dump attribution name
        # TENANT-LOCAL batch indices (the schedule/SLO ground-truth shape)
        self._tenant_batch_index: Dict[str, int] = {}
        # per-tenant ARRIVAL ordinals (lineage ids only): assigned at feed,
        # before admission, so shed/deferred rows keep identity — a separate
        # counter so flight-record numbering stays identical whether or not
        # lineage is enabled (the pipeline's two-ordinal-space model)
        self._tenant_arrivals: Dict[str, int] = {}
        # per-tenant shed+defer counts: once a tenant detoured, its arrival
        # and processed ordinals no longer line up — slice captures and the
        # covering-checkpoint join consult this (per tenant, not mux-global)
        self._tenant_detours: Dict[str, int] = {}
        # per-tenant ledger splits of the detours (the conservation auditor's
        # inputs — mux-global report counters can't attribute a shed row):
        # sheds, defer decisions, and deferred rows later replayed
        self._tenant_shed: Dict[str, int] = {}
        self._tenant_deferred: Dict[str, int] = {}
        self._tenant_deferred_replayed: Dict[str, int] = {}
        # per-tenant PROCESSED counts (fused commits + eager + replays): the
        # slice-checkpoint cursor — never counts a row still pending in an
        # open group, so every slice bundle is commit-consistent
        self._tenant_folded: Dict[str, int] = {}
        self._group_seq = 0
        self._last_readmit_check = 0.0
        # batch lineage (obs/lineage.py): one epoch per multiplexer; trace ids
        # are minted per ROW at ingestion from the tenant-local batch ordinal,
        # so a dump's (tenant, batch-index) evidence and the id name the same
        # batch. Persisted into tenant-slice bundles so a restored pipeline
        # session keeps the mux's id space.
        self._lineage_epoch = _lineage.new_epoch()
        self._instance = str(next(TenantMultiplexer._instance_seq))
        if config.flight_records > 0:
            dump_dir = (
                config.flight_dump_dir
                or os.environ.get(FLIGHT_DIR_ENV)
                or os.path.join(tempfile.gettempdir(), "tm_tpu_flight")
            )
            self._flight: Optional[_FlightRecorder] = _FlightRecorder(
                "TenantMultiplexer",
                self._instance,
                config.flight_records,
                dump_dir,
                config.flight_max_dumps,
            )
        else:
            self._flight = None
        # per-width-bucket (flops, bytes) per dispatch — a width-1 program
        # costs ~1/64th of a width-64 one, so billing must price the bucket
        # that actually executed, not a cross-width mean
        self._width_prices: Dict[int, Tuple[Optional[float], Optional[float]]] = {}
        self._closed = False
        # continuous checkpointing (engine/migrate.py): one bundle stream per
        # adopted tenant under <policy.directory>/<tenant>, gated by ONE
        # mux-level cadence so a trigger snapshots the whole cohort
        self._checkpointers: Dict[str, Any] = {}
        self._ckpt_last_batches = 0
        self._ckpt_last_time = time.monotonic()
        # ONE session lease for the whole mux — one epoch, one fencing token
        # shared by every adopted tenant. No registry row is written here:
        # adopt()/renewal record it per TENANT, so GET /leases shows each
        # tenant's row (same holder/epoch/expiry) and no phantom global row
        _lease_now = time.time()
        self._lease = {
            "holder": _fence.holder_id(),
            "epoch": self._lineage_epoch,
            "ttl_seconds": float(config.lease_seconds),
            "expires_unix": _lease_now + float(config.lease_seconds),
            "renewed_unix": _lease_now,
        }
        self._lease_renew_at = _lease_now + config.lease_seconds / 4.0
        if _audit.ENABLED:
            _audit.track(self, "mux", self._label)
        for tenant, metric in (metrics or {}).items():
            self.adopt(tenant, metric)
        # persistent compile cache wiring is part of engine startup (no-op
        # unless TM_TPU_COMPILE_CACHE or an earlier explicit call set a dir)
        _warmup.configure_compile_cache()

    # ------------------------------------------------------------------ membership

    def adopt(
        self, tenant: str, metric: Optional[Union[Metric, MetricCollection]] = None
    ) -> Union[Metric, MetricCollection]:
        """Register ``tenant`` with its own metric instance (created via the
        factory when not given); returns the instance.

        The tenant is registered with the scope registry as a live session
        (``active_pipelines``), and the metric adopts the tenant label so its
        eager paths (direct compute, robust counters, memory gauges) stay
        attributed. The first adopted target fixes the template: every later
        target must be the same class (same state structure — the fused
        program folds all of them).

        Past the registry cap, new tenant names collapse onto the shared
        :data:`~torchmetrics_tpu.obs.scope.OVERFLOW_TENANT` session — the
        registry's documented attribution-loss semantic: their traffic keeps
        flowing (through one shared metric instance), it just stops being
        individually attributable.
        """
        raw = _scope.validate_tenant(tenant)
        if raw in self._aliases:
            raise ValueError(f"Tenant {raw!r} is already multiplexed")
        effective = _scope.adopt(raw)
        if effective in self._metrics:
            if effective == raw:
                raise ValueError(f"Tenant {raw!r} is already multiplexed")
            # past-cap collapse: the raw name joins the overflow session
            self._aliases[raw] = effective
            return self._metrics[effective]
        if metric is None:
            if self._factory is None:
                raise ValueError(f"No factory to build a metric for tenant {tenant!r}")
            with _scope.session(effective):
                metric = self._factory()
        if not isinstance(metric, (Metric, MetricCollection)):
            raise ValueError(
                f"TenantMultiplexer drives Metric or MetricCollection targets,"
                f" got {type(metric).__name__}"
            )
        if self._template is None:
            self._template = metric
            self._is_collection = isinstance(metric, MetricCollection)
            self._label = f"Mux[{type(metric).__name__}]"
            if self._is_collection:
                self._fused_leaders, self._eager_leaders = metric._engine_fusable_leaders()
            else:
                self._fused_leaders, self._eager_leaders = [], []
                if metric._engine_fusable():
                    self._fused_leaders = [None]  # sentinel: the metric itself fuses
            self._fusable = bool(self._fused_leaders)
        elif type(metric) is not type(self._template):
            raise ValueError(
                f"Tenant {tenant!r} brings a {type(metric).__name__} but this multiplexer"
                f" fuses {type(self._template).__name__} targets — one compiled program"
                " cannot fold mismatched state structures"
            )
        else:
            # same class is not enough: the fused program runs the TEMPLATE's
            # pure_update, so configuration (thresholds, ignore_index, top_k,
            # averaging, ...) must match or this tenant would silently compute
            # with the template's settings
            ours, theirs = _config_fingerprint(self._template), _config_fingerprint(metric)
            if ours != theirs:
                if isinstance(ours, dict) and isinstance(theirs, dict):
                    differing = sorted(
                        k for k in set(ours) | set(theirs) if ours.get(k) != theirs.get(k)
                    )
                else:  # pragma: no cover - both sides are dicts by construction
                    differing = ["<configuration>"]
                raise ValueError(
                    f"Tenant {tenant!r} brings a {type(metric).__name__} whose"
                    f" configuration differs from the template's on {differing} —"
                    " the fused program bakes in ONE configuration; use a separate"
                    " multiplexer (or per-tenant pipelines) for divergent configs"
                )
        if getattr(metric, "_obs_tenant", None) is None:
            metric._obs_tenant = effective
        if self._is_collection:
            for m in metric._modules.values():
                if getattr(m, "_obs_tenant", None) is None:
                    m._obs_tenant = effective
        self._metrics[effective] = metric
        self._aliases[raw] = effective
        _scope.get_registry().pipeline_started(effective)
        # every adopted tenant gets its own lease ROW (same holder, same
        # epoch, same expiry — the mux's one lease) so GET /leases and the
        # watchdog see each tenant individually
        self._note_tenant_lease(effective)
        if self.config.checkpoint is not None and effective not in self._checkpointers:
            from dataclasses import replace as _dc_replace

            from torchmetrics_tpu.engine.migrate import ContinuousCheckpointer

            policy = _dc_replace(
                self.config.checkpoint,
                directory=os.path.join(self.config.checkpoint.directory, effective),
            )
            self._checkpointers[effective] = ContinuousCheckpointer(
                policy, tenant=effective, label=self._label
            )
        return metric

    def _note_tenant_lease(self, effective: str) -> None:
        _scope.note_lease(
            effective,
            holder=self._lease["holder"],
            epoch=self._lease["epoch"],
            ttl_seconds=self._lease["ttl_seconds"],
            expires_unix=self._lease["expires_unix"],
            renewed_unix=self._lease["renewed_unix"],
        )

    def _renew_lease(self, force: bool = False) -> None:
        """Renew the mux's one lease (throttled to ~TTL/4) and refresh every
        adopted tenant's registry row with the new expiry."""
        now = time.time()
        if not force and now < self._lease_renew_at:
            return
        self._lease["expires_unix"] = now + self._lease["ttl_seconds"]
        self._lease["renewed_unix"] = now
        self._lease_renew_at = now + self._lease["ttl_seconds"] / 4.0
        for effective in self._metrics:
            self._note_tenant_lease(effective)
        if _trace.ENABLED:
            _trace.inc("lease.renewals")

    def lease_snapshot(self, tenant: Optional[str] = None) -> Dict[str, Any]:
        """The lease stamp a tenant slice bundle carries, freshly renewed —
        every slice write doubles as a cross-host renewal for the whole mux."""
        self._renew_lease(force=True)
        return {
            "holder": self._lease["holder"],
            "epoch": self._lease["epoch"],
            "ttl_seconds": self._lease["ttl_seconds"],
            "expires_unix": self._lease["expires_unix"],
            "renewed_unix": self._lease["renewed_unix"],
        }

    def _maybe_checkpoint(self, force: bool = False, skip_covered: bool = False) -> int:
        """Group-commit-boundary hook: when the mux-level cadence is due, every
        tenant's slice is written (its own delta stream). Returns bundles
        written. Open (undispatched) rows are excluded per tenant, so each
        slice is commit-consistent without flushing anyone's pending group.

        On cadence an idle tenant still gets a (near-empty) delta — the bundle
        is its freshness heartbeat; ``skip_covered`` (the close path) skips
        slices the last bundle already covers, since the freshness contract
        ends with the session anyway."""
        if not self._checkpointers:
            return 0
        policy = self.config.checkpoint
        committed = (
            self._report.fused_updates
            + self._report.eager_updates
            + self._report.replayed_updates
        )
        if not force:
            due_batches = (
                policy.every_batches
                and committed - self._ckpt_last_batches >= policy.every_batches
            )
            due_time = (
                policy.every_seconds
                and time.monotonic() - self._ckpt_last_time >= policy.every_seconds
            )
            if not due_batches and not due_time:
                return 0
        self._ckpt_last_batches = committed
        self._ckpt_last_time = time.monotonic()
        written = 0
        for tenant, checkpointer in self._checkpointers.items():
            if (
                checkpointer.maybe_mux_slice(
                    self, tenant, force=True, skip_if_covered=skip_covered
                )
                is not None
            ):
                written += 1
        return written

    def checkpoint_now(self) -> int:
        """Force one slice bundle per tenant (cadence bypassed); returns the
        number written (0 without a configured ``CheckpointPolicy``)."""
        return self._maybe_checkpoint(force=True)

    def _effective(self, tenant: str) -> str:
        """The session label a raw tenant name maps to (adopting on demand)."""
        effective = self._aliases.get(tenant)
        if effective is None:
            self.adopt(tenant)
            effective = self._aliases[tenant]
        return effective

    def tenants(self) -> List[str]:
        return list(self._metrics)

    def metric(self, tenant: str) -> Union[Metric, MetricCollection]:
        return self._metrics[self._aliases.get(tenant, tenant)]

    def report(self) -> MuxReport:
        """Copy of the accounting so far (safe to keep across further feeds)."""
        return replace(self._report)

    @property
    def warmup_manifest(self) -> Optional[Dict[str, Any]]:
        return self._warmup_manifest

    def cache_info(self) -> Dict[str, Any]:
        """Summed fused-program cache accounting across signature families."""
        infos = [fn.cache_info() for fn in self._fused_fns.values()]
        return {
            "families": len(infos),
            "static_variants": sum(i["static_variants"] for i in infos),
            "compiled_variants": sum(i["compiled_variants"] for i in infos),
            "hits": sum(i["hits"] for i in infos),
            "misses": sum(i["misses"] for i in infos),
        }

    def flight_records(self) -> List[dict]:
        """Copies of the flight-recorder ring (empty when ``flight_records=0``)."""
        return self._flight.records() if self._flight is not None else []

    @property
    def flight_dumps(self) -> List[str]:
        """Paths of the fault dumps this multiplexer has written."""
        return list(self._flight.dump_paths) if self._flight is not None else []

    @property
    def lineage_epoch(self) -> str:
        """The epoch this multiplexer's trace ids are minted under."""
        return self._lineage_epoch

    def trace_id_for(self, tenant: str, ordinal: int) -> str:
        """The (deterministic) trace id of ``tenant``'s ``ordinal``-th FED
        row — tenant-local arrival ordinals (identity is assigned before
        admission, so the driver's fed-event index is the right key)."""
        return _lineage.mint(
            self._aliases.get(tenant, tenant), self._lineage_epoch, ordinal
        )

    # ---------------------------------------------------------------------- feeding

    def _next_ordinal(self, tenant: str) -> int:
        """The tenant-local batch ordinal (flight records AND lineage ids)."""
        ordinal = self._tenant_batch_index.get(tenant, 0)
        self._tenant_batch_index[tenant] = ordinal + 1
        return ordinal

    def feed(self, tenant: str, *args: Any, **kwargs: Any) -> None:
        """Ingest one update batch for ``tenant`` (admission applies first)."""
        # everything downstream keys on the EFFECTIVE label, so past-cap
        # tenants (collapsed onto the overflow session) keep being served
        tenant = self._effective(tenant)
        self._renew_lease()  # throttled: live traffic keeps the mux lease warm
        trace_id = None
        if _lineage.ENABLED:
            # identity is assigned at FIRST arrival — before the admission
            # decision — so a deferred row re-admitted later keeps the id (and
            # the ingest stamp) it arrived with, exactly like the pipeline.
            # Minted from the tenant-local ARRIVAL ordinal (its own counter,
            # so flight-record ingest numbering is unchanged by this flag).
            ordinal = self._tenant_arrivals.get(tenant, 0)
            self._tenant_arrivals[tenant] = ordinal + 1
            trace_id = _lineage.mint(tenant, self._lineage_epoch, ordinal)
            _lineage.get_index().open(trace_id, tenant, ordinal)
        # wall-clock re-admission sweep: OTHER tenants' deferred backlogs whose
        # quota windows have rolled drain on this feed (interval-gated), so an
        # idle-but-deferred tenant rides any live traffic instead of starving.
        # The fed tenant itself is excluded — its own backlog drains through
        # the admit() path below, keeping the drain-then-admit order (and the
        # admit-the-crossing-batch semantic) exactly as before.
        self._maybe_readmit_deferred(exclude=tenant)
        controller = self._admission()
        if controller is not None:
            decision = controller.admit(tenant)
            if decision == _scope.DEFER:
                backlog = self._deferred.setdefault(tenant, [])
                if len(backlog) >= self.config.max_deferred:
                    # a full backlog holds real device arrays: degrade to
                    # shed instead of growing memory without bound — and tell
                    # the controller, whose admit() counted this as deferred
                    controller.note_degraded_shed(tenant)
                    decision = _scope.SHED
                else:
                    backlog.append((args, kwargs, trace_id))
                    self._report.deferred_batches += 1
                    self._tenant_detours[tenant] = self._tenant_detours.get(tenant, 0) + 1
                    self._tenant_deferred[tenant] = self._tenant_deferred.get(tenant, 0) + 1
                    if trace_id is not None:
                        _lineage.get_index().update(trace_id, outcome="deferred")
                    if _trace.ENABLED:
                        _trace.inc("engine.mux_deferred", mux=self._label, tenant=tenant)
                    return
            if decision == _scope.SHED:
                self._report.shed_batches += 1
                self._tenant_detours[tenant] = self._tenant_detours.get(tenant, 0) + 1
                self._tenant_shed[tenant] = self._tenant_shed.get(tenant, 0) + 1
                if trace_id is not None:
                    _lineage.get_index().update(trace_id, outcome="shed")
                if tenant not in self._shed_warned:
                    self._shed_warned.add(tenant)
                    rank_zero_warn(
                        f"Tenant {tenant!r} is over quota: its update batches are being"
                        " SHED (dropped, counted in tenant.quota_shed). This warning"
                        " fires once per tenant; the burn state is on GET /tenants.",
                        RuntimeWarning,
                    )
                if _trace.ENABLED:
                    _trace.inc("engine.mux_shed", mux=self._label, tenant=tenant)
                return
            # back under quota: the tenant's deferred backlog drains first so
            # its stream order is preserved
            backlog = self._deferred.pop(tenant, None)
            if backlog:
                for b_args, b_kwargs, b_trace_id in backlog:
                    self._report.deferred_replayed += 1
                    self._tenant_deferred_replayed[tenant] = (
                        self._tenant_deferred_replayed.get(tenant, 0) + 1
                    )
                    controller.charge(tenant, updates=1)
                    self._ingest(tenant, b_args, b_kwargs, trace_id=b_trace_id)
            controller.charge(tenant, updates=1)
        self._ingest(tenant, args, kwargs, trace_id=trace_id)

    def _admission(self) -> Optional[Any]:
        return self.config.admission if self.config.admission is not None else _scope.get_admission()

    def _ingest(
        self, tenant: str, args: tuple, kwargs: dict, trace_id: Optional[str] = None
    ) -> None:
        self._report.batches += 1
        # tenant-local INGEST ordinal: the index a dump names is the tenant's
        # own ingested-batch count, matching the per-tenant pipeline (and the
        # chaos schedule's poisoned-batch ground truth), not the shared mux
        # stream — and deliberately NOT the lineage arrival ordinal, so the
        # numbering is identical whether or not lineage is enabled (records
        # carry the trace id as the cross-space join when it is)
        batch_index = self._next_ordinal(tenant)
        if trace_id is not None and _lineage.ENABLED:
            # idempotent re-open: live records keep their arrival stamps, a
            # restored-host tail replay recreates the record
            _lineage.get_index().open(trace_id, tenant, _lineage.ordinal_of(trace_id))
        record = None
        if self._flight is not None:
            record = self._flight.open_record(batch_index, trace_id=trace_id)
            record["tenant"] = tenant
        if _trace.ENABLED:
            _trace.inc("engine.mux_batches", mux=self._label)
            if record is not None:
                _trace.set_gauge(
                    "flight.records", len(self._flight), pipeline=self._label, inst=self._instance
                )
        if not self._fusable:
            self._drive_eager(tenant, args, kwargs, record, trace_id)
            return
        if self._eager_leaders:
            # unfusable group leaders advance per batch, in stream order
            self._drive_eager_leaders(tenant, args, kwargs)
        args, kwargs = self._device_put(args, kwargs)
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        traced, template, unhashable = partition_static_leaves(leaves)
        if unhashable is not None:
            # unhashable statics cannot key a group signature: keep this
            # tenant's order (dispatch its pending group) and go eager
            self._flush_pending(tenant)
            self._drive_fused_leaders_eagerly(tenant, args, kwargs, record, trace_id)
            return
        sig = (treedef, tuple(template), _aval_signature(traced))
        if record is not None or trace_id is not None:
            sig_str = signature_str(sig[2])
            if record is not None:
                record["signature"] = sig_str
            if trace_id is not None:
                _lineage.get_index().update(trace_id, signature=sig_str)
        pending = self._pending.get(tenant)
        if pending is not None:
            # the tenant already has an undispatched row: its earlier batch
            # must land before this one, whatever group it sits in
            self._report.order_flushes += 1
            if _trace.ENABLED:
                _trace.inc("engine.mux_order_flush", mux=self._label)
            self._dispatch_sig(pending)
        group = self._groups.get(sig)
        if group is None:
            group = self._groups[sig] = _MuxGroup(sig, treedef, tuple(template))
        group.tenants.append(tenant)
        group.traced.append(traced)
        group.originals.append((args, kwargs))
        group.records.append(record)
        group.trace_ids.append(trace_id)
        self._pending[tenant] = sig
        if _trace.ENABLED:
            _trace.set_gauge("engine.mux_open_groups", len(self._groups), mux=self._label)
        if len(group) >= self.config.max_width:
            self._dispatch_sig(sig)

    def _device_put(self, args: tuple, kwargs: dict) -> Tuple[tuple, dict]:
        if self.config.device is None:
            return args, kwargs

        def _put(x: Any) -> Any:
            if isinstance(x, (jax.Array, np.ndarray)):
                return jax.device_put(x, self.config.device)
            return x

        return jax.tree_util.tree_map(_put, (args, kwargs))

    def _flush_pending(self, tenant: str) -> None:
        sig = self._pending.get(tenant)
        if sig is not None:
            self._dispatch_sig(sig)

    def flush(self) -> None:
        """Dispatch every open group (insertion order, padded to its bucket).

        Also runs the wall-clock re-admission sweep (time gate bypassed):
        deferred tenants back under quota drain here too.
        """
        self._maybe_readmit_deferred(force=True)
        for sig in list(self._groups):
            self._dispatch_sig(sig)

    def poll_admission(self) -> int:
        """Wall-clock re-admission sweep over every deferred tenant's backlog.

        An external ticker's hook (the pipeline's
        :meth:`~torchmetrics_tpu.engine.pipeline.MetricPipeline.poll_admission`
        analog): each deferred tenant is probed read-only
        (:meth:`~torchmetrics_tpu.obs.scope.AdmissionController.would_admit`)
        and, when back under quota, its backlog drains in order (billed).
        Returns the number of batches drained.
        """
        return self._maybe_readmit_deferred(force=True)

    def _maybe_readmit_deferred(self, force: bool = False, exclude: Optional[str] = None) -> int:
        """Drain deferred backlogs whose tenants are back under quota.

        Interval-gated by ``readmit_check_seconds`` unless ``force`` — the
        per-feed sweep must stay O(1) on the no-deferred hot path and cheap
        even with parked tenants. ``exclude`` skips one tenant (the per-feed
        sweep's caller, whose own backlog the admit() path drains).
        """
        if not self._deferred:
            return 0
        controller = self._admission()
        if controller is None:
            # the controller was uninstalled mid-stream: nothing meters these
            # tenants anymore, so their backlogs drain unconditionally
            deferred, self._deferred = self._deferred, {}
            drained = 0
            for tenant, backlog in deferred.items():
                for args, kwargs, trace_id in backlog:
                    self._report.deferred_replayed += 1
                    self._tenant_deferred_replayed[tenant] = (
                        self._tenant_deferred_replayed.get(tenant, 0) + 1
                    )
                    self._ingest(tenant, args, kwargs, trace_id=trace_id)
                    drained += 1
            return drained
        probe = getattr(controller, "would_admit", None)
        if not callable(probe):
            return 0
        now = time.monotonic()
        if not force and now - self._last_readmit_check < self.config.readmit_check_seconds:
            return 0
        self._last_readmit_check = now
        drained = 0
        # priority classes (TenantQuota.priority): recovered headroom reaches
        # the latency-sensitive tenants first — backlogs drain highest class
        # first, name-ordered within a class for determinism
        order = getattr(controller, "drain_order", None)
        tenants = order(list(self._deferred)) if callable(order) else list(self._deferred)
        for tenant in tenants:
            if tenant == exclude or not probe(tenant):
                continue
            backlog = self._deferred.pop(tenant, None) or []
            for args, kwargs, trace_id in backlog:
                self._report.deferred_replayed += 1
                self._tenant_deferred_replayed[tenant] = (
                    self._tenant_deferred_replayed.get(tenant, 0) + 1
                )
                controller.charge(tenant, updates=1)
                self._ingest(tenant, args, kwargs, trace_id=trace_id)
                drained += 1
            if _trace.ENABLED and backlog:
                _trace.event(
                    "engine.mux_readmitted", mux=self._label, tenant=tenant, batches=len(backlog)
                )
        return drained

    def flush_deferred(self) -> None:
        """Drain every tenant's deprioritized backlog (admission decisions
        bypassed — the work executes regardless — but executed updates are
        still billed, same as an in-stream drain). Highest priority class
        drains first (``TenantQuota.priority``): at close, too, the
        latency-sensitive tenants' held batches fold before batch tiers'."""
        controller = self._admission()
        deferred, self._deferred = self._deferred, {}
        order = getattr(controller, "drain_order", None)
        tenants = order(list(deferred)) if callable(order) else list(deferred)
        for tenant in tenants:
            backlog = deferred[tenant]
            for args, kwargs, trace_id in backlog:
                self._report.deferred_replayed += 1
                self._tenant_deferred_replayed[tenant] = (
                    self._tenant_deferred_replayed.get(tenant, 0) + 1
                )
                if controller is not None:
                    controller.charge(tenant, updates=1)
                self._ingest(tenant, args, kwargs, trace_id=trace_id)
        self.flush()

    def close(self) -> MuxReport:
        """Flush open groups AND the deferred backlog; end the tenant sessions."""
        try:
            self.flush()
            self.flush_deferred()
            # the slice streams end complete: a clean close leaves per-tenant
            # restore points covering every batch the mux ever folded (slices
            # the cadence already covered skip the duplicate write)
            if self._checkpointers and self._report.batches:
                self._maybe_checkpoint(force=True, skip_covered=True)
            self._evaluate_alerts([], force=True)
        finally:
            if not self._closed:
                self._closed = True
                registry = _scope.get_registry()
                for tenant in self._metrics:
                    registry.pipeline_finished(tenant)
                for tenant in self._checkpointers:
                    # the freshness promise ends with the sessions (see the
                    # pipeline close path)
                    _scope.note_checkpoint_closed(tenant)
                lease_rows = _scope.lease_status()
                for tenant in self._metrics:
                    # release only rows this mux's epoch still owns — a
                    # failed-over tenant's fresh lease must stay live
                    if lease_rows.get(tenant, {}).get("epoch") == self._lease["epoch"]:
                        _scope.note_lease_released(tenant)
                if _audit.ENABLED:
                    # freeze every tenant's final ledger rows for the merge
                    _audit.note_close(self)
        return self.report()

    def __enter__(self) -> "TenantMultiplexer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def compute(self, tenant: str) -> Any:
        """Flush the tenant's pending work, then compute its metric."""
        tenant = self._aliases.get(tenant, tenant)
        self._flush_pending(tenant)
        with _scope.session(tenant):
            return self._metrics[tenant].compute()

    # ---------------------------------------------------------------------- warmup

    def warmup(
        self, *args: Any, manifest_path: Optional[str] = None, **kwargs: Any
    ) -> Dict[str, Any]:
        """AOT-precompile every (tenant-width-bucket, signature) fused variant
        for one example batch (concrete arrays or ``jax.ShapeDtypeStruct``
        specs), plus the template's per-batch path (the replay fallback).

        Per-tenant replay programs for *other* tenants' instances are not
        pre-walked — they compile on first fault, and with the persistent
        compilation cache wired those compiles are disk reads of the
        template's program. Returns (and stores) the warmup manifest.
        """
        if self._template is None:
            raise RuntimeError("TenantMultiplexer.warmup needs at least one adopted tenant")
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        traced, template, unhashable = partition_static_leaves(leaves)
        if unhashable is not None:
            raise TypeError(
                f"TenantMultiplexer.warmup received an unhashable static argument of type"
                f" {type(unhashable).__name__}; such batches dispatch per-tenant eagerly"
                " and cannot be precompiled."
            )
        traced_specs = []
        for leaf in traced:
            if isinstance(leaf, jax.ShapeDtypeStruct):
                traced_specs.append(leaf)
            else:
                dtype = leaf.dtype if hasattr(leaf, "dtype") else np.asarray(leaf).dtype
                traced_specs.append(jax.ShapeDtypeStruct(np.shape(leaf), dtype))
        shapes = [list(map(int, s.shape)) for s in traced_specs]
        entries: List[Dict[str, Any]] = []
        if self._fusable:
            fused = self._get_fused_fn(treedef, tuple(template))
            state = self._template_state()
            abstract_state = jax.tree_util.tree_map(
                lambda leaf: jax.ShapeDtypeStruct(
                    np.shape(leaf), getattr(leaf, "dtype", np.asarray(leaf).dtype)
                ),
                state,
            )
            for width in self._buckets:
                states = tuple(abstract_state for _ in range(width))
                rows = tuple(tuple(traced_specs) for _ in range(width))
                valid = jax.ShapeDtypeStruct((width,), np.bool_)
                info = fused.warmup(states, rows, valid)
                if info.get("flops") is not None or info.get("bytes_accessed") is not None:
                    self._width_prices[width] = (info.get("flops"), info.get("bytes_accessed"))
                entries.append({**info, "kind": "mux", "width": width, "shapes": shapes})
        # the template's per-batch path: the replay/eager fallback program
        it = iter(traced_specs)
        abstract_full = [next(it) if isinstance(t, _ArraySlot) else t for t in template]
        a_args, a_kwargs = jax.tree_util.tree_unflatten(treedef, abstract_full)
        for m in self._per_batch_metrics(self._template):
            if not m._jit_enabled():
                continue
            if m._jitted_update is None:
                m._jitted_update = jit_with_static_leaves(m.pure_update)
            filtered = m._filter_kwargs(**a_kwargs) if self._is_collection else a_kwargs
            info = m._jitted_update.warmup(dict(m._state_values), *a_args, **filtered)
            entries.append({**info, "kind": "per_batch", "width": None, "shapes": shapes})
        manifest = _warmup.build_manifest(entries, cache_dir=_warmup.configured_cache_dir())
        self._warmup_manifest = manifest
        if _trace.ENABLED:
            _trace.event(
                "engine.mux_warmup",
                mux=self._label,
                variants=manifest["variants"],
                fresh=manifest["fresh_compiles"],
                seconds=manifest["total_compile_seconds"],
            )
        if manifest_path is not None:
            _warmup.save_manifest(manifest, manifest_path)
        return manifest

    # ------------------------------------------------------------------ fused path

    def _per_batch_metrics(self, target: Union[Metric, MetricCollection]) -> List[Metric]:
        """The metrics the per-tenant eager/replay path drives directly."""
        if not self._is_collection:
            return [target]
        return [target._modules[name] for name in self._fused_leaders if name is not None]

    def _template_state(self) -> Any:
        return self._fused_state(self._template)

    def _fused_state(self, target: Union[Metric, MetricCollection]) -> Any:
        if not self._is_collection:
            return dict(target._state_values)
        return {name: dict(target._modules[name]._state_values) for name in self._fused_leaders}

    def _get_fused_fn(self, treedef: Any, template: tuple) -> StaticLeafJit:
        key = (treedef, template)
        fused = self._fused_fns.get(key)
        if fused is not None:
            return fused
        target = self._template
        if self._is_collection:
            leaders = [(name, target._modules[name]) for name in self._fused_leaders]
        else:
            leaders = None

        def mux_update(states, rows, valid):
            # states: tuple of per-tenant state pytrees; rows: tuple of
            # per-row traced-leaf tuples. Stacking AND unstacking happen
            # INSIDE the compiled program — the host issues exactly one
            # dispatch per group instead of O(width × leaves) stack/slice ops
            # (on a CPU host those small ops dominate; on a TPU they would
            # serialize the dispatch stream this layer exists to collapse).
            stacked_state = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)
            stacked = tuple(
                jnp.stack([row[i] for row in rows]) for i in range(len(rows[0]))
            )

            def one(st, row_leaves, ok):
                it = iter(row_leaves)
                full = [next(it) if isinstance(t, _ArraySlot) else t for t in template]
                a, kw = jax.tree_util.tree_unflatten(treedef, full)
                if leaders is None:
                    new = target.pure_update(st, *a, **kw)
                else:
                    new = {
                        name: m.pure_update(st[name], *a, **m._filter_kwargs(**kw))
                        for name, m in leaders
                    }
                # masked tail: padded tenant rows pass their state through
                # unchanged, so a partial group padded up to its width bucket
                # stays bit-identical to the unpadded per-tenant run
                return jax.tree_util.tree_map(lambda n, o: jnp.where(ok, n, o), new, st)

            out = jax.vmap(one)(stacked_state, stacked, valid)
            return tuple(
                jax.tree_util.tree_map(lambda leaf: leaf[i], out) for i in range(len(states))
            )

        mux_update.__name__ = "mux_update"
        mux_update.__qualname__ = f"{self._label}.mux_update"
        fused = jit_with_static_leaves(mux_update)
        self._fused_fns[key] = fused
        return fused

    def _width_bucket(self, n: int) -> int:
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    def retune_width_buckets(self, buckets) -> Tuple[int, ...]:
        """Adopt a new width-bucket ladder (admission-driven tuning).

        The placement controller proposes ladders sized to the observed tenant
        population (``fleet.PlacementController.propose_width_buckets``); this
        is the mux-side commit. The proposal is validated through the same
        ``MuxConfig`` rules as a construction-time ladder — positive, deduped,
        ascending, top bucket clamped to ``max_width`` (so the ladder stays
        O(log W)) — and an invalid proposal raises without touching state.
        Groups already open keep the padded width they were admitted under;
        only future dispatch padding consults the new ladder. Compiled fused
        variants are cached per padded width, so a retune adds at most
        O(log W) new compilation entries and orphans none.
        """
        cfg = MuxConfig(max_width=self.config.max_width, width_buckets=tuple(buckets))
        resolved = cfg.buckets()
        # both the config (report/introspection surface) and the cached ladder
        # (dispatch hot path) must move together — __init__ caches buckets()
        self.config.width_buckets = resolved
        self._buckets = resolved
        return resolved

    def _row_policy(self, tenant: str):
        """The error policy guarding this tenant's row (any fused metric's,
        else the global default) — mirrors the pipeline's chunk policy."""
        for m in self._per_batch_metrics(self._metrics[tenant]):
            policy = effective_policy(m.error_policy)
            if policy is not None:
                return policy
        return None

    def _stack_probe(self, rows: list) -> list:
        # a named function, not an inline comprehension: the host-side numpy
        # probe stack is a "stack-unstack" seam the sampling profiler
        # (obs/hostprof.py) attributes by frame name — an anonymous
        # comprehension would fold these samples into the dispatch seam
        return [
            np.stack([np.asarray(row[1][i]) for row in rows])
            for i in range(len(rows[0][1]))
        ]

    def _dispatch_sig(self, sig: tuple) -> None:
        group = self._groups.pop(sig, None)
        if group is None or not len(group):
            return
        for tenant in group.tenants:
            self._pending.pop(tenant, None)
        rows = list(
            zip(group.tenants, group.traced, group.originals, group.records, group.trace_ids)
        )
        # one non-finite screen per GROUP (vs one host sync per tenant batch on
        # the guarded eager path); only guarded tenants' rows are screened —
        # an unguarded tenant's NaN must flow into ITS state like always
        guarded = {i for i, row in enumerate(rows) if self._row_policy(row[0]) is not None}
        if guarded:
            # host-side probe: the screen reads host values anyway (one sync
            # per group by design), so stack with numpy instead of burning a
            # device op per leaf; scalar leaves stack to shape (n,) and are
            # screened like any other, matching the pipeline's chunk screen
            stacked_probe = self._stack_probe(rows)
            bad = [i for i in nonfinite_step_indices(stacked_probe) if i in guarded]
            if bad:
                if _trace.ENABLED:
                    _trace.event(
                        "engine.mux_degraded",
                        mux=self._label,
                        reason="nonfinite",
                        tenants=",".join(rows[i][0] for i in bad),
                        width=len(rows),
                    )
                # the clean cohort lands FIRST (cross-tenant order inside a
                # group is free — rows fold independent states), then exactly
                # the poisoned tenants' batches replay through their OWN
                # guarded updates. Each replay is individually guarded: one
                # tenant's raise policy propagates AFTER every other tenant's
                # work — poisoned or clean — has landed, so a neighbor's
                # garbage never costs anyone else a batch.
                clean = [row for i, row in enumerate(rows) if i not in set(bad)]
                if clean:
                    self._dispatch_rows(group, clean)
                self._replay_rows([rows[i] for i in bad], reason="group_replay")
                return
        self._dispatch_rows(group, rows)

    def _tenant_robust_counts(self, tenant: str) -> Tuple[int, int]:
        """(quarantined, skipped) totals of one tenant's metrics — diffed
        around a replay/eager update to attribute the fault to its batch."""
        target = self._metrics[tenant]
        metrics = (
            list(target._modules.values()) if self._is_collection else [target]
        )
        quarantined = skipped = 0
        for m in metrics:
            quarantined += int(getattr(m, "updates_quarantined", 0) or 0)
            skipped += int(getattr(m, "updates_skipped", 0) or 0)
        return quarantined, skipped

    def _dump_flight(
        self,
        reason: str,
        tenant: str,
        poisoned: List[int],
        trace_ids: Optional[List[str]] = None,
    ) -> Optional[str]:
        """One fault dump naming ONE tenant's poisoned tenant-local batches.

        The mux ring is shared (the dump ships the full cross-tenant lineage
        as context), but attribution is per tenant: a group where two tenants'
        rows went bad produces two dumps, each naming exactly its owner's
        batches — the same (tenant, batch-index) evidence shape the per-tenant
        pipeline recorder produces, so the chaos SLO judge reads both alike.
        """
        if self._flight is None:
            return None
        config = {
            "max_width": self.config.max_width,
            "buckets": list(self._buckets),
            "tenants": len(self._metrics),
        }
        path = self._flight.dump(
            reason, poisoned, config, tenant=tenant, poisoned_trace_ids=trace_ids
        )
        if path is not None:
            self._report.flight_dumps += 1
            _lineage.note_dump(trace_ids or [], path)
            if _trace.ENABLED:
                _trace.inc("flight.dumps", pipeline=self._label)
                _trace.event(
                    "engine.mux_flight_dump",
                    mux=self._label,
                    tenant=tenant,
                    reason=reason,
                    path=path,
                    poisoned=",".join(map(str, sorted(set(poisoned)))),
                    trace_ids=",".join(sorted(set(trace_ids or []))),
                )
        return path

    def _replay_rows(self, rows: List[tuple], reason: str = "group_replay") -> None:
        """Guarded per-tenant replays; the first raising tenant's error
        propagates only after every row has been given its replay.

        Fault attribution mirrors the pipeline's: each replay is bracketed by
        the owning tenant's robust counters, the row's flight record is
        stamped, and every faulted tenant gets a dump naming exactly its
        tenant-local batch indices — written BEFORE a raise-policy error
        propagates, so the evidence always lands.
        """
        errors: List[BaseException] = []
        replayed: List[str] = []
        replayed_ids: List[str] = []
        poisoned_by_tenant: Dict[str, List[int]] = {}
        poisoned_ids_by_tenant: Dict[str, List[str]] = {}
        for row in rows:
            tenant, _, (r_args, r_kwargs) = row[0], row[1], row[2]
            record = row[3] if len(row) > 3 else None
            tid = row[4] if len(row) > 4 else None
            if tid is not None:
                replayed_ids.append(tid)
            before = self._tenant_robust_counts(tenant)
            try:
                with _lineage.trace(tid):
                    self._replay_row(tenant, r_args, r_kwargs)
            except BaseException as err:  # raise-policy tenants re-raise below
                errors.append(err)
                if record is not None:
                    record["path"] = "replay"
                    record["fault"] = "raised"
                    poisoned_by_tenant.setdefault(tenant, []).append(record["batch_index"])
                if tid is not None:
                    _lineage.get_index().update(tid, path="replay", outcome="raised")
                    poisoned_ids_by_tenant.setdefault(tenant, []).append(tid)
            else:
                fault = None
                quarantined, skipped = self._tenant_robust_counts(tenant)
                if quarantined > before[0]:
                    fault = "quarantined"
                elif skipped > before[1]:
                    fault = "skipped"
                if record is not None:
                    record["path"] = "replay"
                    record["fault"] = fault
                    if fault is not None:
                        poisoned_by_tenant.setdefault(tenant, []).append(record["batch_index"])
                if tid is not None:
                    _lineage.get_index().update(
                        tid, path="replay", outcome=fault if fault is not None else "ok"
                    )
                    if fault is not None:
                        poisoned_ids_by_tenant.setdefault(tenant, []).append(tid)
            replayed.append(tenant)
        for tenant in set(poisoned_by_tenant) | set(poisoned_ids_by_tenant):
            self._dump_flight(
                reason,
                tenant,
                poisoned_by_tenant.get(tenant, []),
                trace_ids=poisoned_ids_by_tenant.get(tenant),
            )
        self._maybe_checkpoint()
        self._evaluate_alerts(replayed, trace_ids=replayed_ids)
        if errors:
            raise errors[0]

    def _dispatch_rows(self, group: _MuxGroup, rows: List[tuple]) -> None:
        n = len(rows)
        width = self._width_bucket(n)
        pad = width - n
        padded = rows + [rows[-1]] * pad  # repeat-last padding, masked out
        traced_rows = tuple(tuple(row[1]) for row in padded)
        valid = np.arange(width) < n
        states = [self._fused_state(self._metrics[row[0]]) for row in rows]
        states += [states[-1]] * pad
        fused = self._get_fused_fn(group.treedef, group.template)
        controller = self._admission()
        ledger_mark = _cost.get_ledger().mark() if controller is not None else None
        gid = self._group_seq
        self._group_seq += 1
        row_ids = [row[4] for row in rows if len(row) > 4 and row[4] is not None]
        try:
            if _trace.ENABLED:
                span_attrs: Dict[str, Any] = {
                    "pipeline": self._label,
                    "path": "mux",
                    "width": n,
                }
                if row_ids:
                    # trace_id/trace_ids are excluded from histogram labels by
                    # the recorder; the ambient lineage context makes the
                    # dispatch histogram's exemplar reference the lead row
                    span_attrs["trace_id"] = row_ids[0]
                    span_attrs["trace_ids"] = ",".join(row_ids)
                with _lineage.trace(row_ids[0] if row_ids else None):
                    with _trace.span("engine.dispatch", **span_attrs):
                        new_states = fused(tuple(states), traced_rows, valid)
            else:
                new_states = fused(tuple(states), traced_rows, valid)
        except Exception as err:
            # state was never committed; every row replays through its own
            # (guarded or not) per-tenant update, isolating real failures —
            # one tenant's raising replay never robs the others of theirs
            if _trace.ENABLED:
                _trace.event(
                    "engine.mux_degraded",
                    mux=self._label,
                    reason=type(err).__name__,
                    width=n,
                )
            self._replay_rows(rows, reason="group_replay")
            return
        committed: List[str] = []
        for i, row in enumerate(rows):
            tenant = row[0]
            # new_states[i] is the tenant's state pytree, already split by the
            # compiled program — no per-leaf host slicing here
            with _scope.session(tenant):
                self._commit(self._metrics[tenant], new_states[i])
            self._tenant_folded[tenant] = self._tenant_folded.get(tenant, 0) + 1
            committed.append(tenant)
            record = row[3] if len(row) > 3 else None
            if record is not None:
                record["chunk_id"] = gid
                record["path"] = "mux"
            tid = row[4] if len(row) > 4 else None
            if _audit.ENABLED:
                _audit.note_fold(self, "mux", tenant, self._lineage_epoch, tid)
            if tid is not None:
                _lineage.get_index().update(tid, chunk_id=gid, path="mux", outcome="ok")
        self._report.dispatches += 1
        self._report.fused_updates += n
        self._report.padded_rows += pad
        self._report.max_width = max(self._report.max_width, n)
        self._report.last_width = n
        if _trace.ENABLED:
            _trace.inc("engine.mux_dispatches", mux=self._label)
            _trace.inc("engine.mux_fused_updates", n, mux=self._label)
            if pad:
                _trace.inc("engine.mux_padded_rows", pad, mux=self._label)
            _trace.set_gauge("engine.mux_width", n, mux=self._label)
            _trace.set_gauge("engine.mux_open_groups", len(self._groups), mux=self._label)
        if controller is not None:
            self._charge_rows(controller, committed, width, ledger_mark)
        self._maybe_checkpoint()
        self._evaluate_alerts(committed, trace_ids=row_ids)

    def _commit(self, target: Union[Metric, MetricCollection], state: Any) -> None:
        if self._is_collection:
            target._engine_commit({name: state[name] for name in self._fused_leaders}, 1)
        else:
            target._engine_commit_state(state, 1)
        for m in self._per_batch_metrics(target):
            m._check_buffer_overflow()

    def _charge_rows(
        self, controller: Any, tenants: List[str], width: int, ledger_mark: Optional[int]
    ) -> None:
        """Bill the dispatch back per tenant: the executed width bucket's
        per-dispatch estimate split across its rows (each row is one tenant's
        share), plus fresh compile seconds split across the rows that forced
        them (shared executables, shared bill)."""
        try:
            ledger = _cost.get_ledger()
            compile_delta = ledger.since(ledger_mark) if ledger_mark is not None else {}
            fresh = compile_delta.get("variants_compiled", 0)
            if fresh and width not in self._width_prices:
                # the first dispatch at this width compiled exactly this
                # width's program: the delta's estimate IS its price (a
                # genuine 0.0 is a valid price — it must not read as missing,
                # or this width would pay the fallback scan forever)
                self._width_prices[width] = (
                    compile_delta.get("estimated_flops"),
                    compile_delta.get("estimated_bytes"),
                )
            if width in self._width_prices:
                flops, bytes_accessed = self._width_prices[width]
            else:
                # unwarmed width on a cached program (e.g. persistent compile
                # cache hit): fall back to the cross-width ledger mean —
                # approximate, but only until this width is priced
                price = ledger.fn_estimate(f"{self._label}.mux_update")
                flops = price.get("flops_per_dispatch")
                bytes_accessed = price.get("bytes_per_dispatch")
            per_row_flops = (flops or 0.0) / max(1, width)
            per_row_bytes = (bytes_accessed or 0.0) / max(1, width)
            compile_share = (
                float(compile_delta.get("compile_seconds", 0.0)) / len(tenants) if tenants else 0.0
            )
            for tenant in tenants:
                controller.charge(
                    tenant,
                    flops=per_row_flops,
                    bytes_accessed=per_row_bytes,
                    compile_seconds=compile_share,
                )
        except Exception:  # pricing must never cost correctness
            pass

    # ------------------------------------------------------------- per-tenant paths

    def _mark_eager_fault(
        self,
        tenant: str,
        record: Optional[dict],
        before: Tuple[int, int],
        trace_id: Optional[str] = None,
    ) -> None:
        """Stamp an eager-path record with its fault; quarantines dump directly
        (no replay step exists to do it — the pipeline's eager-path rule)."""
        if record is None and trace_id is None:
            return
        quarantined, skipped = self._tenant_robust_counts(tenant)
        fault = None
        if quarantined > before[0]:
            fault = "quarantined"
        elif skipped > before[1]:
            fault = "skipped"
        if record is not None:
            record["path"] = "eager"
            record["fault"] = fault
        if trace_id is not None:
            _lineage.get_index().update(
                trace_id, path="eager", outcome=fault if fault is not None else "ok"
            )
        if fault == "quarantined":
            self._dump_flight(
                "quarantine",
                tenant,
                [record["batch_index"]] if record is not None else [],
                trace_ids=[trace_id] if trace_id is not None else None,
            )

    def _drive_eager(
        self,
        tenant: str,
        args: tuple,
        kwargs: dict,
        record: Optional[dict] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        """Whole-target per-tenant update (target unfusable)."""
        target = self._metrics[tenant]
        attributed = record is not None or trace_id is not None
        before = self._tenant_robust_counts(tenant) if attributed else (0, 0)
        with _scope.session(tenant):
            with _lineage.trace(trace_id):
                if _trace.ENABLED:
                    span_attrs: Dict[str, Any] = {"pipeline": self._label, "path": "eager"}
                    if trace_id is not None:
                        span_attrs["trace_id"] = trace_id
                    with _trace.span("engine.dispatch", **span_attrs):
                        target.update(*args, **kwargs)
                else:
                    target.update(*args, **kwargs)
        self._tenant_folded[tenant] = self._tenant_folded.get(tenant, 0) + 1
        self._report.eager_updates += 1
        self._report.eager_dispatches += 1
        if _audit.ENABLED:
            _audit.note_fold(self, "mux", tenant, self._lineage_epoch, trace_id)
        if _trace.ENABLED:
            _trace.inc("engine.mux_eager_updates", mux=self._label)
        self._mark_eager_fault(tenant, record, before, trace_id)
        self._maybe_checkpoint()
        self._evaluate_alerts(
            [tenant], trace_ids=[trace_id] if trace_id is not None else ()
        )

    def _drive_eager_leaders(self, tenant: str, args: tuple, kwargs: dict) -> None:
        target = self._metrics[tenant]
        with _scope.session(tenant):
            for name in self._eager_leaders:
                m = target._modules[name]
                m.update(*args, **m._filter_kwargs(**kwargs))
        self._report.eager_dispatches += len(self._eager_leaders)

    def _drive_fused_leaders_eagerly(
        self,
        tenant: str,
        args: tuple,
        kwargs: dict,
        record: Optional[dict] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        """Per-tenant fallback for a batch that cannot join a group."""
        target = self._metrics[tenant]
        attributed = record is not None or trace_id is not None
        before = self._tenant_robust_counts(tenant) if attributed else (0, 0)
        with _scope.session(tenant):
            with _lineage.trace(trace_id):
                for m in self._per_batch_metrics(target):
                    filtered = m._filter_kwargs(**kwargs) if self._is_collection else kwargs
                    m.update(*args, **filtered)
                if self._is_collection:
                    target._sync_group_states()
        self._tenant_folded[tenant] = self._tenant_folded.get(tenant, 0) + 1
        self._report.eager_updates += 1
        self._report.eager_dispatches += max(1, len(self._per_batch_metrics(target)))
        if _audit.ENABLED:
            _audit.note_fold(self, "mux", tenant, self._lineage_epoch, trace_id)
        self._mark_eager_fault(tenant, record, before, trace_id)
        self._maybe_checkpoint()
        self._evaluate_alerts(
            [tenant], trace_ids=[trace_id] if trace_id is not None else ()
        )

    def _replay_row(self, tenant: str, args: tuple, kwargs: dict) -> None:
        """Guarded per-tenant replay of a poisoned/failed row: the tenant's own
        error policy decides (skip/quarantine/raise) — its cohort never sees it."""
        target = self._metrics[tenant]
        with _scope.session(tenant):
            if _trace.ENABLED:
                span_attrs: Dict[str, Any] = {"pipeline": self._label, "path": "replay"}
                trace_id = _lineage.current_trace()  # set by _replay_rows
                if trace_id is not None:
                    span_attrs["trace_id"] = trace_id
                with _trace.span("engine.dispatch", **span_attrs):
                    self._replay_updates(target, args, kwargs)
            else:
                self._replay_updates(target, args, kwargs)
        self._tenant_folded[tenant] = self._tenant_folded.get(tenant, 0) + 1
        self._report.replayed_updates += 1
        self._report.eager_dispatches += max(1, len(self._per_batch_metrics(target)))
        if _audit.ENABLED:
            # the ambient trace context is set by _replay_rows around this call
            _audit.note_fold(
                self, "mux", tenant, self._lineage_epoch, _lineage.current_trace()
            )
        if _trace.ENABLED:
            _trace.inc("engine.mux_replayed_updates", mux=self._label, tenant=tenant)

    def _replay_updates(self, target: Any, args: tuple, kwargs: dict) -> None:
        for m in self._per_batch_metrics(target):
            filtered = m._filter_kwargs(**kwargs) if self._is_collection else kwargs
            m.update(*args, **filtered)
        if self._is_collection:
            target._sync_group_states()

    # ------------------------------------------------------------------ alert seam

    def _evaluate_alerts(
        self, tenants: Iterable[str], force: bool = False, trace_ids: Iterable[str] = ()
    ) -> None:
        """Per-committed-group value-health evaluation (``config.alert_engine``):
        sample each committed tenant's values sync-free under its session, then
        run the rules. A broken engine warns once and the stream keeps flowing."""
        engine = self.config.alert_engine
        if engine is None:
            return
        self._alert_commits += 1
        if not force and self._alert_commits % self.config.alert_every:
            return
        try:
            log_hook = getattr(engine, "_log", None)
            log = log_hook() if callable(log_hook) else None
            for tenant in tenants:
                with _scope.session(tenant):
                    _values.sample_local(self._metrics[tenant], log=log)
            transitions = engine.evaluate()
            fired_rules = sorted(
                {
                    t["rule"]
                    for t in transitions
                    if t["to"] == "firing" and t.get("source") == "values"
                }
            )
            if fired_rules:
                # link newly-fired value watchdogs back to the rows whose
                # commit triggered this evaluation (the lineage alert join)
                _lineage.note_alert(list(trace_ids), fired_rules)
        except Exception as err:
            if not self._alert_warned:
                self._alert_warned = True
                rank_zero_warn(
                    f"Alert evaluation failed on the {self._label} multiplexer"
                    f" ({type(err).__name__}: {err}). The stream keeps flowing; further"
                    " failures are silent (this warning fires once) and value watchdogs"
                    " may be stale.",
                    RuntimeWarning,
                )
