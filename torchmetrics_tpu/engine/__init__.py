"""Streaming evaluation engine: pipelined updates, fused scan chunks, AOT warmup.

The execution layer between user batch streams and the ``Metric`` /
``MetricCollection`` machinery:

- :class:`~torchmetrics_tpu.engine.pipeline.MetricPipeline` — consumes a batch
  iterator with host→device **prefetch**, **bounded async dispatch** (never
  ``block_until_ready`` per step), and **micro-batch fusion**: N same-signature
  batches advance the state with one ``lax.scan`` dispatch, chunk lengths padded
  to a small set of buckets with masked tails so the compiled-variant count
  stays bounded. Robust error policies still apply per fused chunk, with
  degrade-to-per-batch replay isolating poisoned batches.
- :class:`~torchmetrics_tpu.engine.mux.TenantMultiplexer` — **cross-tenant
  fused dispatch**: same-signature updates from *different* tenants stacked on
  a leading tenant axis and folded into per-tenant state with one ``vmap``
  dispatch, tenant-width power-of-two buckets keeping the compiled-program
  count ``O(buckets × signatures)`` instead of ``O(tenants × signatures)``,
  per-tenant robust isolation, and cost-aware admission
  (:class:`~torchmetrics_tpu.obs.scope.AdmissionController`) on top.
- :mod:`~torchmetrics_tpu.engine.warmup` — AOT precompilation of every
  (metric, shape-bucket, static-config) variant before the loop, JAX
  **persistent compilation cache** wiring (``TM_TPU_COMPILE_CACHE``), and the
  warmup manifest recording what startup compiled.
- :mod:`~torchmetrics_tpu.engine.migrate` — **live-session checkpoint/restore
  and continuous crash-consistent checkpointing**: a running pipeline session
  (state + replay tail + flight ring + report + registry row + value
  timelines + alert machines) as an atomic, integrity-checked bundle;
  drain→checkpoint→restore→replay-tail with bit-identical restores and
  degraded-not-dead ``/healthz`` while in flight. A
  :class:`~torchmetrics_tpu.engine.migrate.CheckpointPolicy` on
  ``PipelineConfig.checkpoint`` / ``MuxConfig.checkpoint`` writes periodic
  **delta bundles** at chunk-commit boundaries (no drain) with chain-aware
  verification, compaction and retention; after an unplanned death,
  :func:`~torchmetrics_tpu.engine.migrate.latest_valid_bundle` +
  :func:`~torchmetrics_tpu.engine.migrate.restore_session` recover the
  session with a replay gap bounded by the cadence.

Quick start::

    from torchmetrics_tpu.engine import MetricPipeline, PipelineConfig

    pipe = MetricPipeline(metric, PipelineConfig(fuse=8, prefetch=2))
    pipe.warmup(example_preds, example_target)       # AOT + persistent cache
    pipe.run((p, t) for p, t in eval_loader)         # fused, prefetched
    value = metric.compute()
"""

from torchmetrics_tpu.engine.migrate import (
    SESSION_SCHEMA,
    CheckpointPolicy,
    SessionBundleError,
    checkpoint_session,
    checkpoint_staleness_rule,
    compact_chain,
    latest_valid_bundle,
    restore_session,
    sweep_bundles,
    verify_bundle,
)
from torchmetrics_tpu.engine.mux import MuxConfig, MuxReport, TenantMultiplexer
from torchmetrics_tpu.engine.pipeline import (
    FLIGHT_DIR_ENV,
    MetricPipeline,
    PipelineConfig,
    PipelineReport,
)
from torchmetrics_tpu.engine.warmup import (
    CACHE_ENV_VAR,
    build_manifest,
    configure_compile_cache,
    configured_cache_dir,
    load_manifest,
    persistent_cache_stats,
    pow2_buckets,
    save_manifest,
)

__all__ = [
    "CACHE_ENV_VAR",
    "FLIGHT_DIR_ENV",
    "SESSION_SCHEMA",
    "CheckpointPolicy",
    "MetricPipeline",
    "MuxConfig",
    "MuxReport",
    "PipelineConfig",
    "PipelineReport",
    "SessionBundleError",
    "TenantMultiplexer",
    "build_manifest",
    "checkpoint_session",
    "checkpoint_staleness_rule",
    "compact_chain",
    "configure_compile_cache",
    "configured_cache_dir",
    "latest_valid_bundle",
    "load_manifest",
    "persistent_cache_stats",
    "pow2_buckets",
    "restore_session",
    "save_manifest",
    "sweep_bundles",
    "verify_bundle",
]
