"""The retrieval metric base: group-by-query segment engine.

Parity: reference ``src/torchmetrics/retrieval/base.py`` (aggregation ``:24-41``,
``RetrievalMetric`` ``:44-207``).

Design: ``indexes/preds/target`` accumulate as "cat" list states; ``compute`` sorts by
query id on host (group sizes are data-dependent) and maps the per-query functional over
the segments, exactly the reference's epoch-end evaluation model. With a
``buffer_capacity`` the same states become static-shape ``MaskedBuffer`` states:
updates run inside jit/``shard_map`` (validation falls back to a trace-safe masked
path) and cross-shard sync is one ``all_gather`` + compaction.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


def _retrieval_aggregate(values: Array, aggregation: Union[str, Callable] = "mean", dim: Optional[int] = None) -> Array:
    """Aggregate per-query scores: mean/median/min/max or a custom callable."""
    if aggregation == "mean":
        return values.mean() if dim is None else values.mean(axis=dim)
    if aggregation == "median":
        # torch.median semantics: the lower of the two middle elements
        if dim is None:
            flat = jnp.sort(values.ravel())
            return flat[(flat.shape[0] - 1) // 2]
        sorted_vals = jnp.sort(values, axis=dim)
        return jnp.take(sorted_vals, (values.shape[dim] - 1) // 2, axis=dim)
    if aggregation == "min":
        return values.min() if dim is None else values.min(axis=dim)
    if aggregation == "max":
        return values.max() if dim is None else values.max(axis=dim)
    return aggregation(values, dim=dim)


def _check_retrieval_inputs(
    indexes: Array,
    preds: Array,
    target: Array,
    allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Validate and flatten an (indexes, preds, target) triple.

    Returns ``(indexes, preds, target, valid)``. Eagerly, ignore_index entries are
    dropped and ``valid`` is all-True; under tracing nothing can be dropped, so the
    value checks are skipped and ``valid`` marks the kept entries instead."""
    indexes = jnp.asarray(indexes)
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")

    indexes = indexes.ravel()
    preds = preds.ravel()
    target = target.ravel()

    if isinstance(target, jax.core.Tracer) or isinstance(preds, jax.core.Tracer):
        # trace-safe path (buffered updates inside jit/shard_map): value checks need
        # concrete data and dropping needs dynamic shapes — keep an explicit mask
        valid = (
            jnp.ones_like(target, dtype=jnp.bool_)
            if ignore_index is None
            else target != ignore_index
        )
        tgt = target.astype(jnp.float32) if jnp.issubdtype(target.dtype, jnp.floating) else target.astype(jnp.int32)
        return indexes.astype(jnp.int32), preds.astype(jnp.float32), jnp.where(valid, tgt, 0), valid

    if ignore_index is not None:
        valid = np.asarray(target != ignore_index)
        indexes = jnp.asarray(np.asarray(indexes)[valid])
        preds = jnp.asarray(np.asarray(preds)[valid])
        target = jnp.asarray(np.asarray(target)[valid])

    if indexes.size == 0:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty and non-scalar tensors")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    if not allow_non_binary_target:
        if jnp.issubdtype(target.dtype, jnp.floating):
            raise ValueError("`target` must be a tensor of booleans or integers")
        if int(target.max()) > 1 or int(target.min()) < 0:
            raise ValueError("`target` must contain `binary` values")

    target = target.astype(jnp.float32) if jnp.issubdtype(target.dtype, jnp.floating) else target.astype(jnp.int32)
    return (
        indexes.astype(jnp.int32),
        preds.astype(jnp.float32),
        target,
        jnp.ones_like(target, dtype=jnp.bool_),
    )


def _group_by_query(indexes, preds, target) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Host-side group-by-query over flattened triples (dynamic group sizes)."""
    indexes = np.asarray(indexes)
    preds = np.asarray(preds)
    target = np.asarray(target)
    order = np.argsort(indexes, kind="stable")
    indexes, preds, target = indexes[order], preds[order], target[order]
    boundaries = np.flatnonzero(np.diff(indexes)) + 1
    return list(zip(np.split(preds, boundaries), np.split(target, boundaries)))


class RetrievalMetric(Metric, ABC):
    """Base for query-grouped retrieval metrics (binary targets).

    Subclasses implement ``_metric(preds, target)`` over one query's documents.
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    allow_non_binary_target: bool = False

    indexes: List[Array]
    preds: List[Array]
    target: List[Array]

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation: Union[str, Callable] = "mean",
        buffer_capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index

        if not (aggregation in ("mean", "median", "min", "max") or callable(aggregation)):
            raise ValueError(
                "Argument `aggregation` must be one of `mean`, `median`, `min`, `max` or a custom callable function"
                f"which takes tensor of values, but got {aggregation}."
            )
        self.aggregation = aggregation

        # "cat": list states must gather-concat across processes during sync (the
        # upstream's dist_reduce_fx=None also gathers; this repo's None is identity).
        # With a buffer_capacity the same states become static-shape MaskedBuffers:
        # updates run under jit/shard_map and sync is one all_gather + compaction.
        self.buffer_capacity = buffer_capacity
        if buffer_capacity is not None:
            from torchmetrics_tpu.core.buffer import MaskedBuffer

            # graded-relevance metrics (allow_non_binary_target) carry float targets
            target_dtype = jnp.float32 if self.allow_non_binary_target else jnp.int32
            self.add_state("indexes", MaskedBuffer.create(buffer_capacity, dtype=jnp.int32), dist_reduce_fx="cat")
            self.add_state("preds", MaskedBuffer.create(buffer_capacity), dist_reduce_fx="cat")
            self.add_state("target", MaskedBuffer.create(buffer_capacity, dtype=target_dtype), dist_reduce_fx="cat")
            self.add_state("valid", MaskedBuffer.create(buffer_capacity, dtype=jnp.bool_), dist_reduce_fx="cat")
            if self._jit_update_flag is None:
                # validation is host-side; keep the public path eager (exact
                # drop-filtering) — mesh users drive pure_update inside shard_map
                self._jit_update_flag = False
        else:
            self.add_state("indexes", [], dist_reduce_fx="cat")
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        """Validate, flatten and store the batch triple."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target, valid = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        if self.buffer_capacity is not None:
            self.indexes = self.indexes.append(indexes)
            self.preds = self.preds.append(preds)
            self.target = self.target.append(target)
            self.valid = self.valid.append(valid)
        else:
            if isinstance(valid, jax.core.Tracer):
                raise ValueError(
                    "List-state retrieval metrics cannot update under jit (dynamic-size"
                    " appends). Construct the metric with `buffer_capacity` instead."
                )
            self.indexes.append(indexes)
            self.preds.append(preds)
            self.target.append(target)

    def _group_segments(self) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Group accumulated state by query id: list of (preds, target) per query.

        Groups stay as host numpy — per-query documents are tiny, so per-group device
        dispatch would dominate; the per-query functionals accept numpy directly."""
        if self.buffer_capacity is not None:
            keep = np.asarray(self.valid.values()).astype(bool)
            return _group_by_query(
                np.asarray(self.indexes.values())[keep],
                np.asarray(self.preds.values())[keep],
                np.asarray(self.target.values())[keep],
            )
        return _group_by_query(
            dim_zero_cat(self.indexes), dim_zero_cat(self.preds), dim_zero_cat(self.target)
        )

    def _empty_query_check(self, target) -> bool:
        """True when the query lacks the targets this metric needs (positives)."""
        return not float(np.sum(target))

    def compute(self) -> Array:
        """Group by query, score each group, aggregate."""
        res = []
        for mini_preds, mini_target in self._group_segments():
            if self._empty_query_check(mini_target):
                if self.empty_target_action == "error":
                    raise ValueError("`compute` method was provided with a query with no positive target.")
                if self.empty_target_action == "pos":
                    res.append(jnp.asarray(1.0))
                elif self.empty_target_action == "neg":
                    res.append(jnp.asarray(0.0))
            else:
                res.append(self._metric(mini_preds, mini_target))

        if res:
            return _retrieval_aggregate(jnp.stack([jnp.asarray(x, dtype=jnp.float32) for x in res]), self.aggregation)
        return jnp.asarray(0.0)

    @abstractmethod
    def _metric(self, preds: Array, target: Array) -> Array:
        """Score one query's documents."""
