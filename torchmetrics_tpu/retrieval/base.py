"""The retrieval metric base: group-by-query segment engine.

Parity: reference ``src/torchmetrics/retrieval/base.py`` (aggregation ``:24-41``,
``RetrievalMetric`` ``:44-207``).

Design: ``indexes/preds/target`` accumulate as "cat" list states; ``compute`` sorts by
query id on host (group sizes are data-dependent) and maps the per-query functional over
the segments, exactly the reference's epoch-end evaluation model.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


def _retrieval_aggregate(values: Array, aggregation: Union[str, Callable] = "mean", dim: Optional[int] = None) -> Array:
    """Aggregate per-query scores: mean/median/min/max or a custom callable."""
    if aggregation == "mean":
        return values.mean() if dim is None else values.mean(axis=dim)
    if aggregation == "median":
        # torch.median semantics: the lower of the two middle elements
        if dim is None:
            flat = jnp.sort(values.ravel())
            return flat[(flat.shape[0] - 1) // 2]
        sorted_vals = jnp.sort(values, axis=dim)
        return jnp.take(sorted_vals, (values.shape[dim] - 1) // 2, axis=dim)
    if aggregation == "min":
        return values.min() if dim is None else values.min(axis=dim)
    if aggregation == "max":
        return values.max() if dim is None else values.max(axis=dim)
    return aggregation(values, dim=dim)


def _check_retrieval_inputs(
    indexes: Array,
    preds: Array,
    target: Array,
    allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """Validate and flatten an (indexes, preds, target) triple."""
    indexes = jnp.asarray(indexes)
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")

    indexes = indexes.ravel()
    preds = preds.ravel()
    target = target.ravel()

    if ignore_index is not None:
        valid = np.asarray(target != ignore_index)
        indexes = jnp.asarray(np.asarray(indexes)[valid])
        preds = jnp.asarray(np.asarray(preds)[valid])
        target = jnp.asarray(np.asarray(target)[valid])

    if indexes.size == 0:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty and non-scalar tensors")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    if not allow_non_binary_target:
        if jnp.issubdtype(target.dtype, jnp.floating):
            raise ValueError("`target` must be a tensor of booleans or integers")
        if int(target.max()) > 1 or int(target.min()) < 0:
            raise ValueError("`target` must contain `binary` values")

    target = target.astype(jnp.float32) if jnp.issubdtype(target.dtype, jnp.floating) else target.astype(jnp.int32)
    return indexes.astype(jnp.int32), preds.astype(jnp.float32), target


def _group_by_query(indexes, preds, target) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Host-side group-by-query over flattened triples (dynamic group sizes)."""
    indexes = np.asarray(indexes)
    preds = np.asarray(preds)
    target = np.asarray(target)
    order = np.argsort(indexes, kind="stable")
    indexes, preds, target = indexes[order], preds[order], target[order]
    boundaries = np.flatnonzero(np.diff(indexes)) + 1
    return list(zip(np.split(preds, boundaries), np.split(target, boundaries)))


class RetrievalMetric(Metric, ABC):
    """Base for query-grouped retrieval metrics (binary targets).

    Subclasses implement ``_metric(preds, target)`` over one query's documents.
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False

    indexes: List[Array]
    preds: List[Array]
    target: List[Array]

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation: Union[str, Callable] = "mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False

        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index

        if not (aggregation in ("mean", "median", "min", "max") or callable(aggregation)):
            raise ValueError(
                "Argument `aggregation` must be one of `mean`, `median`, `min`, `max` or a custom callable function"
                f"which takes tensor of values, but got {aggregation}."
            )
        self.aggregation = aggregation

        # "cat": list states must gather-concat across processes during sync (the
        # upstream's dist_reduce_fx=None also gathers; this repo's None is identity)
        self.add_state("indexes", [], dist_reduce_fx="cat")
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        """Validate, flatten and store the batch triple."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def _group_segments(self) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Group accumulated state by query id: list of (preds, target) per query.

        Groups stay as host numpy — per-query documents are tiny, so per-group device
        dispatch would dominate; the per-query functionals accept numpy directly."""
        return _group_by_query(
            dim_zero_cat(self.indexes), dim_zero_cat(self.preds), dim_zero_cat(self.target)
        )

    def _empty_query_check(self, target) -> bool:
        """True when the query lacks the targets this metric needs (positives)."""
        return not float(np.sum(target))

    def compute(self) -> Array:
        """Group by query, score each group, aggregate."""
        res = []
        for mini_preds, mini_target in self._group_segments():
            if self._empty_query_check(mini_target):
                if self.empty_target_action == "error":
                    raise ValueError("`compute` method was provided with a query with no positive target.")
                if self.empty_target_action == "pos":
                    res.append(jnp.asarray(1.0))
                elif self.empty_target_action == "neg":
                    res.append(jnp.asarray(0.0))
            else:
                res.append(self._metric(mini_preds, mini_target))

        if res:
            return _retrieval_aggregate(jnp.stack([jnp.asarray(x, dtype=jnp.float32) for x in res]), self.aggregation)
        return jnp.asarray(0.0)

    @abstractmethod
    def _metric(self, preds: Array, target: Array) -> Array:
        """Score one query's documents."""
