"""Retrieval metric modules.

Parity: reference ``src/torchmetrics/retrieval/{average_precision,precision,recall,
hit_rate,fall_out,reciprocal_rank,r_precision,auroc,ndcg,precision_recall_curve}.py``.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.functional.retrieval.metrics import (
    retrieval_auroc,
    retrieval_average_precision,
    retrieval_fall_out,
    retrieval_hit_rate,
    retrieval_normalized_dcg,
    retrieval_precision,
    retrieval_precision_recall_curve,
    retrieval_r_precision,
    retrieval_recall,
    retrieval_reciprocal_rank,
)
from torchmetrics_tpu.retrieval.base import RetrievalMetric, _check_retrieval_inputs

Array = jax.Array


def _validate_top_k(top_k: Optional[int]) -> None:
    if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")


class RetrievalMAP(RetrievalMetric):
    r"""Mean average precision over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalMAP
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> rmap = RetrievalMAP()
        >>> rmap(preds, target, indexes=indexes).round(4)
        Array(0.7917, dtype=float32)
    """

    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_average_precision(preds, target, top_k=self.top_k)


class RetrievalPrecision(RetrievalMetric):
    r"""Mean precision@k over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalPrecision
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> p2 = RetrievalPrecision(top_k=2)
        >>> p2(preds, target, indexes=indexes)
        Array(0.5, dtype=float32)
    """

    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, top_k: Optional[int] = None, adaptive_k: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.top_k = top_k
        self.adaptive_k = adaptive_k

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_precision(preds, target, top_k=self.top_k, adaptive_k=self.adaptive_k)


class RetrievalRecall(RetrievalMetric):
    r"""Mean recall@k over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalRecall
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> r2 = RetrievalRecall(top_k=2)
        >>> r2(preds, target, indexes=indexes).round(4)
        Array(0.75, dtype=float32)
    """

    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_recall(preds, target, top_k=self.top_k)


class RetrievalHitRate(RetrievalMetric):
    r"""Mean hit-rate@k over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalHitRate
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([True, False, False, False, True, False, True])
        >>> hr2 = RetrievalHitRate(top_k=2)
        >>> hr2(preds, target, indexes=indexes)
        Array(0.5, dtype=float32)
    """

    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_hit_rate(preds, target, top_k=self.top_k)


class RetrievalFallOut(RetrievalMetric):
    r"""Mean fall-out@k over queries (empty-target queries are those with no negatives).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalFallOut
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> fo2 = RetrievalFallOut(top_k=2)
        >>> fo2(preds, target, indexes=indexes).round(4)
        Array(0.5, dtype=float32)
    """

    higher_is_better = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _empty_query_check(self, target) -> bool:
        """Fall-out needs at least one negative target."""
        return not float(np.sum(1 - np.asarray(target)))

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_fall_out(preds, target, top_k=self.top_k)


class RetrievalMRR(RetrievalMetric):
    r"""Mean reciprocal rank over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalMRR
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> mrr = RetrievalMRR()
        >>> mrr(preds, target, indexes=indexes).round(4)
        Array(0.75, dtype=float32)
    """

    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_reciprocal_rank(preds, target, top_k=self.top_k)


class RetrievalRPrecision(RetrievalMetric):
    r"""Mean R-precision over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalRPrecision
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> rp = RetrievalRPrecision()
        >>> rp(preds, target, indexes=indexes).round(4)
        Array(0.75, dtype=float32)
    """

    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_r_precision(preds, target)


class RetrievalAUROC(RetrievalMetric):
    r"""Mean AUROC over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalAUROC
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> auroc = RetrievalAUROC()
        >>> auroc(preds, target, indexes=indexes)
        Array(0.75, dtype=float32)
    """

    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, top_k: Optional[int] = None, max_fpr: Optional[float] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        if max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
            raise ValueError(f"Argument `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")
        self.top_k = top_k
        self.max_fpr = max_fpr

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_auroc(preds, target, top_k=self.top_k, max_fpr=self.max_fpr)


class RetrievalNormalizedDCG(RetrievalMetric):
    r"""Mean normalized DCG over queries (graded relevance supported).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalNormalizedDCG
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> ndcg = RetrievalNormalizedDCG()
        >>> ndcg(preds, target, indexes=indexes).round(4)
        Array(0.84669995, dtype=float32)
    """

    allow_non_binary_target: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        _validate_top_k(top_k)
        super().__init__(**kwargs)
        self.top_k = top_k

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_normalized_dcg(preds, target, top_k=self.top_k)


class RetrievalPrecisionRecallCurve(Metric):
    r"""Averaged precision/recall@k curves over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalPrecisionRecallCurve
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> curve = RetrievalPrecisionRecallCurve(max_k=2)
        >>> precisions, recalls, top_k = curve(preds, target, indexes=indexes)
        >>> top_k.tolist()
        [1, 2]
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    indexes: List[Array]
    preds: List[Array]
    target: List[Array]

    def __init__(
        self,
        max_k: Optional[int] = None,
        adaptive_k: bool = False,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation: Union[str, Callable] = "mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if max_k is not None and not (isinstance(max_k, int) and max_k > 0):
            raise ValueError("`max_k` has to be a positive integer or None")
        self.max_k = max_k
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.adaptive_k = adaptive_k
        if not (aggregation in ("mean", "median", "min", "max") or callable(aggregation)):
            raise ValueError(
                "Argument `aggregation` must be one of `mean`, `median`, `min`, `max` or a custom callable function"
                f"which takes tensor of values, but got {aggregation}."
            )
        self.aggregation = aggregation

        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index

        self.add_state("indexes", [], dist_reduce_fx="cat")
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        """Validate, flatten and store the batch triple."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target, valid = _check_retrieval_inputs(
            indexes, preds, target, ignore_index=self.ignore_index
        )
        if isinstance(valid, jax.core.Tracer):
            raise ValueError(
                "RetrievalPrecisionRecallCurve cannot update under jit (dynamic-size appends)."
            )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Tuple[Array, Array, Array]:
        """Mean precision/recall@k over all queries."""
        from torchmetrics_tpu.retrieval.base import _group_by_query
        from torchmetrics_tpu.utils.data import dim_zero_cat

        groups = _group_by_query(
            dim_zero_cat(self.indexes), dim_zero_cat(self.preds), dim_zero_cat(self.target)
        )

        max_k = self.max_k or max(len(p) for p, _ in groups)

        precisions, recalls = [], []
        for mini_preds, mini_target in groups:
            if not mini_target.sum():
                if self.empty_target_action == "error":
                    raise ValueError("`compute` method was provided with a query with no positive target.")
                if self.empty_target_action == "pos":
                    recalls.append(jnp.ones(max_k))
                    precisions.append(jnp.ones(max_k))
                elif self.empty_target_action == "neg":
                    recalls.append(jnp.zeros(max_k))
                    precisions.append(jnp.zeros(max_k))
            else:
                precision, recall, _ = retrieval_precision_recall_curve(
                    jnp.asarray(mini_preds), jnp.asarray(mini_target), max_k, self.adaptive_k
                )
                precisions.append(precision)
                recalls.append(recall)

        from torchmetrics_tpu.retrieval.base import _retrieval_aggregate

        precision = (
            _retrieval_aggregate(jnp.stack(precisions), self.aggregation, dim=0)
            if precisions
            else jnp.zeros(max_k)
        )
        recall = (
            _retrieval_aggregate(jnp.stack(recalls), self.aggregation, dim=0)
            if recalls
            else jnp.zeros(max_k)
        )
        top_k = jnp.arange(1, max_k + 1, dtype=jnp.int32)
        return precision, recall, top_k


class RetrievalRecallAtFixedPrecision(RetrievalPrecisionRecallCurve):
    r"""Max recall@k subject to a minimum precision, with the best k.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalRecallAtFixedPrecision
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> metric = RetrievalRecallAtFixedPrecision(min_precision=0.5)
        >>> recall, best_k = metric(preds, target, indexes=indexes)
        >>> int(best_k)
        3
    """

    def __init__(self, min_precision: float = 0.0, max_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(max_k=max_k, **kwargs)
        if not (isinstance(min_precision, float) and 0.0 <= min_precision <= 1.0):
            raise ValueError("`min_precision` has to be a positive float between 0 and 1")
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        """Best recall meeting the precision floor."""
        precisions, recalls, top_k = super().compute()
        candidates = [
            (float(r), int(k)) for p, r, k in zip(precisions, recalls, top_k) if float(p) >= self.min_precision
        ]
        if candidates:
            max_recall, best_k = max(candidates)
        else:
            max_recall, best_k = 0.0, len(top_k)
        if max_recall == 0.0:
            best_k = len(top_k)
        return jnp.asarray(max_recall, dtype=jnp.float32), jnp.asarray(best_k, dtype=jnp.int32)
