"""Retrieval metrics (stateful modules).

Parity: reference ``src/torchmetrics/retrieval/__init__.py`` (11 classes + base).
"""

from torchmetrics_tpu.retrieval.base import RetrievalMetric
from torchmetrics_tpu.retrieval.modules import (
    RetrievalAUROC,
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
    RetrievalRPrecision,
)

__all__ = [
    "RetrievalAUROC",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMetric",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
    "RetrievalRPrecision",
]
