"""ONNX graph → JAX: convert parsed graphs to npz+json artifacts and execute in jnp.

Reference parity target: ``functional/audio/dnsmos.py`` runs the DNSMOS ONNX
checkpoints through ``onnxruntime`` on the host. Here a converted graph executes
as pure jnp ops — jittable, fusible, TPU-resident. The executor covers the op
subset that small keras/tf-exported CNN scoring heads use; an unsupported op
raises with its name so the table is one function away from extension.

Shape plumbing: ONNX graphs from keras exports compute reshape targets through
``Shape → Gather → Concat`` chains. Those must stay *concrete* under ``jit``, so
ops whose inputs are all host numpy arrays evaluate in numpy; only tensor math on
device arrays traces into the jaxpr.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

import numpy as np

import jax.numpy as jnp
from jax import lax

from torchmetrics_tpu.convert.onnx_reader import parse_onnx

GRAPH_NAME = "graph.json"
PARAMS_NAME = "params.npz"


def convert_onnx_flax(onnx_path: str, out_dir: str) -> str:
    """Convert an ONNX file to a ``graph.json`` + ``params.npz`` directory."""
    from torchmetrics_tpu.convert import _record_manifest, sha256_file

    graph = parse_onnx(onnx_path)
    os.makedirs(out_dir, exist_ok=True)
    params_path = os.path.join(out_dir, PARAMS_NAME)
    spec = {k: graph[k] for k in ("nodes", "inputs", "outputs", "name")}
    # tensor-valued attributes (Constant nodes) move into the params file
    consts = {}
    for i, node in enumerate(spec["nodes"]):
        for k, v in list(node["attrs"].items()):
            if isinstance(v, np.ndarray):
                key = f"__attr_{i}_{k}"
                consts[key] = v
                node["attrs"][k] = {"__tensor__": key}
    np.savez(params_path, **graph["initializers"], **consts)
    graph_path = os.path.join(out_dir, GRAPH_NAME)
    with open(graph_path, "w") as fh:
        json.dump(spec, fh)
    _record_manifest(
        os.path.join(out_dir, PARAMS_NAME),
        {
            "kind": "onnx-flax",
            "source": os.path.abspath(onnx_path),
            "source_sha256": sha256_file(onnx_path),
            "output_sha256": sha256_file(params_path),
            "ops": sorted({n["op"] for n in spec["nodes"]}),
        },
    )
    return out_dir


def load_onnx_graph(model_dir: str):
    """Load a converted directory -> (spec dict, params dict of numpy arrays)."""
    with open(os.path.join(model_dir, GRAPH_NAME)) as fh:
        spec = json.load(fh)
    with np.load(os.path.join(model_dir, PARAMS_NAME)) as z:
        params = {k: z[k] for k in z.files}
    for node in spec["nodes"]:
        for k, v in list(node["attrs"].items()):
            if isinstance(v, dict) and "__tensor__" in v:
                node["attrs"][k] = params.pop(v["__tensor__"])
    return spec, params


def _all_host(values) -> bool:
    """True when every *present* input is host-concrete (numpy or scalar).

    ``None`` entries are absent optional inputs (e.g. ``Clip`` with only a min
    bound, ONNX's empty-string input name) — they must not force the device
    path, or a host-concrete shape-plumbing subgraph traces into the jaxpr and
    loses its static value under jit.
    """
    return all(v is None or isinstance(v, np.ndarray) or np.isscalar(v) for v in values)


def _pool_dims(x, kernel, strides, pads, reducer, init, count_include_pad):
    """Shared 2-D pooling: ONNX pads are [d1_begin, d2_begin, d1_end, d2_end]."""
    rank = len(kernel)
    pads = pads or [0] * (2 * rank)
    strides = strides or [1] * rank
    window = (1, 1, *kernel)
    stride = (1, 1, *strides)
    padding = ((0, 0), (0, 0)) + tuple((pads[i], pads[i + rank]) for i in range(rank))
    out = lax.reduce_window(x, init, reducer, window, stride, padding)
    if reducer is lax.add:  # average pool
        if count_include_pad:
            denom = float(np.prod(kernel))
            return out / denom
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, stride, padding)
        return out / counts
    return out


def _conv(x, w, b, attrs):
    rank = w.ndim - 2
    strides = attrs.get("strides") or [1] * rank
    dilations = attrs.get("dilations") or [1] * rank
    group = int(attrs.get("group") or 1)
    pads = attrs.get("pads")
    auto_pad = attrs.get("auto_pad") or "NOTSET"
    if auto_pad in ("SAME_UPPER", "SAME_LOWER"):
        # ONNX puts the odd pad sample at the END for SAME_UPPER and at the
        # BEGINNING for SAME_LOWER; lax's "SAME" is upper-only, so build explicit
        padding = []
        for i in range(rank):
            size = x.shape[2 + i]
            eff_k = (w.shape[2 + i] - 1) * dilations[i] + 1
            total = max(0, (-(-size // strides[i]) - 1) * strides[i] + eff_k - size)
            small, big = total // 2, total - total // 2
            padding.append((small, big) if auto_pad == "SAME_UPPER" else (big, small))
        padding = tuple(padding)
    elif pads:
        padding = tuple((pads[i], pads[i + rank]) for i in range(rank))
    else:
        padding = "VALID"
    spec = ("NCHW", "OIHW", "NCHW") if rank == 2 else ("NCH", "OIH", "NCH")
    out = lax.conv_general_dilated(
        x, jnp.asarray(w), tuple(strides), padding,
        rhs_dilation=tuple(dilations), dimension_numbers=spec, feature_group_count=group,
    )
    if b is not None:
        out = out + jnp.asarray(b).reshape((1, -1) + (1,) * rank)
    return out


def _gemm(a, b, c, attrs):
    alpha = attrs.get("alpha")
    beta = attrs.get("beta")
    alpha = 1.0 if alpha is None else alpha  # an explicit 0.0 must stay 0.0
    beta = 1.0 if beta is None else beta
    if attrs.get("transA"):
        a = a.T
    if attrs.get("transB"):
        b = b.T
    out = alpha * (a @ b)
    if c is not None:
        out = out + beta * c
    return out


def _slice_op(data, ins, attrs):
    if len(ins) > 1:  # opset >= 10: starts/ends/axes/steps are inputs
        starts = np.asarray(ins[1]).tolist()
        ends = np.asarray(ins[2]).tolist()
        axes = np.asarray(ins[3]).tolist() if len(ins) > 3 and ins[3] is not None else list(range(len(starts)))
        steps = np.asarray(ins[4]).tolist() if len(ins) > 4 and ins[4] is not None else [1] * len(starts)
    else:  # opset 1: attributes
        starts = attrs["starts"]
        ends = attrs["ends"]
        axes = attrs.get("axes") or list(range(len(starts)))
        steps = [1] * len(starts)
    slices = [slice(None)] * data.ndim
    for s, e, ax, st in zip(starts, ends, axes, steps):
        dim = data.shape[ax]
        e = min(e, dim) if e >= 0 else e  # ONNX clamps INT64_MAX-style ends
        slices[int(ax)] = slice(int(s), int(e), int(st))
    return data[tuple(slices)]


_CAST_DTYPES = {1: jnp.float32, 6: jnp.int32, 7: jnp.int64, 9: jnp.bool_, 10: jnp.float16, 11: jnp.float64}


def run_graph(spec: Dict[str, Any], params: Dict[str, np.ndarray], inputs: Dict[str, Any]) -> List[Any]:
    """Execute the graph on ``inputs``; returns the list of graph outputs.

    Host-concrete subgraphs (all-numpy inputs) evaluate in numpy so reshape
    targets and axes stay static under jit; tensor math runs in jnp.
    """
    env: Dict[str, Any] = {"": None}
    env.update(params)
    env.update(inputs)

    for node in spec["nodes"]:
        op = node["op"]
        attrs = node["attrs"]
        ins = [env[name] for name in node["inputs"]]
        host = _all_host(ins)
        xp = np if host else jnp
        x = ins[0] if ins else None

        if op in ("Relu",):
            out = xp.maximum(x, 0)
        elif op == "Sigmoid":
            out = 1.0 / (1.0 + xp.exp(-x))
        elif op == "Tanh":
            out = xp.tanh(x)
        elif op == "Softmax":
            ax = int(attrs.get("axis", -1))
            e = xp.exp(x - xp.max(x, axis=ax, keepdims=True))
            out = e / xp.sum(e, axis=ax, keepdims=True)
        elif op == "LeakyRelu":
            out = xp.where(x >= 0, x, x * attrs.get("alpha", 0.01))
        elif op == "Exp":
            out = xp.exp(x)
        elif op == "Sqrt":
            out = xp.sqrt(x)
        elif op == "Pow":
            out = x ** ins[1]
        elif op == "Clip":
            lo = ins[1] if len(ins) > 1 and ins[1] is not None else attrs.get("min")
            hi = ins[2] if len(ins) > 2 and ins[2] is not None else attrs.get("max")
            out = x if lo is None and hi is None else xp.clip(x, lo, hi)  # boundless Clip is identity
        elif op == "Add":
            out = x + ins[1]
        elif op == "Sub":
            out = x - ins[1]
        elif op == "Mul":
            out = x * ins[1]
        elif op == "Div":
            out = x / ins[1]
        elif op == "MatMul":
            out = x @ ins[1]
        elif op == "Gemm":
            out = _gemm(x, ins[1], ins[2] if len(ins) > 2 else None, attrs)
        elif op == "Conv":
            out = _conv(x, ins[1], ins[2] if len(ins) > 2 else None, attrs)
        elif op in ("MaxPool", "AveragePool"):
            if attrs.get("ceil_mode") or (attrs.get("auto_pad") or "NOTSET") not in ("NOTSET", "VALID"):
                raise NotImplementedError(
                    f"ONNX {op} with ceil_mode/auto_pad (node {node['name']!r}) is not"
                    " supported — extend run_graph in torchmetrics_tpu/convert/onnx_flax.py"
                )
            if op == "MaxPool":
                out = _pool_dims(x, attrs["kernel_shape"], attrs.get("strides"), attrs.get("pads"),
                                 lax.max, -jnp.inf, False)
            else:
                out = _pool_dims(x, attrs["kernel_shape"], attrs.get("strides"), attrs.get("pads"),
                                 lax.add, 0.0, bool(attrs.get("count_include_pad")))
        elif op == "GlobalAveragePool":
            out = jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)
        elif op == "GlobalMaxPool":
            out = jnp.max(x, axis=tuple(range(2, x.ndim)), keepdims=True)
        elif op == "BatchNormalization":
            scale, bias, mean, var = ins[1], ins[2], ins[3], ins[4]
            eps = attrs.get("epsilon", 1e-5)
            shape = (1, -1) + (1,) * (x.ndim - 2)
            out = (x - mean.reshape(shape)) / xp.sqrt(var.reshape(shape) + eps)
            out = out * scale.reshape(shape) + bias.reshape(shape)
        elif op == "Reshape":
            target = [int(v) for v in np.asarray(ins[1]).tolist()]
            target = [x.shape[i] if v == 0 else v for i, v in enumerate(target)]
            out = x.reshape(target)
        elif op == "Transpose":
            perm = attrs.get("perm") or list(range(x.ndim))[::-1]
            out = xp.transpose(x, perm)
        elif op == "Flatten":
            ax = int(attrs.get("axis", 1))
            out = x.reshape((int(np.prod(x.shape[:ax])) or 1, -1))
        elif op == "Squeeze":
            axes = attrs.get("axes") or (np.asarray(ins[1]).tolist() if len(ins) > 1 else None)
            out = xp.squeeze(x, axis=tuple(int(a) for a in axes) if axes else None)
        elif op == "Unsqueeze":
            axes = attrs.get("axes") or np.asarray(ins[1]).tolist()
            out = x
            for a in sorted(int(v) for v in axes):
                out = xp.expand_dims(out, a)
        elif op == "Concat":
            out = xp.concatenate(ins, axis=int(attrs.get("axis", 0)))
        elif op == "Slice":
            out = _slice_op(x, ins, attrs)
        elif op == "Gather":
            out = xp.take(x, np.asarray(ins[1]) if host else ins[1], axis=int(attrs.get("axis", 0)))
        elif op == "Shape":
            out = np.asarray(x.shape, dtype=np.int64)
        elif op == "Cast":
            out = x.astype(_CAST_DTYPES.get(int(attrs["to"]), jnp.float32))
        elif op == "ReduceMean":
            axes = attrs.get("axes")
            out = xp.mean(x, axis=tuple(int(a) for a in axes) if axes else None,
                          keepdims=bool(attrs.get("keepdims", 1)))
        elif op == "Pad":
            mode = attrs.get("mode") or "constant"
            if mode != "constant":
                raise NotImplementedError(
                    f"ONNX Pad mode {mode!r} (node {node['name']!r}) is not supported"
                    " — extend run_graph in torchmetrics_tpu/convert/onnx_flax.py"
                )
            pads = attrs.get("pads") or np.asarray(ins[1]).tolist()
            fill = attrs.get("value", 0.0)
            if len(ins) > 2 and ins[2] is not None:
                fill = float(np.asarray(ins[2]).reshape(-1)[0])
            rank = x.ndim
            width = [(int(pads[i]), int(pads[i + rank])) for i in range(rank)]
            out = xp.pad(x, width, constant_values=fill)
        elif op in ("Identity", "Dropout"):
            out = x
        elif op == "Constant":
            val = attrs.get("value")
            out = np.asarray(val)
        elif op == "ConstantOfShape":
            val = attrs.get("value")
            fill = float(np.asarray(val).reshape(-1)[0]) if val is not None else 0.0
            out = np.full([int(v) for v in np.asarray(x).tolist()], fill, dtype=np.float32)
        elif op == "Expand":
            out = xp.broadcast_to(x, [int(v) for v in np.asarray(ins[1]).tolist()])
        else:
            raise NotImplementedError(
                f"ONNX op {op!r} (node {node['name']!r}) is not in the converter's op"
                " table — extend run_graph in torchmetrics_tpu/convert/onnx_flax.py"
            )

        outputs = node["outputs"]
        env[outputs[0]] = out
        for extra in outputs[1:]:  # e.g. Dropout's mask output — never consumed here
            env[extra] = None

    return [env[name] for name in spec["outputs"]]
