"""Checkpoint conversion kit: torch checkpoints → JAX-native npz/flax artifacts.

This environment (and many TPU pods) has no network egress, so the pretrained
networks behind the model-based metrics — FID/KID/IS/MIFID's Inception-v3
(torch-fidelity checkpoint, reference ``src/torchmetrics/image/fid.py:44-66``),
the LPIPS backbones (torchvision, ``functional/image/lpips.py:65-204``), and the
BERTScore/InfoLM/CLIP transformers models — must be provided as local files. The
converters here turn those torch checkpoints into artifacts every metric in this
package loads directly:

- ``convert_inception``  — torch-fidelity ``pt_inception-2015-12-05-*.pth`` → flat npz
- ``convert_lpips_backbone`` — torchvision ``{alexnet,vgg16,squeezenet1_1}-*.pth`` → flat npz
- ``convert_hf_flax``    — a local HF snapshot with torch weights → flax ``save_pretrained``

Each conversion records input/output SHA-256 checksums in a ``MANIFEST.json`` next to
the outputs, so a converted-weights directory is self-describing and auditable.

CLI: ``python -m torchmetrics_tpu.convert --help``.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Optional

MANIFEST_NAME = "MANIFEST.json"


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            block = fh.read(chunk)
            if not block:
                break
            digest.update(block)
    return digest.hexdigest()


def _record_manifest(out_path: str, entry: Dict[str, Any]) -> str:
    """Merge ``entry`` into the MANIFEST.json beside ``out_path`` (keyed by output)."""
    manifest_path = os.path.join(os.path.dirname(os.path.abspath(out_path)), MANIFEST_NAME)
    manifest: Dict[str, Any] = {}
    if os.path.exists(manifest_path):
        with open(manifest_path) as fh:
            manifest = json.load(fh)
    manifest[os.path.basename(out_path)] = entry
    with open(manifest_path, "w") as fh:
        json.dump(manifest, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return manifest_path


def convert_inception(checkpoint: str, out: str) -> str:
    """torch-fidelity FID Inception-v3 ``.pth`` → flat flax-pytree ``.npz``.

    The emitted npz loads through ``InceptionFeatureExtractor(weights_path=out)``
    (or $TORCHMETRICS_TPU_INCEPTION_WEIGHTS) with no torch needed at runtime.
    """
    from torchmetrics_tpu.image._inception_net import load_torch_fidelity_weights
    from torchmetrics_tpu.utils.serialization import save_tree_npz

    variables = load_torch_fidelity_weights(checkpoint)
    out = save_tree_npz(out, variables)
    _record_manifest(
        out,
        {
            "kind": "fid-inception-v3",
            "source": os.path.basename(checkpoint),
            "source_sha256": sha256_file(checkpoint),
            "sha256": sha256_file(out),
        },
    )
    return out


def convert_lpips_backbone(checkpoint: str, net_type: str, out: str) -> str:
    """torchvision backbone ``.pth`` → flat LPIPS-pyramid ``.npz``.

    ``net_type``: ``alex`` (alexnet-owt), ``vgg`` (vgg16), or ``squeeze``
    (squeezenet1_1). The emitted npz is picked up from the
    $TORCHMETRICS_TPU_LPIPS_BACKBONES directory as ``{net_type}.npz``.
    """
    import torch

    from torchmetrics_tpu.functional.image._lpips_backbones import convert_torchvision_backbone
    from torchmetrics_tpu.utils.serialization import save_tree_npz

    state = torch.load(checkpoint, map_location="cpu", weights_only=True)
    params = convert_torchvision_backbone({k: v.numpy() for k, v in state.items()}, net_type)
    out = save_tree_npz(out, params)
    _record_manifest(
        out,
        {
            "kind": f"lpips-backbone-{net_type}",
            "source": os.path.basename(checkpoint),
            "source_sha256": sha256_file(checkpoint),
            "sha256": sha256_file(out),
        },
    )
    return out


def convert_hf_flax(model_path: str, out_dir: str, model_class: Optional[str] = None) -> str:
    """Local HF snapshot (torch weights) → flax ``save_pretrained`` directory.

    Loads with ``Flax<Auto>Model.from_pretrained(..., from_pt=True)`` when only torch
    weights exist, then saves flax weights + config (and tokenizer/processor when
    present) to ``out_dir`` — the directory the BERTScore/InfoLM/CLIPScore metrics
    accept as ``model_name_or_path``. ``model_class`` optionally names a specific
    transformers Flax class (e.g. ``FlaxCLIPModel``); default is ``FlaxAutoModel``.
    """
    import transformers
    from transformers import AutoTokenizer

    from torchmetrics_tpu.utils.imports import load_flax_with_pt_fallback

    cls = getattr(transformers, model_class) if model_class else transformers.FlaxAutoModel
    model = load_flax_with_pt_fallback(cls, model_path)
    os.makedirs(out_dir, exist_ok=True)
    model.save_pretrained(out_dir)

    # AutoProcessor first: for CLIP-style models it bundles the image processor AND
    # the tokenizer; plain AutoTokenizer is the fallback for bare encoders
    for loader in (getattr(transformers, "AutoProcessor", None), AutoTokenizer):
        if loader is None:
            continue
        try:
            loader.from_pretrained(model_path, local_files_only=True).save_pretrained(out_dir)
            break
        except Exception:  # tokenizer/processor is optional (e.g. bare encoders)
            continue

    import glob

    # large models shard as flax_model-00001-of-0000N.msgpack — record every shard
    shards = sorted(glob.glob(os.path.join(out_dir, "flax_model*.msgpack")))
    for shard in shards:
        _record_manifest(
            shard,
            {"kind": "hf-flax", "source": os.path.abspath(model_path), "sha256": sha256_file(shard)},
        )
    if not shards:  # still leave an auditable trace of the conversion
        _record_manifest(
            os.path.join(out_dir, "flax_model.msgpack"),
            {"kind": "hf-flax", "source": os.path.abspath(model_path)},
        )
    return out_dir
