"""CLI for the checkpoint conversion kit — see package docstring.

Examples::

    python -m torchmetrics_tpu.convert inception pt_inception-2015-12-05-6726825d.pth \
        -o weights/inception.npz
    python -m torchmetrics_tpu.convert lpips-backbone vgg16-397923af.pth --net vgg \
        -o weights/vgg.npz
    python -m torchmetrics_tpu.convert hf-flax /data/hf/roberta-large -o weights/roberta-large
    python -m torchmetrics_tpu.convert hf-flax /data/hf/clip-vit-base-patch16 \
        --model-class FlaxCLIPModel -o weights/clip-vit-base-patch16
"""

from __future__ import annotations

import argparse
import os
import sys

# conversion is host-side numpy work — never wait on an accelerator runtime. The
# host image may pin JAX_PLATFORMS to a tunneled TPU plugin (and import jax at
# interpreter startup), so the env var alone is not enough: force the config and
# deregister any non-cpu backend factory before anything can init it.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    import jax._src.xla_bridge as _xb

    for _name in [n for n in _xb._backend_factories if n != "cpu"]:
        _xb._backend_factories.pop(_name, None)
except Exception:
    pass

from torchmetrics_tpu.convert import convert_hf_flax, convert_inception, convert_lpips_backbone  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchmetrics_tpu.convert",
        description="Convert locally provided torch checkpoints to JAX-native artifacts.",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_inc = sub.add_parser("inception", help="torch-fidelity FID Inception-v3 .pth -> .npz")
    p_inc.add_argument("checkpoint", help="path to pt_inception-2015-12-05-*.pth")
    p_inc.add_argument("-o", "--out", default="inception.npz", help="output npz path")

    p_lpips = sub.add_parser("lpips-backbone", help="torchvision backbone .pth -> .npz")
    p_lpips.add_argument("checkpoint", help="torchvision alexnet/vgg16/squeezenet1_1 .pth")
    p_lpips.add_argument("--net", required=True, choices=("alex", "vgg", "squeeze"))
    p_lpips.add_argument("-o", "--out", default=None, help="output npz path (default {net}.npz)")

    p_hf = sub.add_parser("hf-flax", help="local HF snapshot (torch weights) -> flax directory")
    p_hf.add_argument("model_path", help="local HF model directory or cached name")
    p_hf.add_argument("-o", "--out", required=True, help="output directory")
    p_hf.add_argument(
        "--model-class",
        default=None,
        help="transformers Flax class name (e.g. FlaxCLIPModel); default FlaxAutoModel",
    )

    p_onnx = sub.add_parser(
        "onnx-flax", help="ONNX inference graph (e.g. DNSMOS model_v8/sig_bak_ovr) -> jnp graph dir"
    )
    p_onnx.add_argument("onnx_path", help="path to the .onnx file")
    p_onnx.add_argument("-o", "--out", required=True, help="output directory")

    args = parser.parse_args(argv)
    if args.command == "inception":
        out = convert_inception(args.checkpoint, args.out)
        manifest_anchor = os.path.dirname(os.path.abspath(out))
    elif args.command == "lpips-backbone":
        out = convert_lpips_backbone(args.checkpoint, args.net, args.out or f"{args.net}.npz")
        manifest_anchor = os.path.dirname(os.path.abspath(out))
    elif args.command == "onnx-flax":
        from torchmetrics_tpu.convert.onnx_flax import convert_onnx_flax

        out = convert_onnx_flax(args.onnx_path, args.out)
        manifest_anchor = os.path.abspath(out)
    else:
        out = convert_hf_flax(args.model_path, args.out, model_class=args.model_class)
        manifest_anchor = os.path.abspath(out)  # manifest lives inside the output dir
    print(f"wrote {out} (manifest: {os.path.join(manifest_anchor, 'MANIFEST.json')})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
