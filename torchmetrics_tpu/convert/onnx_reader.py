"""Minimal ONNX model reader — a protobuf wire-format parser, no ``onnx`` package.

The DNSMOS checkpoints (reference ``functional/audio/dnsmos.py:41-95``) ship as
ONNX protobufs and the reference executes them with ``onnxruntime``. Neither
package exists in this image, and an ONNX *file* is just protobuf wire data: a
sequence of (tag varint, payload) records. This module parses exactly the message
subset a converted inference graph needs — ModelProto → GraphProto → NodeProto /
AttributeProto / TensorProto — into plain dicts + numpy arrays, from the published
`onnx.proto` field numbers. Anything it does not understand is skipped (unknown
fields are forward-compatible by protobuf design) or raises with a clear name.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

# wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5

# TensorProto.DataType -> numpy dtype (the subset inference graphs use)
_TENSOR_DTYPES = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64,
    12: np.uint32, 13: np.uint64,
}
# TensorProto repeated-field number -> numpy dtype for non-raw storage
_FIELD_DTYPES = {4: np.float32, 5: np.int32, 7: np.int64, 10: np.float64, 11: np.uint64}


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) records; LEN values are bytes."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == _VARINT:
            val, pos = _read_varint(buf, pos)
        elif wire == _I64:
            val, pos = buf[pos : pos + 8], pos + 8
        elif wire == _LEN:
            ln, pos = _read_varint(buf, pos)
            val, pos = buf[pos : pos + ln], pos + ln
        elif wire == _I32:
            val, pos = buf[pos : pos + 4], pos + 4
        else:
            raise ValueError(f"Unsupported protobuf wire type {wire} (field {field})")
        yield field, wire, val


def _packed_or_single(wire: int, val, out: List[int]) -> None:
    """Repeated varint fields arrive packed (LEN) or one-per-record."""
    if wire == _LEN:
        pos = 0
        while pos < len(val):
            v, pos = _read_varint(val, pos)
            out.append(v)
    else:
        out.append(val)


def _parse_tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    """TensorProto: dims=1, data_type=2, typed data=4/5/6/7/10/11, name=8, raw_data=9."""
    dims: List[int] = []
    data_type = 1
    name = ""
    raw = None
    typed: List[Any] = []
    typed_dtype = None
    for field, wire, val in _fields(buf):
        if field == 1:
            _packed_or_single(wire, val, dims)
        elif field == 2:
            data_type = val
        elif field == 8:
            name = val.decode("utf-8")
        elif field == 9:
            raw = val
        elif field in _FIELD_DTYPES:
            typed_dtype = _FIELD_DTYPES[field]
            if wire == _LEN and field in (5, 7, 11):  # packed varints
                raw_vals: List[int] = []
                _packed_or_single(wire, val, raw_vals)
                # int32_data/int64_data varints are two's-complement in 64 bits
                typed.extend(raw_vals if field == 11 else [_signed_int(v) for v in raw_vals])
            elif wire == _LEN:  # packed floats/doubles
                typed.extend(np.frombuffer(val, dtype=typed_dtype).tolist())
            elif wire == _I32:
                typed.append(np.frombuffer(val, dtype=np.float32)[0])
            elif wire == _I64:
                typed.append(np.frombuffer(val, dtype=np.float64)[0])
            else:
                typed.append(val if field == 11 else _signed_int(val))
        elif field == 6:  # string_data
            raise ValueError(f"String tensors are not supported (tensor {name!r})")
    dtype = _TENSOR_DTYPES.get(data_type)
    if dtype is None:
        raise ValueError(f"Unsupported tensor data_type {data_type} (tensor {name!r})")
    if raw is not None:
        arr = np.frombuffer(raw, dtype=np.dtype(dtype).newbyteorder("<")).astype(dtype)
    elif data_type == 10:
        # float16 in int32_data ships as uint16 BIT PATTERNS, not values (onnx spec)
        arr = np.asarray(typed, dtype=np.uint16).view(np.float16)
    else:
        arr = np.asarray(typed, dtype=typed_dtype or dtype).astype(dtype)
    return name, arr.reshape([int(d) for d in dims]) if dims else arr.reshape(())


def _parse_attribute(buf: bytes) -> Tuple[str, Any]:
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8, strings=9, type=20."""
    name = ""
    single: Any = None
    floats: List[float] = []
    ints: List[int] = []
    strings: List[str] = []
    for field, wire, val in _fields(buf):
        if field == 1:
            name = val.decode("utf-8")
        elif field == 2:
            single = float(np.frombuffer(val, dtype=np.float32)[0])
        elif field == 3:
            single = _signed(val)
        elif field == 4:
            single = val.decode("utf-8", errors="replace")
        elif field == 5:
            single = _parse_tensor(val)[1]
        elif field == 7:
            if wire == _LEN:
                floats.extend(np.frombuffer(val, dtype=np.float32).tolist())
            else:
                floats.append(float(np.frombuffer(val, dtype=np.float32)[0]))
        elif field == 8:
            raw_ints: List[int] = []
            _packed_or_single(wire, val, raw_ints)
            ints.extend(_signed_int(v) for v in raw_ints)
        elif field == 9:
            strings.append(val.decode("utf-8", errors="replace"))
    if single is not None:
        return name, single
    if floats:
        return name, floats
    if ints:
        return name, ints
    if strings:
        return name, strings
    return name, None


def _signed_int(v: int) -> int:
    """Protobuf int64 varints are two's-complement in 64 bits."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _signed(v: int) -> int:
    return _signed_int(v)


def _parse_node(buf: bytes) -> Dict[str, Any]:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    node: Dict[str, Any] = {"inputs": [], "outputs": [], "name": "", "op": "", "attrs": {}}
    for field, _wire, val in _fields(buf):
        if field == 1:
            node["inputs"].append(val.decode("utf-8"))
        elif field == 2:
            node["outputs"].append(val.decode("utf-8"))
        elif field == 3:
            node["name"] = val.decode("utf-8")
        elif field == 4:
            node["op"] = val.decode("utf-8")
        elif field == 5:
            k, v = _parse_attribute(val)
            node["attrs"][k] = v
    return node


def _value_info_name(buf: bytes) -> str:
    for field, _wire, val in _fields(buf):
        if field == 1:
            return val.decode("utf-8")
    return ""


def _parse_graph(buf: bytes) -> Dict[str, Any]:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    graph: Dict[str, Any] = {"nodes": [], "initializers": {}, "inputs": [], "outputs": [], "name": ""}
    for field, _wire, val in _fields(buf):
        if field == 1:
            graph["nodes"].append(_parse_node(val))
        elif field == 2:
            graph["name"] = val.decode("utf-8")
        elif field == 5:
            name, arr = _parse_tensor(val)
            graph["initializers"][name] = arr
        elif field == 11:
            graph["inputs"].append(_value_info_name(val))
        elif field == 12:
            graph["outputs"].append(_value_info_name(val))
    # graph inputs include initializers in older opsets; real runtime inputs are the rest
    graph["inputs"] = [n for n in graph["inputs"] if n not in graph["initializers"]]
    return graph


def parse_onnx(path_or_bytes) -> Dict[str, Any]:
    """Parse an ONNX file into {nodes, initializers, inputs, outputs, name}.

    ``nodes`` are dicts {op, name, inputs, outputs, attrs}; ``initializers`` maps
    names to numpy arrays; ``inputs``/``outputs`` are the graph boundary names
    (initializers excluded from inputs).
    """
    if isinstance(path_or_bytes, (bytes, bytearray)):
        buf = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as fh:
            buf = fh.read()
    for field, _wire, val in _fields(buf):  # ModelProto: graph = 7
        if field == 7:
            return _parse_graph(val)
    raise ValueError("No graph found: not an ONNX ModelProto?")
