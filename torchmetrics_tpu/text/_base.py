"""Shared base for string-input text metrics."""

from __future__ import annotations

from typing import Any

from torchmetrics_tpu.core.metric import Metric


class _TextMetric(Metric):
    """Metric whose update consumes python strings.

    String tokenization cannot trace, so the jitted-update dispatch
    (``core/metric.py:335``) is disabled; the accumulated *counter states* are still
    device arrays and sync with mesh collectives like any other metric.
    """

    def __init__(self, **kwargs: Any) -> None:
        kwargs.setdefault("jit_update", False)
        super().__init__(**kwargs)
