"""InfoLM.

Parity: reference ``src/torchmetrics/text/infolm.py`` + ``functional/text/infolm.py``
(information measures ``:104-296``, per-position masked-LM distributions ``:367-462``,
update/compute ``:465-543``).

The metric masks every token position, runs the masked LM, and aggregates the
temperature-scaled token distributions into one per-sentence vocabulary distribution;
sentence pairs are then compared with the chosen information measure. Pretrained
masked-LM weights must be locally cached (no network egress here) — construction
raises a descriptive error otherwise.
"""

from __future__ import annotations

import math
from collections import Counter, defaultdict
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.text._base import _TextMetric
from torchmetrics_tpu.utils.data import dim_zero_cat
from torchmetrics_tpu.utils.imports import _TRANSFORMERS_AVAILABLE

Array = jax.Array

_ALLOWED_INFORMATION_MEASURES = (
    "kl_divergence",
    "alpha_divergence",
    "beta_divergence",
    "ab_divergence",
    "renyi_divergence",
    "l1_distance",
    "l2_distance",
    "l_infinity_distance",
    "fisher_rao_distance",
)


class _InformationMeasure:
    """The InfoLM divergence/distance family over vocabulary distributions."""

    def __init__(self, information_measure: str, alpha: Optional[float] = None, beta: Optional[float] = None) -> None:
        if information_measure not in _ALLOWED_INFORMATION_MEASURES:
            raise ValueError(
                f"Argument `information_measure` expected to be one of {_ALLOWED_INFORMATION_MEASURES}"
                f" but got {information_measure}"
            )
        self.information_measure = information_measure
        needs_alpha = ("alpha_divergence", "ab_divergence", "renyi_divergence")
        needs_beta = ("beta_divergence", "ab_divergence")
        if information_measure in needs_alpha and not isinstance(alpha, float):
            raise ValueError(f"Parameter `alpha` is expected to be defined for {information_measure}.")
        if information_measure in needs_beta and not isinstance(beta, float):
            raise ValueError(f"Parameter `beta` is expected to be defined for {information_measure}.")
        if information_measure == "alpha_divergence" and alpha in (0, 1):
            raise ValueError(f"Parameter `alpha` is expected to be float differened from 0 and 1 for {information_measure}.")
        if information_measure == "beta_divergence" and beta in (0, -1):
            raise ValueError(f"Parameter `beta` is expected to be float differened from 0 and -1 for {information_measure}.")
        if information_measure == "ab_divergence" and (alpha is None or beta is None or 0 in (alpha, beta, alpha + beta)):
            raise ValueError(
                f"Parameters `alpha`, `beta` and their sum are expected to be differened from 0 for {information_measure}."
            )
        if information_measure == "renyi_divergence" and alpha == 1:
            raise ValueError(f"Parameter `alpha` is expected to be float differened from 1 for {information_measure}.")
        self.alpha = alpha or 0.0
        self.beta = beta or 0.0

    def __call__(self, preds_distribution: Array, target_distribution: Array) -> Array:
        fn = getattr(self, f"_calculate_{self.information_measure}")
        return jnp.nan_to_num(fn(preds_distribution, target_distribution))

    @staticmethod
    def _calculate_kl_divergence(p: Array, t: Array) -> Array:
        return jnp.sum(t * jnp.log(p / t), axis=-1)

    def _calculate_alpha_divergence(self, p: Array, t: Array) -> Array:
        denom = self.alpha * (self.alpha - 1)
        return (1 - jnp.sum(t**self.alpha * p ** (1 - self.alpha), axis=-1)) / denom

    def _calculate_ab_divergence(self, p: Array, t: Array) -> Array:
        a = jnp.log(jnp.sum(t ** (self.beta + self.alpha), axis=-1)) / (self.beta * (self.beta + self.alpha))
        b = jnp.log(jnp.sum(p ** (self.beta + self.alpha), axis=-1)) / (self.alpha * (self.beta + self.alpha))
        c = jnp.log(jnp.sum(t**self.alpha * p**self.beta, axis=-1)) / (self.alpha * self.beta)
        return a + b - c

    def _calculate_beta_divergence(self, p: Array, t: Array) -> Array:
        self.alpha = 1.0
        return self._calculate_ab_divergence(p, t)

    def _calculate_renyi_divergence(self, p: Array, t: Array) -> Array:
        return jnp.log(jnp.sum(t**self.alpha * p ** (1 - self.alpha), axis=-1)) / (self.alpha - 1)

    @staticmethod
    def _calculate_l1_distance(p: Array, t: Array) -> Array:
        return jnp.linalg.norm(t - p, ord=1, axis=-1)

    @staticmethod
    def _calculate_l2_distance(p: Array, t: Array) -> Array:
        return jnp.linalg.norm(t - p, ord=2, axis=-1)

    @staticmethod
    def _calculate_l_infinity_distance(p: Array, t: Array) -> Array:
        return jnp.linalg.norm(t - p, ord=jnp.inf, axis=-1)

    @staticmethod
    def _calculate_fisher_rao_distance(p: Array, t: Array) -> Array:
        return 2 * jnp.arccos(jnp.clip(jnp.sqrt(p * t).sum(axis=-1), 0, 1))


class InfoLM(_TextMetric):
    r"""InfoLM: information measures over masked-LM predictive distributions.

    Requires locally cached masked-LM weights (``google/bert_uncased_L-2_H-128_A-2``
    by default); raises at construction when unavailable (no network egress here).
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        model_name_or_path: str = "google/bert_uncased_L-2_H-128_A-2",
        temperature: float = 0.25,
        information_measure: str = "kl_divergence",
        idf: bool = True,
        alpha: Optional[float] = None,
        beta: Optional[float] = None,
        device: Optional[Any] = None,
        max_length: Optional[int] = None,
        batch_size: int = 64,
        num_threads: int = 0,
        verbose: bool = True,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        # `device`/`num_threads` are accepted for drop-in parity with the reference
        # (text/infolm.py:128-131) and ignored: device placement is global under
        # JAX and tokenization is in-process
        del device, num_threads
        if not (isinstance(batch_size, int) and batch_size > 0):
            raise ValueError(f"Argument `batch_size` is expected to be a positive integer but got {batch_size}")
        self.batch_size = batch_size
        self.verbose = verbose
        self.information_measure_fn = _InformationMeasure(information_measure, alpha, beta)
        if not _TRANSFORMERS_AVAILABLE:
            raise ModuleNotFoundError("InfoLM metric requires that `transformers` is installed.")
        from transformers import AutoTokenizer, FlaxAutoModelForMaskedLM

        from torchmetrics_tpu.utils.imports import load_flax_with_pt_fallback

        try:
            self.tokenizer = AutoTokenizer.from_pretrained(model_name_or_path, local_files_only=True)
            self.model = load_flax_with_pt_fallback(FlaxAutoModelForMaskedLM, model_name_or_path)
        except Exception as err:
            raise OSError(
                f"Could not load `{model_name_or_path}` from the local transformers cache and this"
                " environment has no network access. Provide a locally cached model path."
            ) from err
        if not (isinstance(temperature, float) and temperature > 0):
            raise ValueError(f"Argument `temperature` is expected to be a positive float but got {temperature}")
        # transformers flax models run module.apply eagerly (one dispatch per op);
        # jit the MLM forward with params as an explicit operand — the per-position
        # masking loop then replays one compiled program per (B, S) shape
        self._model_params = self.model.params
        self._jit_logits = jax.jit(
            lambda p, ids, mask: self.model(input_ids=ids, attention_mask=mask, params=p).logits
        )
        self.temperature = temperature
        self.idf = idf
        # cap to the encoder's position budget (padding past it silently corrupts
        # the flax forward; torch raises an index error)
        model_max = self.model.config.max_position_embeddings
        self.max_length = min(max_length, model_max) if max_length else model_max
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("preds_input_ids", [], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", [], dist_reduce_fx="cat")
        self.add_state("target_input_ids", [], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", [], dist_reduce_fx="cat")

    def update(self, preds, target) -> None:
        """Tokenize and store fixed-width id/mask rows."""
        for texts, ids_state, mask_state in (
            (preds, self.preds_input_ids, self.preds_attention_mask),
            (target, self.target_input_ids, self.target_attention_mask),
        ):
            texts = [texts] if isinstance(texts, str) else list(texts)
            enc = self.tokenizer(
                texts, padding="max_length", truncation=True, max_length=self.max_length, return_tensors="np"
            )
            ids_state.append(jnp.asarray(enc["input_ids"]))
            mask_state.append(jnp.asarray(enc["attention_mask"]))

    # ------------------------------------------------------------------ internals

    def _token_mask(self, input_ids: np.ndarray) -> np.ndarray:
        """True for real content tokens (not PAD/SEP/CLS)."""
        special = {
            self.tokenizer.pad_token_id,
            self.tokenizer.sep_token_id,
            self.tokenizer.cls_token_id,
        }
        mask = np.ones_like(input_ids, dtype=bool)
        for tok in special:
            if tok is not None:
                mask &= input_ids != tok
        return mask

    def _ids_idf(self, input_ids: np.ndarray) -> np.ndarray:
        """Per-token inverse document frequencies over this corpus."""
        num_sentences = input_ids.shape[0]
        counter: Counter = Counter()
        for row in input_ids:
            counter.update(set(row.tolist()))
        idf: Dict[int, float] = defaultdict(lambda: math.log(num_sentences + 1))
        idf.update({idx: math.log((num_sentences + 1) / (occ + 1)) for idx, occ in counter.items()})
        return np.vectorize(lambda t: idf[int(t)])(input_ids)

    def _sentence_distribution(self, input_ids: np.ndarray, attention_mask: np.ndarray) -> Array:
        """Aggregate per-position masked-LM distributions into one per sentence."""
        token_mask = self._token_mask(input_ids)
        ids_idf = self._ids_idf(input_ids) if self.idf else None
        seq_len = input_ids.shape[1]
        mask_token_id = self.tokenizer.mask_token_id

        from torchmetrics_tpu.functional.text.bert import _get_progress_bar

        n = input_ids.shape[0]
        distributions = []
        for mask_idx in _get_progress_bar(range(seq_len), self.verbose):
            if not token_mask[:, mask_idx].any():
                distributions.append(np.zeros((input_ids.shape[0], 1)))
                continue
            masked = input_ids.copy()
            masked[:, mask_idx] = mask_token_id
            chunks = []
            for start in range(0, n, self.batch_size):
                ids_b = masked[start : start + self.batch_size]
                mask_b = attention_mask[start : start + self.batch_size]
                rows = ids_b.shape[0]
                if rows < self.batch_size:
                    # bucket the ragged final chunk to a power of two (zero-mask
                    # pad rows are inert, sliced off) so a growing corpus reuses
                    # compiled programs — same recipe as bert._embed_corpus
                    bucket = 1 << (rows - 1).bit_length()
                    if bucket != rows:
                        ids_b = np.pad(ids_b, ((0, bucket - rows), (0, 0)))
                        mask_b = np.pad(mask_b, ((0, bucket - rows), (0, 0)))
                # slice the mask position on device: only (rows, vocab) crosses to
                # host, never the full (rows, seq, vocab) logits
                out = self._jit_logits(self._model_params, ids_b, mask_b)[:rows, mask_idx, :]
                chunks.append(np.asarray(out))
            logits_at_mask = np.concatenate(chunks) if len(chunks) > 1 else chunks[0]
            probs = jax.nn.softmax(jnp.asarray(logits_at_mask) / self.temperature, axis=-1)
            probs = np.asarray(probs, dtype=np.float64)
            if self.idf:
                probs = probs * ids_idf[:, mask_idx : mask_idx + 1]
            distributions.append(probs * token_mask[:, mask_idx : mask_idx + 1])

        vocab = max(d.shape[1] for d in distributions)
        total = np.zeros((input_ids.shape[0], vocab))
        for d in distributions:
            total[:, : d.shape[1]] += d
        if self.idf:
            denom = (token_mask * ids_idf).sum(axis=1, keepdims=True)
        else:
            denom = token_mask.sum(axis=1, keepdims=True)
        return jnp.asarray(total / denom)

    def compute(self):
        """InfoLM score over all accumulated sentence pairs."""
        preds_distribution = self._sentence_distribution(
            np.asarray(dim_zero_cat(self.preds_input_ids)),
            np.asarray(dim_zero_cat(self.preds_attention_mask)),
        )
        target_distribution = self._sentence_distribution(
            np.asarray(dim_zero_cat(self.target_input_ids)),
            np.asarray(dim_zero_cat(self.target_attention_mask)),
        )
        info_lm_score = self.information_measure_fn(preds_distribution, target_distribution)
        if self.return_sentence_level_score:
            return info_lm_score.mean(), info_lm_score
        return info_lm_score.mean()
