"""TranslationEditRate module.

Parity: reference ``src/torchmetrics/text/ter.py:29-176``.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.ter import _TercomTokenizer, _ter_compute, _ter_update
from torchmetrics_tpu.text._base import _TextMetric
from torchmetrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class TranslationEditRate(_TextMetric):
    r"""Translation edit rate of machine-translated text against references.

    Example:
        >>> from torchmetrics_tpu.text import TranslationEditRate
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> ter = TranslationEditRate()
        >>> ter(preds, target).round(4)
        Array(0.1538, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    total_num_edits: Array
    total_tgt_len: Array

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(normalize, bool):
            raise ValueError(f"Expected argument `normalize` to be of type boolean but got {normalize}.")
        if not isinstance(no_punctuation, bool):
            raise ValueError(f"Expected argument `no_punctuation` to be of type boolean but got {no_punctuation}.")
        if not isinstance(lowercase, bool):
            raise ValueError(f"Expected argument `lowercase` to be of type boolean but got {lowercase}.")
        if not isinstance(asian_support, bool):
            raise ValueError(f"Expected argument `asian_support` to be of type boolean but got {asian_support}.")

        self.tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("total_num_edits", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total_tgt_len", jnp.zeros(()), dist_reduce_fx="sum")
        if self.return_sentence_level_score:
            self.add_state("sentence_ter", [], dist_reduce_fx="cat")

    def update(
        self, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]
    ) -> None:
        """Accumulate edit counts and reference lengths."""
        sentence_scores: Optional[List[float]] = [] if self.return_sentence_level_score else None
        total_num_edits, total_tgt_length, sentence_scores = _ter_update(
            preds, target, self.tokenizer, 0.0, 0.0, sentence_scores
        )
        self.total_num_edits = self.total_num_edits + total_num_edits
        self.total_tgt_len = self.total_tgt_len + total_tgt_length
        if sentence_scores is not None:
            self.sentence_ter.append(jnp.asarray(sentence_scores, dtype=jnp.float32))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        """Corpus TER over accumulated state."""
        ter = _ter_compute(self.total_num_edits, self.total_tgt_len)
        if self.return_sentence_level_score:
            return ter, dim_zero_cat(self.sentence_ter)
        return ter
