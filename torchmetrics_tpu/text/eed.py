"""ExtendedEditDistance module.

Parity: reference ``src/torchmetrics/text/eed.py:28-164``.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.eed import _eed_compute, _eed_update
from torchmetrics_tpu.text._base import _TextMetric
from torchmetrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class ExtendedEditDistance(_TextMetric):
    r"""Extended edit distance of machine-translated text against references.

    Example:
        >>> from torchmetrics_tpu.text import ExtendedEditDistance
        >>> preds = ["this is the prediction", "here is an other sample"]
        >>> target = ["this is the reference", "here is another one"]
        >>> eed = ExtendedEditDistance()
        >>> eed(preds=preds, target=target).round(4)
        Array(0.3078, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        language: str = "en",
        return_sentence_level_score: bool = False,
        alpha: float = 2.0,
        rho: float = 0.3,
        deletion: float = 0.2,
        insertion: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        for param_name, param in zip(["alpha", "rho", "deletion", "insertion"], [alpha, rho, deletion, insertion]):
            if not isinstance(param, float) or param < 0:
                raise ValueError(f"Parameter `{param_name}` is expected to be a non-negative float.")
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion

        self.add_state("sentence_eed", [], dist_reduce_fx="cat")

    def update(
        self,
        preds: Union[str, Sequence[str]],
        target: Sequence[Union[str, Sequence[str]]],
    ) -> None:
        """Accumulate per-sentence EED scores."""
        scores = _eed_update(
            preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion
        )
        self.sentence_eed.append(jnp.asarray(scores, dtype=jnp.float32))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        """Mean EED over accumulated sentences."""
        all_scores = dim_zero_cat(self.sentence_eed)
        average = all_scores.mean() if all_scores.size else jnp.asarray(0.0)
        if self.return_sentence_level_score:
            return average, all_scores
        return average
