"""Perplexity module.

Parity: reference ``src/torchmetrics/text/perplexity.py:27-124``. Fully jittable
(tensor inputs), unlike its string-input siblings.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.functional.text.perplexity import _perplexity_compute, _perplexity_update

Array = jax.Array


class Perplexity(Metric):
    r"""Perplexity of a language model's predictions.

    Example:
        >>> import jax
        >>> from torchmetrics_tpu.text import Perplexity
        >>> preds = jax.random.uniform(jax.random.PRNGKey(22), (2, 8, 5))
        >>> target = jax.random.randint(jax.random.PRNGKey(89), (2, 8), 0, 5)
        >>> perp = Perplexity(ignore_index=-100)
        >>> float(perp(preds, target)) > 1
        True
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    total_log_probs: Array
    count: Array

    def __init__(self, ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError(f"Argument `ignore_index` expected to either be `None` or an `int` but got {ignore_index}")
        self.ignore_index = ignore_index
        self.add_state("total_log_probs", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate token NLL sums and valid-token counts."""
        total_log_probs, count = _perplexity_update(preds, target, self.ignore_index)
        self.total_log_probs = self.total_log_probs + total_log_probs
        self.count = self.count + count

    def compute(self) -> Array:
        """Perplexity over accumulated state."""
        return _perplexity_compute(self.total_log_probs, self.count)

    def _compute_group_params(self):
        return (self.ignore_index,)
