"""BERTScore module.

Parity: reference ``src/torchmetrics/text/bert.py:57-268``: tokenized id/mask "cat"
states, full functional option pass-through at compute (the reference's compute calls
the functional ``bert_score`` with pre-tokenized dict inputs — ``text/bert.py:176-206``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.text.bert import (
    _DEFAULT_MODEL,
    _load_flax_model,
    _simple_whitespace_tokenizer,
    bert_score,
)
from torchmetrics_tpu.text._base import _TextMetric
from torchmetrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class BERTScore(_TextMetric):
    r"""BERTScore: greedy cosine matching of contextual embeddings.

    ``model`` may be any callable ``(input_ids, attention_mask) -> (B, S, D)``
    (``(B, num_layers, S, D)`` when ``all_layers=True``); without it,
    ``model_name_or_path`` is loaded via transformers' Flax auto classes (locally
    cached weights required — this environment cannot download them). All reference
    options (``all_layers``, ``user_forward_fn``, ``rescale_with_baseline`` +
    ``baseline_path``/``baseline_url``, ``return_hash``, ``lang``, ``batch_size``,
    ``verbose``) pass through to the functional entry at compute.

    Example:
        >>> import jax
        >>> from torchmetrics_tpu.text import BERTScore
        >>> def toy_model(input_ids, attention_mask):
        ...     table = jax.random.normal(jax.random.PRNGKey(0), (1000, 8))
        ...     return table[input_ids % 1000]
        >>> bertscore = BERTScore(model=toy_model)
        >>> bertscore.update(["hello there"], ["hello there"])
        >>> float(bertscore.compute()["f1"]) > 0.99
        True
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    preds_input_ids: List[Array]
    preds_attention_mask: List[Array]
    target_input_ids: List[Array]
    target_attention_mask: List[Array]

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Callable] = None,
        user_tokenizer: Any = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        device: Optional[Any] = None,
        max_length: int = 512,
        batch_size: int = 64,
        num_threads: int = 0,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_path: Optional[str] = None,
        baseline_url: Optional[str] = None,
        mesh: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        # `device`/`num_threads` exist for drop-in signature parity with the
        # reference (text/bert.py:178-180), where they pick the torch device and
        # DataLoader workers; under JAX device placement is global (mesh/jit) and
        # tokenization is in-process, so both are accepted and ignored.
        del device, num_threads
        self.model_name_or_path = model_name_or_path or _DEFAULT_MODEL
        if model is None:
            model, user_tokenizer = _load_flax_model(self.model_name_or_path, num_layers, all_layers)
            if user_forward_fn is not None:
                # reference contract: user_forward_fn receives the loaded transformers
                # model itself, not the embedding wrapper
                model = model.hf_model
        if mesh is not None and user_forward_fn is None:
            from torchmetrics_tpu.functional.text.bert import _shard_model_over_mesh

            # data-parallel embedding extraction: sentence batch sharded over the mesh
            model = _shard_model_over_mesh(model, mesh)
        self.model = model
        self.user_tokenizer = user_tokenizer
        self.num_layers = num_layers
        self.all_layers = all_layers
        self.user_forward_fn = user_forward_fn
        self.verbose = verbose
        self.idf = idf
        # cap to the loaded encoder's position-embedding budget: padding past it
        # makes the flax forward produce garbage silently (torch would raise an
        # index error) — matters for small/custom local models with < 512 positions
        model_max = getattr(
            getattr(getattr(model, "hf_model", None), "config", None), "max_position_embeddings", None
        )
        if model_max is not None and max_length > model_max:
            max_length = model_max
        self.max_length = max_length
        self.batch_size = batch_size
        self.return_hash = return_hash
        self.lang = lang
        self.rescale_with_baseline = rescale_with_baseline
        self.baseline_path = baseline_path
        self.baseline_url = baseline_url

        self.add_state("preds_input_ids", [], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", [], dist_reduce_fx="cat")
        self.add_state("target_input_ids", [], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", [], dist_reduce_fx="cat")

    def _tokenize(self, texts: Sequence[str]) -> Dict[str, np.ndarray]:
        if self.user_tokenizer is not None:
            enc = self.user_tokenizer(
                list(texts), padding="max_length", truncation=True,
                max_length=self.max_length, return_tensors="np",
            )
            return {"input_ids": np.asarray(enc["input_ids"]), "attention_mask": np.asarray(enc["attention_mask"])}
        # crc32-hashed whitespace fallback, padded to max_length (cat-synced states)
        return _simple_whitespace_tokenizer(list(texts), self.max_length, pad_to_max_length=True)

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        """Tokenize and store fixed-width id/mask rows."""
        preds_list = [preds] if isinstance(preds, str) else list(preds)
        target_list = [target] if isinstance(target, str) else list(target)
        if len(preds_list) != len(target_list):
            raise ValueError("Number of predicted and reference sentences must be the same!")
        enc_p = self._tokenize(preds_list)
        enc_t = self._tokenize(target_list)
        self.preds_input_ids.append(jnp.asarray(enc_p["input_ids"]))
        self.preds_attention_mask.append(jnp.asarray(enc_p["attention_mask"]))
        self.target_input_ids.append(jnp.asarray(enc_t["input_ids"]))
        self.target_attention_mask.append(jnp.asarray(enc_t["attention_mask"]))

    def compute(self) -> Dict[str, Union[Array, List[float], str]]:
        """BERTScore P/R/F1 over all accumulated sentences (pre-tokenized dict path of
        the functional entry, mirroring reference ``text/bert.py:176-206``)."""
        enc_preds = {
            "input_ids": np.asarray(dim_zero_cat(self.preds_input_ids)),
            "attention_mask": np.asarray(dim_zero_cat(self.preds_attention_mask)),
        }
        enc_target = {
            "input_ids": np.asarray(dim_zero_cat(self.target_input_ids)),
            "attention_mask": np.asarray(dim_zero_cat(self.target_attention_mask)),
        }
        return bert_score(
            enc_preds,
            enc_target,
            model_name_or_path=self.model_name_or_path,
            num_layers=self.num_layers,
            all_layers=self.all_layers,
            model=self.model,
            user_forward_fn=self.user_forward_fn,
            verbose=self.verbose,
            idf=self.idf,
            max_length=self.max_length,
            batch_size=self.batch_size,
            return_hash=self.return_hash,
            lang=self.lang,
            rescale_with_baseline=self.rescale_with_baseline,
            baseline_path=self.baseline_path,
            baseline_url=self.baseline_url,
        )
