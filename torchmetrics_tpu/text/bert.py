"""BERTScore module.

Parity: reference ``src/torchmetrics/text/bert.py:57-268``: tokenized id/mask "cat"
states, model embedding + greedy cosine matching at compute.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.text.bert import (
    _DEFAULT_MODEL,
    _embed_and_scale,
    _get_precision_recall_f1,
    _get_tokens_idf,
    _load_flax_model,
    _simple_whitespace_tokenizer,
)
from torchmetrics_tpu.text._base import _TextMetric
from torchmetrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class BERTScore(_TextMetric):
    r"""BERTScore: greedy cosine matching of contextual embeddings.

    ``model`` may be any callable ``(input_ids, attention_mask) -> (B, S, D)``; without
    it, ``model_name_or_path`` is loaded via transformers' Flax auto classes (locally
    cached weights required — this environment cannot download them).

    Example:
        >>> import jax
        >>> from torchmetrics_tpu.text import BERTScore
        >>> def toy_model(input_ids, attention_mask):
        ...     table = jax.random.normal(jax.random.PRNGKey(0), (1000, 8))
        ...     return table[input_ids % 1000]
        >>> bertscore = BERTScore(model=toy_model)
        >>> bertscore.update(["hello there"], ["hello there"])
        >>> float(bertscore.compute()["f1"]) > 0.99
        True
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    preds_input_ids: List[Array]
    preds_attention_mask: List[Array]
    target_input_ids: List[Array]
    target_attention_mask: List[Array]

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        model: Optional[Callable] = None,
        user_tokenizer: Any = None,
        idf: bool = False,
        max_length: int = 512,
        mesh: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if model is None:
            model, user_tokenizer = _load_flax_model(model_name_or_path or _DEFAULT_MODEL, num_layers)
        if mesh is not None:
            from torchmetrics_tpu.functional.text.bert import _shard_model_over_mesh

            # data-parallel embedding extraction: sentence batch sharded over the mesh
            model = _shard_model_over_mesh(model, mesh)
        self.model = model
        self.user_tokenizer = user_tokenizer
        self.idf = idf
        self.max_length = max_length

        self.add_state("preds_input_ids", [], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", [], dist_reduce_fx="cat")
        self.add_state("target_input_ids", [], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", [], dist_reduce_fx="cat")

    def _tokenize(self, texts: Sequence[str]) -> Dict[str, np.ndarray]:
        if self.user_tokenizer is not None:
            enc = self.user_tokenizer(
                list(texts), padding="max_length", truncation=True,
                max_length=self.max_length, return_tensors="np",
            )
            return {"input_ids": np.asarray(enc["input_ids"]), "attention_mask": np.asarray(enc["attention_mask"])}
        # crc32-hashed whitespace fallback, padded to max_length (cat-synced states)
        return _simple_whitespace_tokenizer(list(texts), self.max_length, pad_to_max_length=True)

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        """Tokenize and store fixed-width id/mask rows."""
        preds_list = [preds] if isinstance(preds, str) else list(preds)
        target_list = [target] if isinstance(target, str) else list(target)
        if len(preds_list) != len(target_list):
            raise ValueError("Number of predicted and reference sentences must be the same!")
        enc_p = self._tokenize(preds_list)
        enc_t = self._tokenize(target_list)
        self.preds_input_ids.append(jnp.asarray(enc_p["input_ids"]))
        self.preds_attention_mask.append(jnp.asarray(enc_p["attention_mask"]))
        self.target_input_ids.append(jnp.asarray(enc_t["input_ids"]))
        self.target_attention_mask.append(jnp.asarray(enc_t["attention_mask"]))

    def compute(self) -> Dict[str, Array]:
        """BERTScore P/R/F1 over all accumulated sentences."""
        enc_preds = {
            "input_ids": np.asarray(dim_zero_cat(self.preds_input_ids)),
            "attention_mask": np.asarray(dim_zero_cat(self.preds_attention_mask)),
        }
        enc_target = {
            "input_ids": np.asarray(dim_zero_cat(self.target_input_ids)),
            "attention_mask": np.asarray(dim_zero_cat(self.target_attention_mask)),
        }
        tokens_idf = (
            _get_tokens_idf(enc_target["input_ids"], enc_target["attention_mask"]) if self.idf else None
        )
        preds_emb, preds_w = _embed_and_scale(enc_preds, self.model, self.idf, tokens_idf)
        target_emb, target_w = _embed_and_scale(enc_target, self.model, self.idf, tokens_idf)
        precision, recall, f1_score = _get_precision_recall_f1(preds_emb, target_emb, preds_w, target_w)
        return {"precision": precision, "recall": recall, "f1": f1_score}
