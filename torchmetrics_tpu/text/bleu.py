"""BLEU / SacreBLEU metric modules.

Parity: reference ``src/torchmetrics/text/bleu.py:30-163`` and
``src/torchmetrics/text/sacre_bleu.py:38-169``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update, _tokenize_fn
from torchmetrics_tpu.functional.text.sacre_bleu import AVAILABLE_TOKENIZERS, _SacreBLEUTokenizer
from torchmetrics_tpu.text._base import _TextMetric

Array = jax.Array


class BLEUScore(_TextMetric):
    r"""BLEU score of machine-translated text against references.

    Example:
        >>> from torchmetrics_tpu.text import BLEUScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> bleu = BLEUScore()
        >>> bleu(preds, target).round(4)
        Array(0.75979996, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    preds_len: Array
    target_len: Array
    numerator: Array
    denominator: Array

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights if weights is not None else [1.0 / n_gram] * n_gram

        self.add_state("preds_len", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("target_len", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("numerator", jnp.zeros(self.n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", jnp.zeros(self.n_gram), dist_reduce_fx="sum")

    _tokenizer = staticmethod(_tokenize_fn)

    def update(self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]) -> None:
        """Accumulate clipped n-gram counts for the batch."""
        preds_ = [preds] if isinstance(preds, str) else preds
        target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
        if len(preds_) != len(target_):
            raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")

        numerator = np.asarray(self.numerator).copy()
        denominator = np.asarray(self.denominator).copy()
        preds_len, target_len = _bleu_score_update(
            preds_, target_, numerator, denominator, 0.0, 0.0, self.n_gram, self._tokenizer
        )
        self.preds_len = self.preds_len + preds_len
        self.target_len = self.target_len + target_len
        self.numerator = jnp.asarray(numerator)
        self.denominator = jnp.asarray(denominator)

    def compute(self) -> Array:
        """BLEU over accumulated corpus statistics."""
        return _bleu_score_compute(
            self.preds_len, self.target_len, self.numerator, self.denominator, self.n_gram, self.weights, self.smooth
        )


class SacreBLEUScore(BLEUScore):
    r"""SacreBLEU score with the sacrebleu tokenizer family.

    Example:
        >>> from torchmetrics_tpu.text import SacreBLEUScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> sacre_bleu = SacreBLEUScore()
        >>> sacre_bleu(preds, target).round(4)
        Array(0.75979996, dtype=float32)
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
        self._tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)

    def update(self, preds: Sequence[str], target: Sequence[Sequence[str]]) -> None:
        """Accumulate clipped n-gram counts with sacrebleu tokenization."""
        numerator = np.asarray(self.numerator).copy()
        denominator = np.asarray(self.denominator).copy()
        preds_len, target_len = _bleu_score_update(
            preds, target, numerator, denominator, 0.0, 0.0, self.n_gram, self._tokenizer
        )
        self.preds_len = self.preds_len + preds_len
        self.target_len = self.target_len + target_len
        self.numerator = jnp.asarray(numerator)
        self.denominator = jnp.asarray(denominator)
