"""SQuAD module.

Parity: reference ``src/torchmetrics/text/squad.py:30-153``.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.squad import (
    PREDS_TYPE,
    TARGETS_TYPE,
    _squad_compute,
    _squad_input_check,
    _squad_update,
)
from torchmetrics_tpu.text._base import _TextMetric

Array = jax.Array


class SQuAD(_TextMetric):
    r"""SQuAD v1.1 exact-match / F1 metric.

    Example:
        >>> from torchmetrics_tpu.text import SQuAD
        >>> preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]
        >>> target = [{"answers": {"answer_start": [97], "text": ["1976"]},
        ...            "id": "56e10a3be3433e1400422b22"}]
        >>> sq = SQuAD()
        >>> {k: float(v) for k, v in sq(preds, target).items()}
        {'exact_match': 100.0, 'f1': 100.0}
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 100.0

    f1_score: Array
    exact_match: Array
    total: Array

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("f1_score", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("exact_match", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: PREDS_TYPE, target: TARGETS_TYPE) -> None:
        """Accumulate F1/EM sums and example counts."""
        preds_dict, target_dict = _squad_input_check(preds, target)
        f1, exact_match, total = _squad_update(preds_dict, target_dict)
        self.f1_score = self.f1_score + f1
        self.exact_match = self.exact_match + exact_match
        self.total = self.total + total

    def compute(self) -> Dict[str, Array]:
        """Percent EM/F1 over accumulated state."""
        return _squad_compute(self.f1_score, self.exact_match, self.total)
