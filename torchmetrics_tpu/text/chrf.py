"""CHRFScore module.

Parity: reference ``src/torchmetrics/text/chrf.py:38-228``; the reference's 6×N scalar
states collapse into six fixed-shape per-order vectors (psum-able over the mesh).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.chrf import _chrf_score_compute, _chrf_score_update
from torchmetrics_tpu.text._base import _TextMetric
from torchmetrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class CHRFScore(_TextMetric):
    r"""chrF/chrF++ score of machine-translated text against references.

    Example:
        >>> from torchmetrics_tpu.text import CHRFScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> chrf = CHRFScore()
        >>> chrf(preds, target).round(4)
        Array(0.86399996, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
        self.n_char_order = n_char_order
        if not isinstance(n_word_order, int) or n_word_order < 0:
            raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
        self.n_word_order = n_word_order
        if beta < 0:
            raise ValueError("Expected argument `beta` to be greater than 0.")
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score
        self.n_order = float(n_char_order + n_word_order)

        for prefix in ("total_preds", "total_target", "total_matching"):
            self.add_state(f"{prefix}_char_n_grams", jnp.zeros(n_char_order), dist_reduce_fx="sum")
            self.add_state(f"{prefix}_word_n_grams", jnp.zeros(n_word_order), dist_reduce_fx="sum")
        if self.return_sentence_level_score:
            self.add_state("sentence_chrf_score", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]) -> None:
        """Accumulate the six per-order n-gram total vectors."""
        import numpy as np

        sentence_scores: Optional[List[float]] = [] if self.return_sentence_level_score else None
        (
            total_preds_char,
            total_preds_word,
            total_target_char,
            total_target_word,
            total_matching_char,
            total_matching_word,
            sentence_scores,
        ) = _chrf_score_update(
            preds,
            target,
            np.asarray(self.total_preds_char_n_grams, dtype=np.float64),
            np.asarray(self.total_preds_word_n_grams, dtype=np.float64),
            np.asarray(self.total_target_char_n_grams, dtype=np.float64),
            np.asarray(self.total_target_word_n_grams, dtype=np.float64),
            np.asarray(self.total_matching_char_n_grams, dtype=np.float64),
            np.asarray(self.total_matching_word_n_grams, dtype=np.float64),
            self.n_char_order,
            self.n_word_order,
            self.n_order,
            self.beta,
            self.lowercase,
            self.whitespace,
            sentence_scores,
        )
        self.total_preds_char_n_grams = jnp.asarray(total_preds_char, dtype=jnp.float32)
        self.total_preds_word_n_grams = jnp.asarray(total_preds_word, dtype=jnp.float32)
        self.total_target_char_n_grams = jnp.asarray(total_target_char, dtype=jnp.float32)
        self.total_target_word_n_grams = jnp.asarray(total_target_word, dtype=jnp.float32)
        self.total_matching_char_n_grams = jnp.asarray(total_matching_char, dtype=jnp.float32)
        self.total_matching_word_n_grams = jnp.asarray(total_matching_word, dtype=jnp.float32)
        if sentence_scores is not None:
            self.sentence_chrf_score.append(jnp.asarray(sentence_scores, dtype=jnp.float32))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        """Corpus chrF over accumulated state."""
        chrf = _chrf_score_compute(
            self.total_preds_char_n_grams,
            self.total_preds_word_n_grams,
            self.total_target_char_n_grams,
            self.total_target_word_n_grams,
            self.total_matching_char_n_grams,
            self.total_matching_word_n_grams,
            self.n_order,
            self.beta,
        )
        if self.return_sentence_level_score:
            return chrf, dim_zero_cat(self.sentence_chrf_score)
        return chrf
