"""Text metrics (stateful modules).

Parity: reference ``src/torchmetrics/text/__init__.py``.
"""

from torchmetrics_tpu.text.bert import BERTScore
from torchmetrics_tpu.text.bleu import BLEUScore, SacreBLEUScore
from torchmetrics_tpu.text.infolm import InfoLM
from torchmetrics_tpu.text.chrf import CHRFScore
from torchmetrics_tpu.text.eed import ExtendedEditDistance
from torchmetrics_tpu.text.error_rates import (
    CharErrorRate,
    EditDistance,
    MatchErrorRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)
from torchmetrics_tpu.text.perplexity import Perplexity
from torchmetrics_tpu.text.rouge import ROUGEScore
from torchmetrics_tpu.text.squad import SQuAD
from torchmetrics_tpu.text.ter import TranslationEditRate

__all__ = [
    "BERTScore",
    "BLEUScore",
    "InfoLM",
    "CharErrorRate",
    "CHRFScore",
    "EditDistance",
    "ExtendedEditDistance",
    "MatchErrorRate",
    "Perplexity",
    "ROUGEScore",
    "SacreBLEUScore",
    "SQuAD",
    "TranslationEditRate",
    "WordErrorRate",
    "WordInfoLost",
    "WordInfoPreserved",
]
