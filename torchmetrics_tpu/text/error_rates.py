"""Edit-distance-based text metric modules: WER, CER, MER, WIL, WIP, EditDistance.

Parity: reference ``src/torchmetrics/text/{wer,cer,mer,wil,wip,edit}.py``.
"""

from __future__ import annotations

from typing import Any, List, Optional, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.cer import _cer_compute, _cer_update
from torchmetrics_tpu.functional.text.edit import _edit_distance_compute, _edit_distance_update
from torchmetrics_tpu.functional.text.mer import _mer_compute, _mer_update
from torchmetrics_tpu.functional.text.wer import _wer_compute, _wer_update
from torchmetrics_tpu.functional.text.wil import _word_info_lost_compute, _word_info_lost_update
from torchmetrics_tpu.functional.text.wip import _wip_compute, _wip_update
from torchmetrics_tpu.text._base import _TextMetric
from torchmetrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class WordErrorRate(_TextMetric):
    r"""Word error rate of transcriptions.

    Example:
        >>> from torchmetrics_tpu.text import WordErrorRate
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> wer = WordErrorRate()
        >>> wer(preds, target)
        Array(0.5, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    errors: Array
    total: Array

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate word-level edit operations and reference words."""
        errors, total = _wer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        """WER over accumulated state."""
        return _wer_compute(self.errors, self.total)


class CharErrorRate(_TextMetric):
    r"""Character error rate of transcriptions.

    Example:
        >>> from torchmetrics_tpu.text import CharErrorRate
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> cer = CharErrorRate()
        >>> cer(preds, target).round(4)
        Array(0.34149998, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    errors: Array
    total: Array

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate character-level edit operations and reference chars."""
        errors, total = _cer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        """CER over accumulated state."""
        return _cer_compute(self.errors, self.total)


class MatchErrorRate(_TextMetric):
    r"""Match error rate of transcriptions.

    Example:
        >>> from torchmetrics_tpu.text import MatchErrorRate
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> mer = MatchErrorRate()
        >>> mer(preds, target).round(4)
        Array(0.44439998, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    errors: Array
    total: Array

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate edit operations and max-length totals."""
        errors, total = _mer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        """MER over accumulated state."""
        return _mer_compute(self.errors, self.total)


class WordInfoLost(_TextMetric):
    r"""Word information lost of transcriptions.

    Example:
        >>> from torchmetrics_tpu.text import WordInfoLost
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> wil = WordInfoLost()
        >>> wil(preds, target).round(4)
        Array(0.65279996, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    errors: Array
    target_total: Array
    preds_total: Array

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("target_total", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("preds_total", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate hit counts and word totals."""
        errors, target_total, preds_total = _word_info_lost_update(preds, target)
        self.errors = self.errors + errors
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def compute(self) -> Array:
        """WIL over accumulated state."""
        return _word_info_lost_compute(self.errors, self.target_total, self.preds_total)


class WordInfoPreserved(_TextMetric):
    r"""Word information preserved of transcriptions.

    Example:
        >>> from torchmetrics_tpu.text import WordInfoPreserved
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> wip = WordInfoPreserved()
        >>> wip(preds, target).round(4)
        Array(0.34719998, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False  # matches the reference metadata (its value, odd as it is)
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    errors: Array
    target_total: Array
    preds_total: Array

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("target_total", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("preds_total", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate hit counts and word totals."""
        errors, target_total, preds_total = _wip_update(preds, target)
        self.errors = self.errors + errors
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def compute(self) -> Array:
        """WIP over accumulated state."""
        return _wip_compute(self.errors, self.target_total, self.preds_total)


class EditDistance(_TextMetric):
    r"""Levenshtein edit distance between text sequences.

    Example:
        >>> from torchmetrics_tpu.text import EditDistance
        >>> metric = EditDistance()
        >>> metric(["rain"], ["shine"])
        Array(3., dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(
        self, substitution_cost: int = 1, reduction: Optional[str] = "mean", **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        if not (isinstance(substitution_cost, int) and substitution_cost >= 0):
            raise ValueError(
                f"Expected argument `substitution_cost` to be a positive integer, but got {substitution_cost}"
            )
        allowed_reduction = (None, "mean", "sum", "none")
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction}, but got {reduction}")
        self.substitution_cost = substitution_cost
        self.reduction = reduction

        if self.reduction == "none" or self.reduction is None:
            self.add_state("edit_scores_list", [], dist_reduce_fx="cat")
        else:
            self.add_state("edit_scores", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")
            self.add_state("num_elements", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate per-sample edit distances (or their sum)."""
        distance = _edit_distance_update(preds, target, self.substitution_cost)
        if self.reduction == "none" or self.reduction is None:
            self.edit_scores_list.append(distance)
        else:
            self.edit_scores = self.edit_scores + distance.sum()
            self.num_elements = self.num_elements + distance.size

    def compute(self) -> Array:
        """Edit distance over accumulated state."""
        if self.reduction == "none" or self.reduction is None:
            return _edit_distance_compute(dim_zero_cat(self.edit_scores_list), 1, self.reduction)
        return _edit_distance_compute(self.edit_scores, self.num_elements, self.reduction)
