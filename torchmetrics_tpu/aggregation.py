"""Generic aggregation metrics with NaN policy.

Parity: reference ``src/torchmetrics/aggregation.py:30-727`` (``BaseAggregator``,
``MaxMetric``, ``MinMetric``, ``SumMetric``, ``MeanMetric``, ``CatMetric``,
``RunningMean``, ``RunningSum``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError
from torchmetrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class BaseAggregator(Metric):
    """Base for simple aggregators over a stream of values.

    ``nan_strategy``: ``'error' | 'warn' | 'ignore' | 'disable' | float`` — float imputes
    NaNs with that value (reference ``aggregation.py:30-103``).
    """

    is_differentiable = None
    higher_is_better = None
    full_state_update: bool = False

    def __init__(
        self,
        fn: Union[Callable, str],
        default_value: Any,
        nan_strategy: Union[str, float] = "error",
        state_name: str = "value",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed = ("error", "warn", "ignore", "disable")
        if not (isinstance(nan_strategy, float) or nan_strategy in allowed):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed} but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        # 'error'/'warn' need a host-side NaN check (a device sync + python raise/warn),
        # which cannot live inside a jitted transition — run those eagerly for parity.
        if self._jit_update_flag is None and nan_strategy in ("error", "warn"):
            self._jit_update_flag = False
        self.add_state(state_name, default=default_value, dist_reduce_fx=fn)
        self.state_name = state_name

    # what NaNs are replaced with under the masking policies — the neutral element of
    # the aggregation (0 for sum/mean with zero weight, ∓inf for max/min)
    _nan_fill: float = 0.0

    def _cast_and_nan_check_input(self, x: Any, weight: Optional[Any] = None):
        """Convert input to float array and apply the NaN policy."""
        x = jnp.asarray(x, dtype=self._dtype) if not isinstance(x, jax.Array) else x.astype(self._dtype)
        if weight is None:
            weight = jnp.ones_like(x)
        weight = (
            jnp.asarray(weight, dtype=self._dtype)
            if not isinstance(weight, jax.Array)
            else weight.astype(self._dtype)
        )
        weight = jnp.broadcast_to(weight, x.shape)

        nans = jnp.isnan(x)
        nans_w = jnp.isnan(weight)
        is_traced = isinstance(x, jax.core.Tracer) or isinstance(weight, jax.core.Tracer)
        any_nan = (
            bool(jnp.any(nans | nans_w)) if (not is_traced and self.nan_strategy in ("error", "warn")) else False
        )
        if self.nan_strategy == "error" and any_nan:
            raise RuntimeError("Encountered `nan` values in tensor")
        if self.nan_strategy == "warn" and any_nan:
            rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
        if self.nan_strategy in ("ignore", "warn"):
            # static-shape masking: NaN entries get the aggregation's neutral element and
            # zero weight instead of dynamic removal (no jit analog of boolean filtering)
            keep = ~(nans | nans_w)
            x = jnp.where(keep, x, self._nan_fill)
            weight = jnp.where(keep, weight, 0.0)
        elif isinstance(self.nan_strategy, float):
            x = jnp.where(nans, self.nan_strategy, x)
            weight = jnp.where(nans_w, self.nan_strategy, weight)
        return x.reshape(-1), weight.reshape(-1)

    def update(self, value: Any) -> None:  # pragma: no cover - overridden
        pass

    def compute(self) -> Array:
        return getattr(self, self.state_name)


class MaxMetric(BaseAggregator):
    """Running maximum (reference ``aggregation.py:106-168``)."""

    full_state_update = True
    higher_is_better = None  # matches the reference (None, not True)
    _nan_fill = -float("inf")

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", -jnp.inf, nan_strategy, state_name="max_value", **kwargs)

    def update(self, value: Any) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        self.max_value = jnp.maximum(self.max_value, jnp.max(value)) if value.size else self.max_value


class MinMetric(BaseAggregator):
    """Running minimum (reference ``aggregation.py:171-233``)."""

    full_state_update = True
    higher_is_better = None  # matches the reference (None, not False)
    _nan_fill = float("inf")

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.inf, nan_strategy, state_name="min_value", **kwargs)

    def update(self, value: Any) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        self.min_value = jnp.minimum(self.min_value, jnp.min(value)) if value.size else self.min_value


class SumMetric(BaseAggregator):
    """Running sum (reference ``aggregation.py:236-298``)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.zeros(()), nan_strategy, state_name="sum_value", **kwargs)

    def update(self, value: Any) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        self.sum_value = self.sum_value + jnp.sum(value)


class CatMetric(BaseAggregator):
    """Concatenate all seen values (reference ``aggregation.py:301-356``).

    With ``capacity`` set, the state is a static-shape :class:`MaskedBuffer` instead of
    a ragged list — updates jit and the state syncs inside ``shard_map`` (SURVEY §7).
    Eager updates drop NaNs exactly like list mode; inside a user's own jit/scan
    dropping would need dynamic shapes, so NaNs follow ``nan_strategy`` value
    replacement there instead.
    """

    def __init__(
        self, nan_strategy: Union[str, float] = "warn", capacity: Optional[int] = None, **kwargs: Any
    ) -> None:
        if capacity is not None:
            from torchmetrics_tpu.core.buffer import MaskedBuffer

            super().__init__("cat", MaskedBuffer.create(capacity), nan_strategy, **kwargs)
            if nan_strategy == "ignore" and kwargs.get("jit_update") is None:
                # keep the public path eager so NaNs are dropped exactly like list
                # mode; pure_update/scan users get the documented imputation
                self._jit_update_flag = False
        else:
            super().__init__("cat", [], nan_strategy, **kwargs)
        self.capacity = capacity

    def update(self, value: Any) -> None:
        value, weight = self._cast_and_nan_check_input(value)
        if self.capacity is not None:
            if self.nan_strategy in ("ignore", "warn") and not isinstance(value, jax.core.Tracer):
                value = value[weight > 0]  # eager: drop NaNs exactly like list mode
            # under jit dropping needs dynamic shapes — NaNs stay imputed instead
            self.value = self.value.append(jnp.ravel(value))
            return
        if self.nan_strategy in ("ignore", "warn") and not isinstance(value, jax.core.Tracer):
            value = value[weight > 0]  # list state updates run eagerly: dynamic filter OK
        if value.size:
            self.value.append(value)

    def compute(self) -> Any:
        if self.capacity is not None:
            if isinstance(self.value.count, jax.core.Tracer):
                return self.value.data  # inside jit: fixed-shape padded view
            return self.value.values()
        if isinstance(self.value, list) and self.value:
            return dim_zero_cat(self.value)
        return self.value


class MeanMetric(BaseAggregator):
    """Weighted running mean (reference ``aggregation.py:359-437``)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.zeros(()), nan_strategy, state_name="mean_value", **kwargs)
        self.add_state("weight", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, value: Any, weight: Any = 1.0) -> None:
        value, weight = self._cast_and_nan_check_input(value, weight)
        self.mean_value = self.mean_value + jnp.sum(value * weight)
        self.weight = self.weight + jnp.sum(weight)

    def compute(self) -> Array:
        return self.mean_value / self.weight


# RunningMean / RunningSum are defined in wrappers/running.py (they subclass Running);
# re-exported here for parity with the reference's `torchmetrics.aggregation` module.
def __getattr__(name: str):
    if name in ("RunningMean", "RunningSum"):
        from torchmetrics_tpu.wrappers.running import RunningMean, RunningSum

        return {"RunningMean": RunningMean, "RunningSum": RunningSum}[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "BaseAggregator",
    "MaxMetric",
    "MinMetric",
    "SumMetric",
    "MeanMetric",
    "CatMetric",
    "RunningMean",
    "RunningSum",
]
