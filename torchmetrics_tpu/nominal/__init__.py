"""Nominal metrics (stateful modules).

Parity: reference ``src/torchmetrics/nominal/__init__.py`` (5 classes).
"""

from torchmetrics_tpu.nominal.modules import (
    CramersV,
    FleissKappa,
    PearsonsContingencyCoefficient,
    TheilsU,
    TschuprowsT,
)

__all__ = [
    "CramersV",
    "FleissKappa",
    "PearsonsContingencyCoefficient",
    "TheilsU",
    "TschuprowsT",
]
