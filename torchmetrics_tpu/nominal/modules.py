"""Nominal metric modules.

Parity: reference ``src/torchmetrics/nominal/{cramers,pearson,tschuprows,theils_u,
fleiss_kappa}.py`` — all accumulate a ``(num_classes, num_classes)`` confusion matrix
(psum-able) except Fleiss' kappa, which stores per-sample count rows ("cat").
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.functional.nominal.association import (
    _cramers_v_compute,
    _fleiss_kappa_compute,
    _fleiss_kappa_update,
    _nominal_confmat_update,
    _pearsons_contingency_coefficient_compute,
    _theils_u_compute,
    _tschuprows_t_compute,
)
from torchmetrics_tpu.functional.nominal.utils import _nominal_input_validation
from torchmetrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class _ConfmatNominalMetric(Metric):
    """Base for nominal statistics over an accumulated contingency table."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    confmat: Array

    def __init__(
        self,
        num_classes: int,
        nan_strategy: str = "replace",
        nan_replace_value: Optional[float] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(num_classes, int) or num_classes < 2:
            raise ValueError(f"Argument `num_classes` is expected to be an integer larger than 1, but got {num_classes}")
        self.num_classes = num_classes
        _nominal_input_validation(nan_strategy, nan_replace_value)
        self.nan_strategy = nan_strategy
        self.nan_replace_value = nan_replace_value
        self.add_state("confmat", jnp.zeros((num_classes, num_classes)), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate the contingency table."""
        confmat = _nominal_confmat_update(
            preds, target, self.num_classes, self.nan_strategy, self.nan_replace_value
        )
        self.confmat = self.confmat + confmat

    def _compute_group_params(self):
        return (self.num_classes, self.nan_strategy, self.nan_replace_value)


class CramersV(_ConfmatNominalMetric):
    r"""Cramer's V statistic of association between two categorical series.

    Example:
        >>> import jax
        >>> from torchmetrics_tpu.nominal import CramersV
        >>> preds = jax.random.randint(jax.random.PRNGKey(42), (100,), 0, 4)
        >>> target = (preds + jax.random.randint(jax.random.PRNGKey(43), (100,), 0, 2)) % 4
        >>> cramers_v = CramersV(num_classes=4)
        >>> float(cramers_v(preds, target)) > 0
        True
    """

    def __init__(self, num_classes: int, bias_correction: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes=num_classes, **kwargs)
        self.bias_correction = bias_correction

    def compute(self) -> Array:
        """Cramer's V over the accumulated table."""
        return _cramers_v_compute(self.confmat, self.bias_correction)


class PearsonsContingencyCoefficient(_ConfmatNominalMetric):
    r"""Pearson's contingency coefficient between two categorical series.

    Example:
        >>> import jax
        >>> from torchmetrics_tpu.nominal import PearsonsContingencyCoefficient
        >>> preds = jax.random.randint(jax.random.PRNGKey(42), (100,), 0, 4)
        >>> target = (preds + jax.random.randint(jax.random.PRNGKey(43), (100,), 0, 2)) % 4
        >>> pcc = PearsonsContingencyCoefficient(num_classes=4)
        >>> float(pcc(preds, target)) > 0
        True
    """

    def compute(self) -> Array:
        """Pearson's C over the accumulated table."""
        return _pearsons_contingency_coefficient_compute(self.confmat)


class TschuprowsT(_ConfmatNominalMetric):
    r"""Tschuprow's T statistic between two categorical series.

    Example:
        >>> import jax
        >>> from torchmetrics_tpu.nominal import TschuprowsT
        >>> preds = jax.random.randint(jax.random.PRNGKey(42), (100,), 0, 4)
        >>> target = (preds + jax.random.randint(jax.random.PRNGKey(43), (100,), 0, 2)) % 4
        >>> tschuprows_t = TschuprowsT(num_classes=4)
        >>> float(tschuprows_t(preds, target)) > 0
        True
    """

    def __init__(self, num_classes: int, bias_correction: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes=num_classes, **kwargs)
        self.bias_correction = bias_correction

    def compute(self) -> Array:
        """Tschuprow's T over the accumulated table."""
        return _tschuprows_t_compute(self.confmat, self.bias_correction)


class TheilsU(_ConfmatNominalMetric):
    r"""Theil's U (uncertainty coefficient) between two categorical series.

    Example:
        >>> import jax
        >>> from torchmetrics_tpu.nominal import TheilsU
        >>> preds = jax.random.randint(jax.random.PRNGKey(42), (100,), 0, 4)
        >>> target = (preds + jax.random.randint(jax.random.PRNGKey(43), (100,), 0, 2)) % 4
        >>> theils_u = TheilsU(num_classes=4)
        >>> float(theils_u(preds, target)) > 0
        True
    """

    def compute(self) -> Array:
        """Theil's U over the accumulated table."""
        return _theils_u_compute(self.confmat)


class FleissKappa(Metric):
    r"""Fleiss' kappa inter-rater agreement.

    Example:
        >>> import jax
        >>> from torchmetrics_tpu.nominal import FleissKappa
        >>> ratings = jax.random.randint(jax.random.PRNGKey(42), (10, 5), 0, 10)
        >>> kappa = FleissKappa(mode='counts')
        >>> float(kappa(ratings)) < 1
        True
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, mode: str = "counts", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if mode not in ["counts", "probs"]:
            raise ValueError("Argument ``mode`` must be one of 'counts' or 'probs'.")
        self.mode = mode
        self.add_state("counts", [], dist_reduce_fx="cat")

    def update(self, ratings: Array) -> None:
        """Store per-sample category counts for the batch."""
        counts = _fleiss_kappa_update(ratings, self.mode)
        self.counts.append(counts)

    def compute(self) -> Array:
        """Fleiss' kappa over all accumulated samples."""
        return _fleiss_kappa_compute(dim_zero_cat(self.counts))
