"""Placement control plane: the WRITE side of the fleet telemetry plane.

:mod:`torchmetrics_tpu.obs.fleet` *observes* — it samples every host, derives
rates and skew, and serves ADVISORY rebalance hints on ``GET /fleet``. This
module *acts* on those observations (which is why it lives beside ``obs/``,
not inside it): a :class:`PlacementController` owns the tenant → host /
mux-session assignment table and closes the loop the hints left open.

Contract with the READ side — the controller **consumes** the installed
:class:`~torchmetrics_tpu.obs.fleet.FleetSampler`'s ``rates()`` / ``skew()`` /
``rebalance_hints()`` and derives **no metrics of its own**. Every scoring
input the controller uses is a number ``GET /fleet`` already serves, so an
operator can always reproduce a placement decision from the public plane.

The pieces:

- **Initial placement** is consistent-hash (rendezvous / highest-random-weight
  over the configured hosts — minimal reshuffling when the host set changes)
  with a load-scored override: when the hash-chosen host is measurably the
  hottest in the fleet, the least-burning host takes the tenant instead.
- **Reconcile loop**: :meth:`PlacementController.tick` is scrape-ticked like
  the fence watchdog and the conservation auditor (cadence-gated, injectable
  clock — wire-free: ``/metrics`` traffic drives it). Measured imbalance is
  compared against a **hysteresis band**: reconciliation engages above
  ``hysteresis_high``, keeps working until the coefficient drops below
  ``hysteresis_low``, and stays idle in between — so a fleet hovering at the
  threshold does not thrash tenants back and forth. At most
  ``max_concurrent_moves`` moves execute per reconcile, each as a full
  drain→checkpoint→restore→replay-tail move through the injected ``mover``
  (the :mod:`torchmetrics_tpu.engine.migrate` machinery — injected, so this
  module stays pure stdlib), each under
  :func:`torchmetrics_tpu.obs.scope.migration` so ``/healthz`` answers
  degraded-not-dead with the moving tenant named.
- **Failover target choice**: :meth:`choose_restore_host` picks the
  least-loaded live host for a fenced tenant — the
  :class:`~torchmetrics_tpu.robust.fence.Watchdog` delegates here when a
  controller is installed, instead of restoring onto whatever directory the
  caller named.
- **Width-bucket tuning**: :meth:`propose_width_buckets` derives a mux
  ``width_buckets`` ladder from the measured tenant population, bounded by
  the existing O(log W) powers-of-two discipline.
- **Durability**: the assignment table is a schema-versioned atomic JSON file
  (:func:`torchmetrics_tpu.utils.fileio.atomic_write_text`), restored on
  construction — a controller restart inherits its placements instead of
  re-hashing the world.

Install the process singleton with :func:`install_controller`; every
``/metrics`` scrape ticks it and refreshes the ``placement.*`` gauge
families, and ``GET /placement`` serves :meth:`PlacementController.report`.
With no controller installed every integration seam is one ``is None``
branch — the disabled path costs nothing.

Pure stdlib; the engine machinery arrives only through the injected mover.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import torchmetrics_tpu.obs.scope as _scope
from torchmetrics_tpu.obs import fleet as _fleet
from torchmetrics_tpu.utils.fileio import atomic_write_text

__all__ = [
    "PLACEMENT_SCHEMA",
    "PlacementConfig",
    "PlacementController",
    "get_controller",
    "install_controller",
]

# durable assignment-table schema: bump on any layout change, and refuse to
# load a mismatched table loudly (the chaos schedule.loads() discipline — a
# half-understood placement table is worse than no table)
PLACEMENT_SCHEMA = 1

DEFAULT_CADENCE_SECONDS = 5.0
# the hysteresis band (normalized imbalance coefficient, [0, 1]): reconcile
# engages above high, disengages below low. The defaults bracket the fleet
# plane's paging threshold (fleet.DEFAULT_IMBALANCE_THRESHOLD = 0.5): moves
# start exactly where the imbalance alert pages, and continue until the fleet
# is measurably comfortable — not merely one hint below the trigger.
DEFAULT_HYSTERESIS_HIGH = 0.5
DEFAULT_HYSTERESIS_LOW = 0.25


@dataclass
class PlacementConfig:
    """Tuning knobs for :class:`PlacementController`.

    Args:
        hosts: the host names placement assigns over (the virtual-host names
            a single-process harness models, or real process indices as
            strings). At least one; order is irrelevant (rendezvous hashing
            is order-free).
        cadence_seconds: min seconds between reconcile passes (``tick``
            honors it — the scrape-tick driver calls far more often).
        hysteresis_high: reconcile engages when measured imbalance exceeds
            this.
        hysteresis_low: reconcile disengages when imbalance drops below this
            (must be < ``hysteresis_high`` — the gap is the anti-thrash
            band).
        max_concurrent_moves: ceiling on moves in flight per reconcile pass —
            a rebalance is a drain+restore per tenant, and a controller that
            moves half the fleet at once IS the incident it exists to
            prevent.
        state_path: durable JSON table location (``None`` disables
            durability — tests, or callers that own persistence).
        decision_log: bounded count of retained reconcile decisions (the
            ``GET /placement`` decision log; oldest dropped).
        smoothing_windows: how many sampler cadences of history the
            controller's rate reads smooth over (``sampler.rates(window=
            smoothing_windows * cadence)``). Adjacent-sample rates are
            twitchy — one quiet tick reads as a rate collapse, crowns the
            wrong hot host, and a controller scoring off that WOULD thrash
            sessions back and forth. Must be >= 1 (1 = adjacent samples).
        pinned: tenants the controller must never move (operator pin — a
            session whose drain/restore is known-unsafe, or one an incident
            response wants frozen in place). Pinned tenants keep their
            assignment and are skipped by the hint loop; everything else
            about them (lookup, report, gauges) is unchanged.
    """

    hosts: Tuple[str, ...] = ()
    cadence_seconds: float = DEFAULT_CADENCE_SECONDS
    hysteresis_high: float = DEFAULT_HYSTERESIS_HIGH
    hysteresis_low: float = DEFAULT_HYSTERESIS_LOW
    max_concurrent_moves: int = 1
    state_path: Optional[str] = None
    decision_log: int = 64
    smoothing_windows: float = 10.0
    pinned: Tuple[str, ...] = ()

    def __post_init__(self) -> None:
        hosts = tuple(str(h) for h in self.hosts)
        if not hosts:
            raise ValueError("Expected at least one host in `hosts`")
        if len(set(hosts)) != len(hosts):
            raise ValueError(f"Expected unique `hosts`, got {self.hosts}")
        self.hosts = hosts
        if self.cadence_seconds <= 0:
            raise ValueError(f"Expected `cadence_seconds` > 0, got {self.cadence_seconds}")
        if not 0.0 < self.hysteresis_high <= 1.0:
            raise ValueError(
                f"Expected `hysteresis_high` in (0, 1], got {self.hysteresis_high}"
            )
        if not 0.0 <= self.hysteresis_low < self.hysteresis_high:
            raise ValueError(
                "Expected 0 <= `hysteresis_low` < `hysteresis_high`, got"
                f" low={self.hysteresis_low} high={self.hysteresis_high}"
            )
        if self.max_concurrent_moves < 1:
            raise ValueError(
                f"Expected `max_concurrent_moves` >= 1, got {self.max_concurrent_moves}"
            )
        if self.decision_log < 1:
            raise ValueError(f"Expected `decision_log` >= 1, got {self.decision_log}")
        if self.smoothing_windows < 1:
            raise ValueError(
                f"Expected `smoothing_windows` >= 1, got {self.smoothing_windows}"
            )
        self.pinned = tuple(str(t) for t in self.pinned)


def _rendezvous_weight(tenant: str, host: str) -> int:
    """Highest-random-weight score of (tenant, host) — stable across runs."""
    digest = hashlib.sha256(f"{tenant}\x00{host}".encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big")


class PlacementController:
    """The tenant → host assignment table plus the loop that keeps it balanced.

    Args:
        config: the :class:`PlacementConfig` knobs.
        sampler: an explicit :class:`~torchmetrics_tpu.obs.fleet.FleetSampler`
            to consume; default resolves the installed process singleton per
            tick (:func:`~torchmetrics_tpu.obs.fleet.get_sampler`). All
            scoring reads this sampler's public tables — the controller never
            derives its own metrics.
        mover: ``mover(tenant, from_host, to_host) -> bool`` executes one
            real drain→checkpoint→restore→replay-tail move (the
            :mod:`~torchmetrics_tpu.engine.migrate` machinery, injected so
            this module stays stdlib-pure). ``None`` degrades moves to
            table-only reassignment — correct for harnesses whose "hosts"
            are the sampler's virtual placement map and nothing physical
            moves.
        clock: monotonic clock (injectable for deterministic tests).
        wall: wall clock for display stamps.
        recorder: where ``placement.*`` gauges land (default: process-global).
    """

    def __init__(
        self,
        config: PlacementConfig,
        sampler: Optional[Any] = None,
        mover: Optional[Callable[[str, str, str], bool]] = None,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        recorder: Optional[Any] = None,
    ) -> None:
        self.config = config
        self.mover = mover
        self._sampler = sampler
        self._clock = clock
        self._wall = wall
        self._recorder = recorder
        self._lock = threading.RLock()
        self._assignments: Dict[str, Dict[str, Any]] = {}
        self._moving: Dict[str, Dict[str, Any]] = {}  # tenant -> in-flight move row
        self._decisions: List[Dict[str, Any]] = []
        self._last_reconcile: Optional[Dict[str, Any]] = None
        self._last_tick_mono: Optional[float] = None
        self.moves_started = 0
        self.moves_completed = 0
        self.moves_failed = 0
        # convergence episode: opens when imbalance crosses above the high
        # threshold, closes when it drops below the low one — the open-to-close
        # wall delta IS the convergence time the SLO judges
        self._episode_start: Optional[float] = None
        self._last_convergence_seconds: Optional[float] = None
        self._episodes_closed = 0
        if config.state_path:
            self._restore_table()

    # ------------------------------------------------------------- durability

    def _restore_table(self) -> None:
        path = self.config.state_path
        assert path is not None
        if not os.path.exists(path):
            return
        with open(path, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
        schema = payload.get("schema")
        if schema != PLACEMENT_SCHEMA:
            raise ValueError(
                f"Placement table {path!r} has schema {schema!r}, this build expects"
                f" {PLACEMENT_SCHEMA} — refusing to half-understand a placement table"
            )
        assignments = payload.get("assignments") or {}
        for tenant, row in assignments.items():
            host = str(row.get("host"))
            if host not in self.config.hosts:
                # a restored assignment onto a host this controller no longer
                # manages is re-placed on first sight, not silently trusted
                continue
            self._assignments[str(tenant)] = {
                "host": host,
                "source": str(row.get("source", "restored")),
                "assigned_unix": float(row.get("assigned_unix", 0.0)),
                "moves": int(row.get("moves", 0)),
            }
        counters = payload.get("counters") or {}
        self.moves_started = int(counters.get("moves_started", 0))
        self.moves_completed = int(counters.get("moves_completed", 0))
        self.moves_failed = int(counters.get("moves_failed", 0))

    def _persist_table(self) -> None:
        path = self.config.state_path
        if not path:
            return
        with self._lock:
            payload = {
                "schema": PLACEMENT_SCHEMA,
                "written_unix": self._wall(),
                "hosts": list(self.config.hosts),
                "assignments": {t: dict(row) for t, row in self._assignments.items()},
                "counters": {
                    "moves_started": self.moves_started,
                    "moves_completed": self.moves_completed,
                    "moves_failed": self.moves_failed,
                },
            }
        atomic_write_text(path, json.dumps(payload, sort_keys=True, indent=1) + "\n")

    # -------------------------------------------------------------- consuming

    def _resolve_sampler(self) -> Optional[Any]:
        return self._sampler if self._sampler is not None else _fleet.get_sampler()

    def _host_loads(self, rates: Optional[Dict[str, Any]] = None) -> Dict[str, float]:
        """Measured per-host burn over the configured hosts, /fleet-sourced.

        Score preference order mirrors what the hints already rank: the
        cost-ledger flop burn when the ledger priced anything this window,
        else the measured update rate. Hosts the sampler has not seen load 0.
        """
        sampler = self._resolve_sampler()
        loads = {host: 0.0 for host in self.config.hosts}
        if sampler is None:
            return loads
        rates = sampler.rates() if rates is None else rates
        hosts = rates.get("hosts") or {}
        use_flops = any(float(row.get("flops_per_second", 0.0) or 0.0) > 0 for row in hosts.values())
        for host, row in hosts.items():
            if host not in loads:
                continue
            loads[host] = float(
                row.get("flops_per_second", 0.0) if use_flops else row.get("updates_per_second", 0.0)
            )
        return loads

    # ------------------------------------------------------------- assignment

    def hash_host(self, tenant: str) -> str:
        """The pure consistent-hash (rendezvous) choice for ``tenant``."""
        return max(self.config.hosts, key=lambda host: (_rendezvous_weight(tenant, host), host))

    def assign(self, tenant: str) -> str:
        """Place ``tenant`` (idempotent): rendezvous hash, load-scored override.

        The override consults only the sampler's measured per-host burn: when
        the hash-chosen host is the fleet's measurably hottest (strictly above
        every alternative), the least-burning host takes the tenant instead —
        a flash crowd must not pile every hash-colliding arrival onto a host
        that is already the skew signal's subject.
        """
        _scope.validate_tenant(tenant)
        with self._lock:
            row = self._assignments.get(tenant)
            if row is not None:
                return row["host"]
        host = self.hash_host(tenant)
        source = "hash"
        if len(self.config.hosts) > 1:
            loads = self._host_loads()
            if any(loads.values()) and loads[host] >= max(loads.values()) and loads[host] > min(loads.values()):
                host = min(self.config.hosts, key=lambda h: (loads[h], h))
                source = "load"
        with self._lock:
            row = self._assignments.get(tenant)
            if row is not None:  # lost a race: first placement wins
                return row["host"]
            self._assignments[tenant] = {
                "host": host,
                "source": source,
                "assigned_unix": self._wall(),
                "moves": 0,
            }
        self._persist_table()
        return host

    def seed(self, assignments: Dict[str, str]) -> None:
        """Adopt a pre-existing placement wholesale (migration-in path).

        A controller brought up over a fleet that already *has* a placement —
        operator-assigned, inherited from a predecessor, or a chaos harness
        modeling a skewed world — must start from that reality, not re-hash
        it: rebalancing is the controller's job, silently shuffling a live
        fleet at startup is not. Every host must be one this controller
        manages (ValueError otherwise — a seed onto an unmanaged host is a
        config mismatch, not an assignment). Seeded rows persist durably like
        any other, and the sampler's placement map is updated so the READ
        side attributes rates to the seeded hosts immediately.
        """
        rows: Dict[str, str] = {}
        for tenant, host in assignments.items():
            _scope.validate_tenant(tenant)
            host = str(host)
            if host not in self.config.hosts:
                raise ValueError(
                    f"Cannot seed tenant {tenant!r} onto unmanaged host {host!r};"
                    f" this controller places over {self.config.hosts}"
                )
            rows[str(tenant)] = host
        sampler = self._resolve_sampler()
        with self._lock:
            for tenant, host in rows.items():
                self._assignments[tenant] = {
                    "host": host,
                    "source": "seed",
                    "assigned_unix": self._wall(),
                    "moves": 0,
                }
        if sampler is not None and getattr(sampler, "placement", None) is not None:
            sampler.placement.update(rows)
        self._persist_table()
        self._decide("seed", tenants=len(rows))

    def lookup(self, tenant: str) -> Optional[str]:
        """The assigned host, or ``None`` for a never-placed tenant."""
        with self._lock:
            row = self._assignments.get(tenant)
            return row["host"] if row is not None else None

    def assignments(self) -> Dict[str, Dict[str, Any]]:
        """The assignment table, copied: ``{tenant: {host, source, ...}}``."""
        with self._lock:
            return {tenant: dict(row) for tenant, row in self._assignments.items()}

    def _reassign(self, tenant: str, host: str, source: str) -> None:
        with self._lock:
            row = self._assignments.setdefault(
                tenant, {"host": host, "source": source, "assigned_unix": self._wall(), "moves": 0}
            )
            row["host"] = host
            row["source"] = source
            row["assigned_unix"] = self._wall()
            row["moves"] = int(row.get("moves", 0)) + 1
        sampler = self._resolve_sampler()
        if sampler is not None and getattr(sampler, "placement", None) is not None:
            # single-process harnesses model hosts through the sampler's
            # static placement map — the move is not real until the READ side
            # attributes the tenant's future rate to its new host
            sampler.placement[tenant] = host
        self._persist_table()

    # -------------------------------------------------------------- failover

    def choose_restore_host(self, tenant: str, exclude: Optional[str] = None) -> str:
        """The restore host for a fenced tenant: least measured burn, live only.

        ``exclude`` (default: the tenant's current assignment — the
        presumed-hung origin) never wins; hosts missing from the newest fleet
        sample are skipped when any live alternative exists. Falls back to
        the rendezvous choice over the eligible set when the fleet plane has
        no rates yet.
        """
        origin = exclude if exclude is not None else self.lookup(tenant)
        candidates = [h for h in self.config.hosts if h != origin] or list(self.config.hosts)
        sampler = self._resolve_sampler()
        if sampler is not None:
            try:
                missing = {str(m) for m in (sampler.history() or [{}])[-1].get("missing_hosts", [])}
            except Exception:
                missing = set()
            live = [h for h in candidates if h not in missing]
            if live:
                candidates = live
            loads = self._host_loads()
            if any(loads.get(h, 0.0) for h in candidates):
                return min(candidates, key=lambda h: (loads.get(h, 0.0), h))
        return max(candidates, key=lambda host: (_rendezvous_weight(tenant, host), host))

    def note_failover(self, tenant: str, host: str) -> None:
        """Record a watchdog-executed failover landing ``tenant`` on ``host``."""
        self._reassign(tenant, host, source="failover")
        self._decide("failover", tenant=tenant, to=host)

    # ------------------------------------------------------------- reconcile

    def _decide(self, action: str, **detail: Any) -> Dict[str, Any]:
        row = {"action": action, "unix": self._wall(), **detail}
        with self._lock:
            self._decisions.append(row)
            del self._decisions[: -self.config.decision_log]
        return row

    def tick(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """One cadence-gated reconcile pass; the scrape-tick driver's entry.

        Returns the reconcile summary when a pass ran, ``None`` when the
        cadence has not elapsed or no sampler is installed (the plane-off
        one-branch path).
        """
        mono = float(now if now is not None else self._clock())
        with self._lock:
            if (
                self._last_tick_mono is not None
                and mono - self._last_tick_mono < self.config.cadence_seconds
            ):
                return None
            self._last_tick_mono = mono
        sampler = self._resolve_sampler()
        if sampler is None:
            return None
        return self.reconcile(now=mono)

    def reconcile(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Compare measured imbalance to the hysteresis band; move if needed.

        Every scoring input is the sampler's: ``rates()`` → ``skew()`` →
        ``rebalance_hints()`` — exactly the tables ``GET /fleet`` serves.
        Moves cap at ``max_concurrent_moves`` per pass; a tenant currently
        migrating or fenced is never moved (the hints already filter both,
        and the executor re-checks — a double drain is state corruption).
        """
        mono = float(now if now is not None else self._clock())
        sampler = self._resolve_sampler()
        summary: Dict[str, Any] = {
            "unix": self._wall(),
            "imbalance": None,
            "engaged": False,
            "moves": [],
        }
        if sampler is None:
            summary["decision"] = "no-sampler"
            with self._lock:
                self._last_reconcile = summary
            return summary
        # smoothed reads: adjacent-sample rates are twitchy (one quiet tick
        # reads as a rate collapse and crowns the wrong hot host), so the
        # controller scores over a few sampler cadences of history — the same
        # public rates() table, wider delta base
        window: Optional[float] = None
        cadence = getattr(sampler, "cadence_seconds", None)
        if cadence:
            window = self.config.smoothing_windows * float(cadence)
        rates = sampler.rates(window=window)
        skew = sampler.skew(rates)
        imbalance = float(skew.get("imbalance") or 0.0)
        summary["imbalance"] = imbalance
        # hysteresis: engage above high; once an episode is open, keep
        # reconciling down to low — the band between is the no-thrash zone
        with self._lock:
            if self._episode_start is None and imbalance > self.config.hysteresis_high:
                self._episode_start = mono
                self._decide("episode-open", imbalance=imbalance)
            engaged = self._episode_start is not None
            if engaged and imbalance < self.config.hysteresis_low:
                self._last_convergence_seconds = mono - self._episode_start
                self._episodes_closed += 1
                self._episode_start = None
                engaged = False
                self._decide(
                    "episode-close",
                    imbalance=imbalance,
                    convergence_seconds=self._last_convergence_seconds,
                )
            in_flight = len(self._moving)
        summary["engaged"] = engaged
        if not engaged:
            summary["decision"] = "balanced"
            with self._lock:
                self._last_reconcile = summary
            return summary
        budget = self.config.max_concurrent_moves - in_flight
        if budget <= 0:
            summary["decision"] = "move-cap"
            with self._lock:
                self._last_reconcile = summary
            return summary
        hints = (sampler.rebalance_hints(rates, skew) or {}).get("hints") or []
        busy = set(_scope.migrating_tenants()) | set(_scope.fenced_tenants())
        moved: List[Dict[str, Any]] = []
        for hint in hints:
            if budget <= 0:
                break
            tenant = str(hint["tenant"])
            if tenant in self.config.pinned:
                continue  # operator pin: never moved, however hot it reads
            if tenant in busy:
                continue  # belt and braces over the hint-side filter
            with self._lock:
                if tenant in self._moving:
                    continue
            to_host = str(hint["to"])
            from_host = self.lookup(tenant) or str(hint["from"])
            if to_host == from_host or to_host not in self.config.hosts:
                continue
            moved.append(self._execute_move(tenant, from_host, to_host, hint))
            budget -= 1
        summary["moves"] = moved
        summary["decision"] = "moved" if moved else "no-eligible-move"
        with self._lock:
            self._last_reconcile = summary
        return summary

    def _execute_move(
        self, tenant: str, from_host: str, to_host: str, hint: Dict[str, Any]
    ) -> Dict[str, Any]:
        """One bounded move: announce, drain+restore via the mover, commit.

        The whole move runs under ``scope.migration(tenant, "rebalance")`` so
        ``/healthz`` names the moving tenant degraded-not-dead for its full
        duration — including the mover's checkpoint/restore, which nests its
        own migration phases (innermost wins in the report, the outer entry
        keeps the window covered edge to edge).
        """
        start = self._clock()
        row = {
            "tenant": tenant,
            "from": from_host,
            "to": to_host,
            "started_unix": self._wall(),
            "projected_imbalance": hint.get("projected_imbalance"),
        }
        with self._lock:
            self.moves_started += 1
            self._moving[tenant] = row
        ok = True
        try:
            with _scope.migration(tenant, "rebalance"):
                if self.mover is not None:
                    ok = bool(self.mover(tenant, from_host, to_host))
        except Exception as err:  # noqa: BLE001 - a failed move must not kill the loop
            ok = False
            row["error"] = f"{type(err).__name__}: {err}"
        finally:
            with self._lock:
                self._moving.pop(tenant, None)
        row["seconds"] = self._clock() - start
        row["ok"] = ok
        if ok:
            self._reassign(tenant, to_host, source="rebalance")
            with self._lock:
                self.moves_completed += 1
        else:
            with self._lock:
                self.moves_failed += 1
        # re-persist AFTER the outcome counters settle: the durable table's
        # counters must cover this move, not lag one write behind it
        self._persist_table()
        self._decide("move", **{k: v for k, v in row.items() if k != "started_unix"})
        return row

    # ------------------------------------------------------------ mux tuning

    def propose_width_buckets(self, max_width: int = 64) -> Tuple[int, ...]:
        """A mux ``width_buckets`` ladder sized to the measured population.

        Powers of two up to the smallest bucket covering the tenant
        population this controller places (live sampler tenants joined with
        the assignment table), capped at ``max_width`` — so a 12-tenant fleet
        compiles a (1,2,4,8,16) ladder instead of padding into a 64-wide
        program, and the ladder length stays O(log W) by construction.
        ``MuxConfig(width_buckets=...)`` validates and tops the ladder.
        """
        if max_width < 1:
            raise ValueError(f"Expected `max_width` >= 1, got {max_width}")
        sampler = self._resolve_sampler()
        population = len(self._assignments)
        if sampler is not None:
            try:
                population = max(population, len(sampler.rates().get("tenants") or {}))
            except Exception:
                pass
        population = max(1, min(int(population), int(max_width)))
        ladder: List[int] = []
        width = 1
        while width < population:
            ladder.append(width)
            width *= 2
        ladder.append(min(width, int(max_width)))
        return tuple(ladder)

    # --------------------------------------------------------------- serving

    def report(self, tenant: Optional[str] = None) -> Dict[str, Any]:
        """The ``GET /placement`` payload: table, moves, decisions, convergence."""
        with self._lock:
            assignments = {t: dict(row) for t, row in self._assignments.items()}
            moving = {t: dict(row) for t, row in self._moving.items()}
            decisions = [dict(row) for row in self._decisions]
            last_reconcile = dict(self._last_reconcile) if self._last_reconcile else None
            episode_open = self._episode_start is not None
            convergence = self._last_convergence_seconds
            episodes_closed = self._episodes_closed
        if tenant is not None:
            assignments = {t: row for t, row in assignments.items() if t == tenant}
            moving = {t: row for t, row in moving.items() if t == tenant}
            decisions = [row for row in decisions if row.get("tenant") == tenant]
        return {
            "schema": PLACEMENT_SCHEMA,
            "config": {
                "hosts": list(self.config.hosts),
                "cadence_seconds": self.config.cadence_seconds,
                "hysteresis_high": self.config.hysteresis_high,
                "hysteresis_low": self.config.hysteresis_low,
                "max_concurrent_moves": self.config.max_concurrent_moves,
                "smoothing_windows": self.config.smoothing_windows,
                "pinned": list(self.config.pinned),
                "durable": bool(self.config.state_path),
            },
            "assignments": assignments,
            "moving": moving,
            "decisions": decisions,
            "moves": {
                "started": self.moves_started,
                "completed": self.moves_completed,
                "failed": self.moves_failed,
                "in_flight": len(moving),
            },
            "convergence": {
                "episode_open": episode_open,
                "episodes_closed": episodes_closed,
                "last_convergence_seconds": convergence,
            },
            "last_reconcile": last_reconcile,
        }

    def record_gauges(
        self, recorder: Optional[Any] = None, now: Optional[float] = None
    ) -> Dict[str, Any]:
        """Write the ``placement.*`` gauge families into the recorder.

        All point-in-time controller state, so every family is a gauge —
        never ``_total``. Per-host assignment counts carry the ``host``
        label; everything else is unlabeled (``tenant=None`` opts out of
        ambient scope tagging, the fleet-gauge discipline).
        """
        import torchmetrics_tpu.obs.trace as trace  # lazy: placement stays cycle-free

        rec = recorder if recorder is not None else (self._recorder or trace.get_recorder())
        mono = float(now if now is not None else self._clock())
        with self._lock:
            per_host: Dict[str, int] = {host: 0 for host in self.config.hosts}
            for row in self._assignments.values():
                per_host[row["host"]] = per_host.get(row["host"], 0) + 1
            n_assignments = len(self._assignments)
            in_flight = len(self._moving)
            convergence = self._last_convergence_seconds
            episode_open = self._episode_start is not None
            decision_age = (
                None
                if not self._decisions
                else max(0.0, self._wall() - float(self._decisions[-1]["unix"]))
            )
        rec.set_gauge("placement.assignments", float(n_assignments), tenant=None)
        for host, count in per_host.items():
            rec.set_gauge("placement.host_tenants", float(count), host=host, tenant=None)
        rec.set_gauge("placement.moves_in_flight", float(in_flight), tenant=None)
        rec.set_gauge("placement.moves_started", float(self.moves_started), tenant=None)
        rec.set_gauge("placement.moves_completed", float(self.moves_completed), tenant=None)
        rec.set_gauge("placement.moves_failed", float(self.moves_failed), tenant=None)
        rec.set_gauge("placement.rebalancing", 1.0 if episode_open else 0.0, tenant=None)
        if convergence is not None:
            rec.set_gauge("placement.convergence_seconds", float(convergence), tenant=None)
        if decision_age is not None:
            rec.set_gauge("placement.decision_age_seconds", float(decision_age), tenant=None)
        return {
            "assignments": n_assignments,
            "in_flight": in_flight,
            "mono": mono,
        }


# ------------------------------------------------------------ module singleton

# the process singleton the /metrics render chain ticks and /placement serves —
# the obs.fleet.install_sampler pattern exactly
_CONTROLLER: Optional[PlacementController] = None


def install_controller(
    controller: Optional[PlacementController],
) -> Optional[PlacementController]:
    """Install (or clear, with ``None``) the process-wide placement controller.

    Returns the previous singleton so callers can restore it (test hygiene).
    """
    global _CONTROLLER
    previous = _CONTROLLER
    _CONTROLLER = controller
    return previous


def get_controller() -> Optional[PlacementController]:
    """The installed placement controller, or ``None`` (placement is static)."""
    return _CONTROLLER
