"""Fleet control plane: placement, load-scored rebalancing, failover targets.

The WRITE side of the fleet story. :mod:`torchmetrics_tpu.obs.fleet`
observes (continuous sampling, rates, skew, advisory hints on ``GET
/fleet``); this package acts — the :class:`PlacementController` owns the
tenant → host assignment table, reconciles measured imbalance against a
hysteresis band with bounded drain→checkpoint→restore moves, chooses
failover targets for the fence watchdog, and proposes mux width-bucket
ladders from the measured tenant population. It consumes only the ``/fleet``
plane's tables and never derives metrics of its own.

Pure stdlib (engine machinery arrives via the injected mover callback).
"""

from torchmetrics_tpu.fleet.placement import (
    PLACEMENT_SCHEMA,
    PlacementConfig,
    PlacementController,
    get_controller,
    install_controller,
)

__all__ = [
    "PLACEMENT_SCHEMA",
    "PlacementConfig",
    "PlacementController",
    "get_controller",
    "install_controller",
]
