"""State-memory accounting: what a metric's accumulated state costs, in bytes.

On TPU the scarce resource is HBM, and a metrics runtime accumulates state
*silently* — a ``MaskedBuffer`` preallocates its full capacity at construction,
``compute_on_cpu`` list states grow one host array per update with no bound,
and the wrappers (``MetricTracker``/``Running``/``BootStrapper``) keep hidden
extra copies of the base metric's state. None of that was visible anywhere.
This module closes the gap with three layers:

- :func:`footprint` — walk one metric's state registry (the live
  ``_state_values`` pytree declared through ``add_state``) summing per-leaf
  ``nbytes`` with shape/dtype, classifying each state as a **device array**
  (jax), **host array** (numpy), **ragged list** (per-item bytes + item
  count), or **MaskedBuffer** (capacity bytes vs fill bytes, so a
  preallocated-but-empty buffer is visible). Rollups recurse through
  ``MetricCollection`` and the wrappers via the ``_memory_children`` hook, and
  hidden copies (the sync cache, quarantined host batches, host-side reset
  defaults) are accounted explicitly. Aliased arrays (compute-group members
  share their leader's immutable state) are deduplicated by object identity:
  ``total_bytes`` counts every reference, ``unique_bytes`` counts every
  distinct buffer.
- :func:`device_memory_stats` — guarded polling of jax
  ``device.memory_stats()`` (``bytes_in_use`` / ``peak_bytes_in_use``).
  CPU backends don't implement it → clean skip (empty dict); jax never
  imported → clean skip; a backend is never first-touch-initialized by
  accounting.
- :func:`record_gauges` — write the footprint totals and device stats as
  gauges into the :class:`~torchmetrics_tpu.obs.trace.TraceRecorder`
  (``memory.*`` / ``state.*`` families), so Prometheus text, snapshots,
  cross-host aggregation and Perfetto counter tracks all pick them up with no
  further wiring. Unlike the hot-path instrumentation this writes regardless
  of ``trace.ENABLED`` — an explicit accounting call *is* the intent — while
  costing the runtime nothing when never called.

Pure stdlib at import time (like the rest of ``obs``): numpy/jax are consulted
lazily, and only when the objects being measured already forced them in.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Iterable, List, Optional, Tuple

import torchmetrics_tpu.obs.trace as trace

__all__ = [
    "device_memory_stats",
    "footprint",
    "format_bytes",
    "peak_device_bytes",
    "record_gauges",
    "report",
    "state_rows",
]

# per-leaf classification kinds (the four state kinds plus bookkeeping)
KIND_DEVICE = "device_array"
KIND_HOST = "host_array"
KIND_LIST = "list_state"
KIND_BUFFER = "masked_buffer"
KIND_OTHER = "other"


def _modules():
    """(jax, numpy, MaskedBuffer) — whichever are already importable.

    Measuring a metric means jax is live anyway; the lazy probe only keeps
    ``import torchmetrics_tpu.obs`` free of jax/numpy (the trace-module
    contract).
    """
    jax_mod = sys.modules.get("jax")
    np_mod = sys.modules.get("numpy")
    buffer_cls = None
    if jax_mod is not None:
        try:
            from torchmetrics_tpu.core.buffer import MaskedBuffer as buffer_cls
        except Exception:  # pragma: no cover - partial installs
            buffer_cls = None
    return jax_mod, np_mod, buffer_cls


def _array_nbytes(value: Any) -> int:
    """Byte size of an array-like from shape/dtype — never touches device data."""
    nbytes = getattr(value, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    size = getattr(value, "size", None)
    itemsize = getattr(getattr(value, "dtype", None), "itemsize", None)
    if size is not None and itemsize is not None:
        return int(size) * int(itemsize)
    return 0


def _classify_array(value: Any) -> Optional[str]:
    jax_mod, np_mod, _ = _modules()
    if jax_mod is not None and isinstance(value, jax_mod.Array):
        return KIND_DEVICE
    if np_mod is not None and isinstance(value, np_mod.ndarray):
        return KIND_HOST
    return None


def _leaf_row(value: Any) -> Dict[str, Any]:
    """One classified row for a single state value (not recursing children)."""
    _, _, buffer_cls = _modules()
    if buffer_cls is not None and isinstance(value, buffer_cls):
        data_bytes = _array_nbytes(value.data)
        count_bytes = _array_nbytes(value.count)
        item_bytes = data_bytes // value.capacity if value.capacity else 0
        fill_items = None
        fill_bytes = None
        try:
            # the count is a tiny device scalar; reading it blocks async
            # dispatch for one scalar transfer — acceptable for an explicit
            # accounting call, skipped under tracing (abstract count)
            import jax as _jax

            if not isinstance(value.count, _jax.core.Tracer):
                fill_items = int(value.count)
                fill_bytes = min(fill_items, value.capacity) * item_bytes
        except Exception:  # pragma: no cover - defensive
            pass
        return {
            "kind": KIND_BUFFER,
            "nbytes": data_bytes + count_bytes,
            "capacity": value.capacity,
            "capacity_bytes": data_bytes,
            "fill_items": fill_items,
            "fill_bytes": fill_bytes,
            "shape": tuple(value.data.shape),
            "dtype": str(value.data.dtype),
        }
    if isinstance(value, list):
        item_bytes = 0
        device_items = 0
        host_items = 0
        for item in value:
            item_bytes += _array_nbytes(item)
            kind = _classify_array(item)
            if kind == KIND_DEVICE:
                device_items += 1
            elif kind == KIND_HOST:
                host_items += 1
        return {
            "kind": KIND_LIST,
            "nbytes": item_bytes,
            "items": len(value),
            "device_items": device_items,
            "host_items": host_items,
        }
    kind = _classify_array(value)
    if kind is not None:
        return {
            "kind": kind,
            "nbytes": _array_nbytes(value),
            "shape": tuple(value.shape),
            "dtype": str(value.dtype),
        }
    return {"kind": KIND_OTHER, "nbytes": int(sys.getsizeof(value, 0))}


def _leaf_buffer_parts(value: Any) -> List[Tuple[int, int]]:
    """``(identity, nbytes)`` per distinct array buffer behind one state value.

    Compute-group members hold *references* to their leader's immutable state
    arrays; the rollup dedups on these ids so an aliased collection is not
    double-billed.
    """
    _, _, buffer_cls = _modules()
    if buffer_cls is not None and isinstance(value, buffer_cls):
        return [(id(value.data), _array_nbytes(value.data)), (id(value.count), _array_nbytes(value.count))]
    if isinstance(value, list):
        return [(id(item), _array_nbytes(item)) for item in value]
    nbytes = _array_nbytes(value)
    if nbytes == 0 and getattr(value, "dtype", None) is None:
        nbytes = int(sys.getsizeof(value, 0))
    return [(id(value), nbytes)]


def state_rows(metric: Any) -> List[Dict[str, Any]]:
    """Per-state classified rows for one metric (live states + hidden copies).

    Hidden copies accounted beyond the registered states: the eager-sync cache
    (``_cache`` holds the pre-sync local state while synced), quarantined host
    batches retained under the ``quarantine`` error policy, and the host-side
    reset defaults kept by ``add_state``.
    """
    rows: List[Dict[str, Any]] = []
    state_values = getattr(metric, "_state_values", None)
    if isinstance(state_values, dict):
        for name, value in state_values.items():
            rows.append({"state": name, **_leaf_row(value), "parts": _leaf_buffer_parts(value)})
    cache = getattr(metric, "_cache", None)
    if isinstance(cache, dict):
        for name, value in cache.items():
            rows.append(
                {"state": f"__sync_cache__.{name}", **_leaf_row(value), "parts": _leaf_buffer_parts(value)}
            )
    quarantine = getattr(metric, "_quarantine", None)
    if isinstance(quarantine, list) and quarantine:
        nbytes = 0
        for batch in quarantine:
            for part in (batch.get("args", ()), tuple(batch.get("kwargs", {}).values())):
                for leaf in _flatten_batch(part):
                    nbytes += _array_nbytes(leaf)
        rows.append(
            {
                "state": "__quarantine__",
                "kind": KIND_HOST,
                "nbytes": nbytes,
                "items": len(quarantine),
                "parts": [(id(quarantine), nbytes)],
            }
        )
    defaults = getattr(metric, "_defaults", None)
    if isinstance(defaults, dict):
        nbytes = sum(
            _array_nbytes(value)
            for value in defaults.values()
            if _classify_array(value) is not None
        )
        if nbytes:
            rows.append(
                {"state": "__defaults__", "kind": KIND_HOST, "nbytes": nbytes, "parts": [(id(defaults), nbytes)]}
            )
    return rows


def _flatten_batch(value: Any):
    if isinstance(value, (list, tuple)):
        for item in value:
            yield from _flatten_batch(item)
    elif isinstance(value, dict):
        for item in value.values():
            yield from _flatten_batch(item)
    else:
        yield value


def _children_of(obj: Any) -> List[Tuple[str, Any]]:
    hook = getattr(obj, "_memory_children", None)
    if callable(hook):
        try:
            return list(hook())
        except Exception:  # pragma: no cover - defensive: accounting never raises
            return []
    return []


def footprint(obj: Any, _seen: Optional[set] = None) -> Dict[str, Any]:
    """Full recursive state-memory footprint of a metric / collection / wrapper.

    Returns a plain JSON-able dict::

        {"name", "total_bytes", "unique_bytes", "device_bytes", "host_bytes",
         "list_items", "n_states", "states": [...], "children": [...]}

    ``total_bytes`` counts every state reference including aliased
    compute-group members; ``unique_bytes`` deduplicates shared buffers by
    object identity and is the number that corresponds to real memory.
    ``device_bytes``/``host_bytes`` split the *unique* total by residency
    (MaskedBuffer capacity counts as device).
    """
    if _seen is None:
        _seen = set()
    out: Dict[str, Any] = {
        "name": type(obj).__name__,
        "total_bytes": 0,
        "unique_bytes": 0,
        "device_bytes": 0,
        "host_bytes": 0,
        "list_items": 0,
        "n_states": 0,
        "states": [],
        "children": [],
    }
    if id(obj) in _seen:  # cycle / shared child: count once
        out["aliased"] = True
        return out
    _seen.add(id(obj))

    for row in state_rows(obj):
        parts = row.pop("parts", [])
        out["n_states"] += 1
        out["total_bytes"] += row["nbytes"]
        row["unique_bytes"] = sum(nbytes for ident, nbytes in parts if ident not in _seen)
        _seen.update(ident for ident, _ in parts)
        if row["kind"] == KIND_LIST:
            out["list_items"] += row["items"]
        out["unique_bytes"] += row["unique_bytes"]
        if row["kind"] in (KIND_DEVICE, KIND_BUFFER):
            out["device_bytes"] += row["unique_bytes"]
        elif row["kind"] == KIND_LIST:
            # split by residency of the items (device pre-move, host after
            # compute_on_cpu); mixed lists attribute proportionally by count
            if row["items"]:
                device_frac = row["device_items"] / row["items"]
            else:
                device_frac = 0.0
            out["device_bytes"] += int(row["unique_bytes"] * device_frac)
            out["host_bytes"] += row["unique_bytes"] - int(row["unique_bytes"] * device_frac)
        else:
            out["host_bytes"] += row["unique_bytes"]
        out["states"].append(row)

    for label, child in _children_of(obj):
        sub = footprint(child, _seen)
        sub["label"] = label
        out["children"].append(sub)
        for key in ("total_bytes", "unique_bytes", "device_bytes", "host_bytes", "list_items", "n_states"):
            out[key] += sub[key]
    return out


# ------------------------------------------------------------- device polling


# one-shot marker: the initialized-backend probe uses a private jax attribute
# (the only way to ask "is a backend live" without first-touch-initializing
# one); if a jax upgrade moves it, say so ONCE instead of silently reporting
# no device memory forever
_PROBE_BROKEN_WARNED = False


def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """Per-device ``memory_stats()`` — ``{device: {bytes_in_use, peak_bytes_in_use, ...}}``.

    Guarded three ways: jax never imported → ``{}``; no backend initialized
    yet → ``{}`` (accounting must never be the thing that first-touch-inits a
    wedged TPU tunnel, same contract as ``trace._host_meta``); the backend
    doesn't implement ``memory_stats`` (CPU) → ``{}``. A jax version where the
    backend probe itself is unavailable also returns ``{}``, but warns once —
    that degradation must be distinguishable from "CPU, nothing to report".
    """
    global _PROBE_BROKEN_WARNED
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return {}
    try:
        from jax._src import xla_bridge as _xla_bridge

        backends = getattr(_xla_bridge, "_backends", None)
    except Exception:
        backends = None
    if backends is None:  # private-API drift, NOT "no backend yet"
        if not _PROBE_BROKEN_WARNED:
            _PROBE_BROKEN_WARNED = True
            import warnings

            warnings.warn(
                "torchmetrics_tpu.obs.memory cannot determine whether a jax backend is"
                " initialized on this jax version (jax._src.xla_bridge._backends moved);"
                " device memory stats are disabled. State-footprint accounting is"
                " unaffected.",
                RuntimeWarning,
                stacklevel=2,
            )
        return {}
    if not backends:  # probe works; no backend initialized yet — clean skip
        return {}
    try:
        devices = jax_mod.devices()
    except Exception:
        return {}
    out: Dict[str, Dict[str, int]] = {}
    for device in devices:
        try:
            stats = device.memory_stats()
        except Exception:
            continue
        if not isinstance(stats, dict):
            continue  # CPU backends return None: clean skip
        row = {
            key: int(stats[key])
            for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
            if isinstance(stats.get(key), (int, float))
        }
        if row:
            out[str(device)] = row
    return out


def peak_device_bytes() -> Optional[int]:
    """Max ``peak_bytes_in_use`` across devices, or ``None`` when unavailable."""
    peaks = [
        stats["peak_bytes_in_use"]
        for stats in device_memory_stats().values()
        if "peak_bytes_in_use" in stats
    ]
    return max(peaks) if peaks else None


# ------------------------------------------------------------------- gauges


def record_gauges(
    metrics: Iterable[Any] = (),
    recorder: Optional[trace.TraceRecorder] = None,
    include_device: bool = True,
) -> Dict[str, Any]:
    """Record footprint + device-memory gauges into the recorder; returns them.

    Families (dots become underscores under the ``tm_tpu_`` Prometheus
    prefix):

    - ``memory.state_bytes{metric,inst}`` — unique accumulated state bytes
      per top-level metric (wrapper/collection children included in the
      owner's number);
    - ``memory.state_device_bytes`` / ``memory.state_host_bytes`` — residency
      split, same labels;
    - ``state.list_items{metric,inst}`` — total ragged list items held (same
      label scheme as the hot-path gauge the eager update records);
    - ``memory.device_bytes_in_use{device}`` /
      ``memory.device_peak_bytes_in_use{device}`` — backend ``memory_stats``
      when the platform reports them.

    ``inst`` is the metric's per-process construction ordinal (stable across
    registration changes — unregistering one metric never shifts another's
    series onto a stale label, and two same-class metrics never collide), with
    a registry-position fallback ``r<i>`` for containers that carry no
    ordinal.

    Writes go straight to the recorder (NOT gated on ``trace.ENABLED``): an
    explicit accounting call is its own opt-in, and the /metrics endpoint must
    show memory series even when span tracing is off. Hot paths never call
    this.
    """
    rec = recorder if recorder is not None else trace.get_recorder()
    out: Dict[str, Any] = {"metrics": [], "devices": {}}
    for index, metric in enumerate(metrics):
        fp = footprint(metric)
        inst = getattr(metric, "_obs_instance", None) or f"r{index}"
        labels = {"metric": fp["name"], "inst": str(inst)}
        tenant = getattr(metric, "_obs_tenant", None)
        if tenant:
            # tenant attribution (obs/scope.py): a metric registered under a
            # tenant bills its state bytes to that tenant's label
            labels["tenant"] = str(tenant)
            fp["tenant"] = str(tenant)
        else:
            # explicit opt-out (scope.tag strips None): an accounting call made
            # inside someone's scope must not mis-bill an untenanted metric
            labels["tenant"] = None
        rec.set_gauge("memory.state_bytes", float(fp["unique_bytes"]), **labels)
        rec.set_gauge("memory.state_device_bytes", float(fp["device_bytes"]), **labels)
        rec.set_gauge("memory.state_host_bytes", float(fp["host_bytes"]), **labels)
        rec.set_gauge("state.list_items", float(fp["list_items"]), **labels)
        out["metrics"].append({**labels, "footprint": fp})
    if include_device:
        stats = device_memory_stats()
        for device, row in stats.items():
            if "bytes_in_use" in row:
                rec.set_gauge("memory.device_bytes_in_use", float(row["bytes_in_use"]), device=device)
            if "peak_bytes_in_use" in row:
                rec.set_gauge(
                    "memory.device_peak_bytes_in_use", float(row["peak_bytes_in_use"]), device=device
                )
        out["devices"] = stats
    return out


# ------------------------------------------------------------------- report


def format_bytes(n: Optional[float]) -> str:
    """Human-readable byte count (binary units)."""
    if n is None:
        return "?"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GiB"  # pragma: no cover - unreachable


def report(metrics: Iterable[Any] = (), top_k: int = 20, tenant: Optional[str] = None) -> Dict[str, Any]:
    """Top-K footprint report — the payload behind ``GET /memory``.

    Per-metric footprints sorted by ``unique_bytes`` (largest first), each
    metric's state rows likewise sorted and truncated to ``top_k``, plus
    fleet-relevant totals and the guarded device stats. ``tenant`` narrows the
    report to metrics registered under that tenant (the ``?tenant=`` view).
    """
    rows = []
    for index, metric in enumerate(metrics):
        metric_tenant = getattr(metric, "_obs_tenant", None)
        if tenant is not None and metric_tenant != tenant:
            continue
        fp = footprint(metric)
        fp["instance"] = index
        if metric_tenant:
            fp["tenant"] = str(metric_tenant)
        fp["states"] = sorted(fp["states"], key=lambda r: -r["nbytes"])[: max(0, top_k)]
        rows.append(fp)
    rows.sort(key=lambda fp: -fp["unique_bytes"])
    totals = {
        key: sum(fp[key] for fp in rows)
        for key in ("total_bytes", "unique_bytes", "device_bytes", "host_bytes", "list_items")
    }
    out = {
        "metrics": rows[: max(0, top_k)],
        "n_metrics": len(rows),
        "totals": totals,
        "totals_human": {k: format_bytes(v) for k, v in totals.items() if k != "list_items"},
        "device_memory_stats": device_memory_stats(),
    }
    if tenant is not None:
        out["tenant_filter"] = tenant
    return out
