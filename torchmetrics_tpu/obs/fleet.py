"""Fleet telemetry plane: continuous cross-host sampling, rates, skew.

:mod:`~torchmetrics_tpu.obs.aggregate` merges host snapshots *on demand*, and
everything it reports is a lifetime counter — no rates, no history, no trend.
This module closes that gap with a :class:`FleetSampler` that

- periodically gathers every host's snapshot over the guarded collective seam
  (:func:`~torchmetrics_tpu.obs.aggregate.gather_snapshots` under the
  configured ``robust.sync_guard`` — a hung host yields a LOUD degraded
  sample with ``missing_hosts``, never a stall),
- retains a bounded drop-oldest ring of compact timestamped samples
  (:func:`~torchmetrics_tpu.obs.aggregate.fleet_sample`), and
- derives what lifetime counters cannot give: per-tenant and per-host
  **rates** (updates/sec, computes/sec, cost-ledger flop/byte burn per
  second, checkpoint-bytes/sec) and **skew signals** (per-host load share,
  max/min host ratio, a normalized imbalance coefficient, the top-K hottest
  tenants per host), exported as ``fleet.*`` gauges through the ordinary
  recorder → Prometheus/snapshot/Perfetto path.

Driving the sampler follows the fence-watchdog pattern exactly: install the
process singleton with :func:`install_sampler` and every ``/metrics`` scrape
ticks it (:meth:`FleetSampler.tick` respects the cadence), or call
:meth:`FleetSampler.start` for a background daemon thread, or call
:meth:`FleetSampler.sample` yourself with an injectable clock for
deterministic tests. :func:`imbalance_rule` is the declarative AlertRule
preset over the ``fleet.imbalance`` gauge, so sustained skew fires through
the standard pending→firing machinery and flips ``/healthz``
degraded-not-dead (the server joins the hot host's name into the reason).

Rates come from **consecutive-sample deltas**, not lifetime counters: a
counter that has been climbing for six hours says nothing about what is
burning *now*, and a restarted host's counter reset would read as negative
burn — deltas are clamped at zero instead. The derivation window is
therefore exactly the sampling cadence (PERF.md, "Rate-derivation & skew
methodology").

Single-process worlds sample the local snapshot with no collective. For
single-process harnesses that *model* a fleet (the chaos ``skewed_load``
scenario), ``placement=`` maps tenants onto virtual hosts so per-host shares
and skew derive from the measured per-tenant rates under that placement.

Pure stdlib; all JAX touching stays behind the aggregate seam's lazy imports.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

import torchmetrics_tpu.obs.trace as trace
from torchmetrics_tpu.obs import aggregate as _aggregate
from torchmetrics_tpu.obs.alerts import AlertRule

__all__ = [
    "DEFAULT_CADENCE_SECONDS",
    "DEFAULT_IMBALANCE_THRESHOLD",
    "FleetSampler",
    "get_sampler",
    "imbalance_rule",
    "install_sampler",
]

DEFAULT_CADENCE_SECONDS = 5.0
DEFAULT_RING = 256
DEFAULT_TOP_K = 3
# imbalance is normalized to [0, 1]: 0 = every host carries an equal share,
# 1 = one host carries everything. 0.5 ≈ the hottest host carrying half the
# fleet's headroom above its fair share — sustained, that is a paging signal.
DEFAULT_IMBALANCE_THRESHOLD = 0.5


def imbalance_rule(
    above: float = DEFAULT_IMBALANCE_THRESHOLD,
    for_seconds: float = 2.0,
    severity: str = "page",
) -> AlertRule:
    """The declarative sustained-skew watchdog over ``fleet.imbalance``.

    A plain threshold rule: the normalized imbalance coefficient staying
    ``above`` the limit for ``for_seconds`` walks pending→firing through the
    standard machinery, flips ``/healthz`` degraded-not-dead, and resolves
    itself when the fleet rebalances. Install it like any other rule
    (``alerts.configure(fleet.imbalance_rule(), ...)``).
    """
    return AlertRule(
        name="fleet_imbalance",
        kind="threshold",
        series="fleet.imbalance",
        above=float(above),
        for_seconds=float(for_seconds),
        severity=severity,
    )


class FleetSampler:
    """Continuous cross-host sampling with a bounded drop-oldest sample ring.

    Args:
        cadence_seconds: target seconds between samples (``tick`` honors it;
            the daemon thread sleeps it).
        ring: sample-ring capacity; the oldest sample drops when full.
        top_k: hottest tenants listed per host in the skew block.
        recorder: the :class:`~torchmetrics_tpu.obs.trace.TraceRecorder` the
            ``fleet.*`` gauges land in (default: the process-global one).
        placement: optional ``{tenant: host_name}`` map for single-process
            harnesses modeling a fleet — per-host shares and skew then group
            measured per-tenant rates by this static placement instead of by
            real process indices.
        hosts: optional explicit host universe. Rate tables only contain
            hosts that carried load, so a fully idle provisioned host is
            invisible to them — and a fleet concentrated on one host would
            read as a single-host fleet with nothing to balance. Naming the
            provisioned hosts pads :meth:`skew` (and therefore
            :meth:`rebalance_hints` and the ``fleet.imbalance`` gauge) with
            zero-load entries for the idle ones, so concentration on one of
            two provisioned hosts reads as imbalance 1.0, not 0.0.
        clock: monotonic clock rate deltas divide by (injectable).
        wall: wall clock for display stamps (injectable).
    """

    def __init__(
        self,
        cadence_seconds: float = DEFAULT_CADENCE_SECONDS,
        ring: int = DEFAULT_RING,
        top_k: int = DEFAULT_TOP_K,
        recorder: Optional[trace.TraceRecorder] = None,
        placement: Optional[Mapping[str, str]] = None,
        hosts: Optional[Sequence[str]] = None,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        description: str = "fleet sample",
    ) -> None:
        if cadence_seconds <= 0:
            raise ValueError(f"Expected `cadence_seconds` > 0, got {cadence_seconds}")
        if ring < 2:
            raise ValueError(f"Expected `ring` >= 2 (rates need two samples), got {ring}")
        self.cadence_seconds = float(cadence_seconds)
        self.top_k = max(1, int(top_k))
        self.placement = dict(placement) if placement else None
        self.hosts = tuple(dict.fromkeys(str(h) for h in hosts)) if hosts else None
        self.description = description
        self._recorder = recorder
        self._clock = clock
        self._wall = wall
        self._ring: deque = deque(maxlen=int(ring))
        self._lock = threading.RLock()
        # one gather (a collective!) in flight at a time: concurrent scrape
        # ticks must coalesce, not pile collectives onto a wedged guard
        self._gather_lock = threading.Lock()
        self._last_merged: Optional[Dict[str, Any]] = None
        self._samples_taken = 0
        self._degraded_samples = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- sampling

    def _rec(self) -> trace.TraceRecorder:
        return self._recorder if self._recorder is not None else trace.get_recorder()

    def sample(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Take one fleet sample NOW: gather, merge, derive, export gauges.

        In a multi-host world this is a collective — every rank must call it
        (the scrape-tick and daemon-thread drivers are per-process, so each
        rank's own driver supplies its side). A hung peer degrades the sample
        loudly (``degraded=True`` + ``missing_hosts``) under the configured
        ``sync_guard`` instead of stalling. Returns the appended sample.
        """
        rec = self._rec()
        # refresh the burn numerators this host contributes before the gather:
        # the cost ledger's cumulative flop/byte estimates live as gauges only
        # after an explicit record_gauges (scrape-time refresh pattern)
        from torchmetrics_tpu.obs import cost as _cost

        _cost.record_gauges(recorder=rec)
        with self._gather_lock:
            merged = _aggregate.aggregate(
                recorder=rec, include_events=False, description=self.description
            )
        mono = float(now if now is not None else self._clock())
        sample = _aggregate.fleet_sample(merged, unix=self._wall(), mono=mono)
        with self._lock:
            self._ring.append(sample)
            self._last_merged = merged
            self._samples_taken += 1
            if sample["degraded"]:
                self._degraded_samples += 1
        self.record_gauges(recorder=rec, now=mono)
        return sample

    def tick(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Sample iff the cadence elapsed since the newest sample.

        The synchronous driver: wire it into the ``/metrics`` render chain
        (the fence-watchdog pattern) and scrape traffic keeps the ring warm
        with no thread at all. Returns the new sample, or ``None`` when the
        cadence has not elapsed or another gather is already in flight.
        """
        mono = float(now if now is not None else self._clock())
        with self._lock:
            if self._ring and mono - self._ring[-1]["mono"] < self.cadence_seconds:
                return None
        if self._gather_lock.locked():
            return None  # a concurrent scrape is already mid-gather
        return self.sample(now=mono)

    def start(self) -> "FleetSampler":
        """Start the background daemon sampling thread (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="tm-tpu-fleet-sampler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the daemon thread (no-op when never started)."""
        thread = self._thread
        self._stop.set()
        if thread is not None:
            thread.join(timeout=timeout)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample()
            except Exception:  # noqa: BLE001 - the sampler must outlive one bad tick
                with self._lock:
                    self._degraded_samples += 1
            if self._stop.wait(self.cadence_seconds):
                return

    @property
    def started(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def ring(self) -> int:
        """The ring capacity (drop-oldest bound on retained samples)."""
        return int(self._ring.maxlen or 0)

    @property
    def samples_taken(self) -> int:
        """Lifetime sample count (monotonic; the ring only keeps the newest)."""
        with self._lock:
            return self._samples_taken

    @property
    def degraded_samples(self) -> int:
        """Lifetime count of degraded (partial/failed-gather) samples."""
        with self._lock:
            return self._degraded_samples

    # ------------------------------------------------------------ derivation

    def _host_of(self, tenant: str, fallback: str) -> str:
        if self.placement is not None:
            return str(self.placement.get(tenant, fallback))
        return fallback

    def rates(self, window: Optional[float] = None) -> Dict[str, Any]:
        """Per-tenant / per-host / total rates from the two newest samples.

        Deltas are clamped at zero (a restarted host's counter reset must not
        read as negative burn); the window is the real monotonic gap between
        the samples. With fewer than two samples every table is empty and
        ``window_seconds`` is ``None``.

        ``window`` widens the delta base: the oldest retained sample within
        ``window`` seconds of the newest, instead of the immediately
        preceding one. Adjacent-sample rates are exact but twitchy — one
        quiet tick reads as a rate collapse and can momentarily crown the
        wrong hot host — so trend consumers (the hot-spot tracker, shift
        verdicts) smooth over a few cadences while the gauges stay
        instantaneous.
        """
        with self._lock:
            retained = len(self._ring)
            samples = list(self._ring)
        if window is not None and len(samples) >= 2:
            newest = samples[-1]
            eligible = [s for s in samples[:-1] if newest["mono"] - s["mono"] <= window]
            samples = [eligible[0] if eligible else samples[-2], newest]
        else:
            samples = samples[-2:]
        out: Dict[str, Any] = {
            "samples": retained,
            "window_seconds": None,
            "tenants": {},
            "hosts": {},
            "total": {},
        }
        if len(samples) < 2:
            return out
        old, new = samples
        dt = new["mono"] - old["mono"]
        if dt <= 0:
            return out
        out["window_seconds"] = dt

        def delta(a: float, b: float) -> float:
            return max(0.0, float(b) - float(a)) / dt

        hosts: Dict[str, Dict[str, float]] = {}
        tenants: Dict[str, Dict[str, Any]] = {}
        old_tenants = old.get("tenants") or {}
        for tenant, row in (new.get("tenants") or {}).items():
            prev = old_tenants.get(tenant) or {}
            updates = delta(prev.get("updates", 0), row.get("updates", 0))
            computes = delta(prev.get("computes", 0), row.get("computes", 0))
            ckpt_prev = (old.get("checkpoint") or {}).get("per_tenant", {}).get(tenant, 0.0)
            ckpt_new = (new.get("checkpoint") or {}).get("per_tenant", {}).get(tenant, 0.0)
            ckpt = delta(ckpt_prev, ckpt_new)
            # host attribution: the static placement map when modeling a
            # fleet in one process, else the real per-host deltas
            if self.placement is not None:
                real = sorted((row.get("per_host") or {}).keys()) or ["0"]
                host_rates = {self._host_of(tenant, real[0]): updates}
            else:
                host_rates = {}
                prev_hosts = prev.get("per_host") or {}
                for host, sub in (row.get("per_host") or {}).items():
                    prev_sub = prev_hosts.get(host) or {}
                    host_rates[host] = delta(
                        prev_sub.get("updates", 0), sub.get("updates", 0)
                    )
                if not host_rates and updates:
                    host_rates = {"0": updates}
            tenants[tenant] = {
                "updates_per_second": updates,
                "computes_per_second": computes,
                "checkpoint_bytes_per_second": ckpt,
                "hosts": sorted(host_rates),
            }
            for host, rate in host_rates.items():
                row_h = hosts.setdefault(
                    host,
                    {"updates_per_second": 0.0, "computes_per_second": 0.0},
                )
                row_h["updates_per_second"] += rate
                # computes attribute proportionally to the update split when a
                # tenant spans hosts; with one host per tenant this is exact
                share = rate / updates if updates else 1.0 / max(1, len(host_rates))
                row_h["computes_per_second"] += computes * share
        # cost-ledger burn: per REAL host (the ledger is per metric class, so
        # a virtual placement cannot split it) plus the fleet total
        old_cost = old.get("cost") or {}
        new_cost = new.get("cost") or {}
        for host, sub in (new_cost.get("per_host") or {}).items():
            prev_sub = (old_cost.get("per_host") or {}).get(host) or {}
            row_h = hosts.setdefault(
                host, {"updates_per_second": 0.0, "computes_per_second": 0.0}
            )
            row_h["flops_per_second"] = delta(prev_sub.get("flops", 0.0), sub.get("flops", 0.0))
            row_h["bytes_per_second"] = delta(prev_sub.get("bytes", 0.0), sub.get("bytes", 0.0))
        out["tenants"] = tenants
        out["hosts"] = hosts
        out["total"] = {
            "updates_per_second": sum(t["updates_per_second"] for t in tenants.values()),
            "computes_per_second": sum(t["computes_per_second"] for t in tenants.values()),
            "flop_burn_per_second": delta(old_cost.get("flops", 0.0), new_cost.get("flops", 0.0)),
            "byte_burn_per_second": delta(old_cost.get("bytes", 0.0), new_cost.get("bytes", 0.0)),
            "checkpoint_bytes_per_second": delta(
                (old.get("checkpoint") or {}).get("bytes", 0.0),
                (new.get("checkpoint") or {}).get("bytes", 0.0),
            ),
        }
        return out

    def skew(
        self,
        rates: Optional[Dict[str, Any]] = None,
        window: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Skew signals from the per-host rate table.

        ``imbalance`` is normalized to [0, 1]: ``(max_share - 1/H) / (1 -
        1/H)`` over ``H`` hosts — 0 when every host carries an equal share, 1
        when one host carries everything, and 0 for an idle or single-host
        fleet (nothing to balance). ``max_min_ratio`` is ``None`` when the
        coldest host is fully idle (the ratio would be unbounded; the
        imbalance coefficient already saturates there). ``window`` is passed
        through to :meth:`rates` when no precomputed table is given.
        """
        rates = self.rates(window=window) if rates is None else rates
        hosts = rates.get("hosts") or {}
        loads = {host: float(row.get("updates_per_second", 0.0)) for host, row in hosts.items()}
        if self.hosts is not None and loads:
            # provisioned-but-idle hosts carried no load, so the rate table
            # never mentions them — pad them in at zero or concentration on
            # one provisioned host reads as a balanced single-host fleet
            for host in self.hosts:
                loads.setdefault(host, 0.0)
        total = sum(loads.values())
        n = len(loads)
        out: Dict[str, Any] = {
            "hosts": {},
            "imbalance": 0.0,
            "max_min_ratio": None,
            "hot_host": None,
            "cold_host": None,
            "top_tenants": {},
        }
        if not n:
            return out
        shares = {
            host: (load / total if total > 0 else 1.0 / n) for host, load in loads.items()
        }
        out["hosts"] = {
            host: {"updates_per_second": loads[host], "share": shares[host]}
            for host in sorted(loads)
        }
        hot = max(shares, key=lambda h: (shares[h], h))
        cold = min(shares, key=lambda h: (shares[h], h))
        out["hot_host"] = hot
        out["cold_host"] = cold
        if n > 1 and total > 0:
            out["imbalance"] = max(0.0, (shares[hot] - 1.0 / n) / (1.0 - 1.0 / n))
            if loads[cold] > 0:
                out["max_min_ratio"] = loads[hot] / loads[cold]
        # top-K hottest tenants per host (measured update rate, descending)
        per_host_tenants: Dict[str, List] = {}
        for tenant, row in (rates.get("tenants") or {}).items():
            for host in row.get("hosts") or []:
                per_host_tenants.setdefault(host, []).append(
                    {"tenant": tenant, "updates_per_second": row["updates_per_second"]}
                )
        out["top_tenants"] = {
            host: sorted(
                rows, key=lambda r: (-r["updates_per_second"], r["tenant"])
            )[: self.top_k]
            for host, rows in sorted(per_host_tenants.items())
        }
        return out

    def rebalance_hints(
        self,
        rates: Optional[Dict[str, Any]] = None,
        skew: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """ADVISORY ranked tenant→host candidate moves scored from measured burn.

        Each hint projects the imbalance coefficient after moving one hot-host
        tenant to the coldest host; hints are ranked best-projection first.
        Purely advisory — nothing here executes a move (that is the future
        placement controller's job); every payload says so explicitly.
        """
        rates = self.rates() if rates is None else rates
        skew = self.skew(rates) if skew is None else skew
        out: Dict[str, Any] = {
            "advisory": True,
            "note": "ranked candidate moves scored from measured burn;"
            " nothing is executed — placement stays operator-controlled",
            "hints": [],
        }
        hot, cold = skew.get("hot_host"), skew.get("cold_host")
        if hot is None or cold is None or hot == cold:
            return out
        loads = {
            host: float(row.get("updates_per_second", 0.0))
            for host, row in (skew.get("hosts") or {}).items()
        }
        total = sum(loads.values())
        n = len(loads)
        if total <= 0 or n < 2:
            return out

        def coefficient(host_loads: Dict[str, float]) -> float:
            top = max(host_loads.values())
            return max(0.0, (top / total - 1.0 / n) / (1.0 - 1.0 / n))

        current = coefficient(loads)
        # a tenant mid-migration or fenced is not movable advice: its state is
        # in flight (or its session is a zombie awaiting failover), and a
        # controller acting on the hint would double-drain it — the hint
        # ranking must join the control-plane busy set, not just the rates
        from torchmetrics_tpu.obs import scope as _scope

        busy = set(_scope.migrating_tenants()) | set(_scope.fenced_tenants())
        hints = []
        for tenant, row in (rates.get("tenants") or {}).items():
            if tenant in busy:
                continue
            if hot not in (row.get("hosts") or []):
                continue
            rate = float(row.get("updates_per_second", 0.0))
            if rate <= 0:
                continue
            moved = dict(loads)
            moved[hot] -= rate
            moved[cold] += rate
            # a counterproductive move (the whole hot load just flips hosts)
            # is not advice — only strictly improving projections rank
            if coefficient(moved) >= current:
                continue
            hints.append(
                {
                    "tenant": tenant,
                    "from": hot,
                    "to": cold,
                    "updates_per_second": rate,
                    "load_share_moved": rate / total,
                    "projected_imbalance": coefficient(moved),
                    "advisory": True,
                }
            )
        hints.sort(key=lambda h: (h["projected_imbalance"], -h["updates_per_second"], h["tenant"]))
        out["hints"] = hints[: self.top_k]
        return out

    # -------------------------------------------------------------- exports

    def record_gauges(
        self,
        recorder: Optional[trace.TraceRecorder] = None,
        now: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Write the ``fleet.*`` gauge families into the recorder.

        Totals and per-host gauges are deliberately unlabeled/host-labeled
        with ``tenant=None`` (the scope-tag opt-out); per-tenant rate gauges
        carry the tenant label. Returns a small summary dict.
        """
        rec = recorder if recorder is not None else self._rec()
        mono = float(now if now is not None else self._clock())
        with self._lock:
            latest = self._ring[-1] if self._ring else None
            n_samples = len(self._ring)
            degraded_samples = self._degraded_samples
        if latest is None:
            return {"samples": 0}
        rates = self.rates()
        skew = self.skew(rates)
        rec.set_gauge("fleet.hosts", float(latest["n_hosts"]), tenant=None)
        rec.set_gauge("fleet.missing_hosts", float(len(latest["missing_hosts"])), tenant=None)
        rec.set_gauge("fleet.degraded", 1.0 if latest["degraded"] else 0.0, tenant=None)
        rec.set_gauge("fleet.samples", float(n_samples), tenant=None)
        rec.set_gauge("fleet.degraded_samples", float(degraded_samples), tenant=None)
        rec.set_gauge(
            "fleet.sample_age_seconds", max(0.0, mono - latest["mono"]), tenant=None
        )
        rec.set_gauge("fleet.imbalance", float(skew["imbalance"]), tenant=None)
        if skew["max_min_ratio"] is not None:
            rec.set_gauge("fleet.host_ratio", float(skew["max_min_ratio"]), tenant=None)
        for host, row in skew["hosts"].items():
            rec.set_gauge("fleet.host_load_share", row["share"], host=host, tenant=None)
            rec.set_gauge(
                "fleet.host_updates_per_second",
                row["updates_per_second"],
                host=host,
                tenant=None,
            )
        total = rates.get("total") or {}
        for name, field in (
            ("fleet.updates_per_second", "updates_per_second"),
            ("fleet.computes_per_second", "computes_per_second"),
            ("fleet.flop_burn_per_second", "flop_burn_per_second"),
            ("fleet.byte_burn_per_second", "byte_burn_per_second"),
            ("fleet.checkpoint_bytes_per_second", "checkpoint_bytes_per_second"),
        ):
            if field in total:
                rec.set_gauge(name, float(total[field]), tenant=None)
        for tenant, row in (rates.get("tenants") or {}).items():
            rec.set_gauge(
                "fleet.updates_per_second", row["updates_per_second"], tenant=tenant
            )
            rec.set_gauge(
                "fleet.computes_per_second", row["computes_per_second"], tenant=tenant
            )
            if row.get("checkpoint_bytes_per_second"):
                rec.set_gauge(
                    "fleet.checkpoint_bytes_per_second",
                    row["checkpoint_bytes_per_second"],
                    tenant=tenant,
                )
        return {
            "samples": n_samples,
            "hosts": len(skew["hosts"]),
            "tenants": len(rates.get("tenants") or {}),
            "imbalance": skew["imbalance"],
        }

    # --------------------------------------------------------------- serving

    def current(self, tenant: Optional[str] = None) -> Dict[str, Any]:
        """The ``GET /fleet`` payload: merged view + rates + skew + hints.

        Per-host rows join the control-plane liveness each host shipped with
        its snapshot (lease/fence/checkpoint freshness) and the fleet alerts
        naming that host. ``tenant=`` filters the per-tenant tables (the
        server 404s unknown tenants before calling in).
        """
        with self._lock:
            latest = self._ring[-1] if self._ring else None
            merged = self._last_merged
            n_samples = len(self._ring)
            degraded_samples = self._degraded_samples
        rates = self.rates()
        skew = self.skew(rates)
        hints = self.rebalance_hints(rates, skew)
        host_rows: List[Dict[str, Any]] = []
        if merged is not None:
            alert_hosts: Dict[int, List[str]] = {}
            for alert in merged.get("alerts", ()):
                if alert.get("state") != "firing":
                    continue
                for pidx in alert.get("hosts", ()):
                    alert_hosts.setdefault(int(pidx), []).append(str(alert.get("rule")))
            for row in merged.get("hosts", ()):
                pidx = int(row.get("process_index", 0))
                status = row.get("scope_status") or {}
                checkpoints = status.get("checkpoints") or {}
                host_row = {
                    "process_index": pidx,
                    "host_id": row.get("host_id"),
                    "leases": status.get("leases") or {},
                    "fences": status.get("fences") or {},
                    "checkpoint_freshness": {
                        t: {
                            "last_unix": c.get("last_unix"),
                            "stale_after_seconds": c.get("stale_after_seconds"),
                            "closed": bool(c.get("closed")),
                        }
                        for t, c in checkpoints.items()
                    },
                    "alerts_firing": sorted(set(alert_hosts.get(pidx, []))),
                }
                share_row = skew["hosts"].get(str(pidx))
                if share_row is not None:
                    host_row["load_share"] = share_row["share"]
                    host_row["updates_per_second"] = share_row["updates_per_second"]
                host_rows.append(host_row)
        tenants = rates.get("tenants") or {}
        if tenant is not None:
            tenants = {t: row for t, row in tenants.items() if t == tenant}
            hints = dict(hints)
            hints["hints"] = [h for h in hints["hints"] if h["tenant"] == tenant]
        return {
            "sampler": {
                "cadence_seconds": self.cadence_seconds,
                "ring": self._ring.maxlen,
                "samples": n_samples,
                "degraded_samples": degraded_samples,
                "started": self.started,
                "placement": self.placement,
                "last_sample_unix": latest["unix"] if latest else None,
                "degraded": bool(latest and latest["degraded"]),
                "missing_hosts": list(latest["missing_hosts"]) if latest else [],
            },
            "window_seconds": rates.get("window_seconds"),
            "hosts": host_rows,
            "tenants": tenants,
            "total": rates.get("total") or {},
            "skew": skew,
            "rebalance": hints,
        }

    def history(
        self, window: Optional[float] = None, tenant: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        """Bounded sample history, oldest first (``GET /fleet/history``).

        ``window`` keeps only samples within that many seconds of the newest
        (monotonic stamps); ``tenant`` narrows each sample's tenant table.
        """
        with self._lock:
            samples = list(self._ring)
        if window is not None and samples:
            horizon = samples[-1]["mono"] - float(window)
            samples = [s for s in samples if s["mono"] >= horizon]
        if tenant is not None:
            samples = [
                {**s, "tenants": {t: r for t, r in (s.get("tenants") or {}).items() if t == tenant}}
                for s in samples
            ]
        return [dict(s) for s in samples]


# ------------------------------------------------------------ module singleton

# the process singleton the /metrics render chain ticks and /fleet serves —
# the robust/fence.py install_watchdog pattern exactly
_SAMPLER: Optional[FleetSampler] = None


def install_sampler(sampler: Optional[FleetSampler]) -> Optional[FleetSampler]:
    """Install (or clear, with ``None``) the process-wide fleet sampler.

    Returns the previous singleton so callers can restore it (test hygiene).
    """
    global _SAMPLER
    previous = _SAMPLER
    _SAMPLER = sampler
    return previous


def get_sampler() -> Optional[FleetSampler]:
    """The installed fleet sampler, or ``None`` (the disabled path)."""
    return _SAMPLER
