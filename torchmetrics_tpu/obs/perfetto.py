"""Chrome trace-event export: host-side spans viewable in Perfetto.

Serves the ROADMAP TPU-trace open item's host half: ``jax.profiler`` captures
*device* traces into Perfetto, but the runtime's host-side telemetry (update
dispatch spans, compile spans, collective wall time) lived only in the obs
ring buffer. This module renders that ring buffer as Chrome trace-event JSON
(the JSON array/object flavor consumed by Perfetto and ``chrome://tracing``),
so host spans load *next to* device traces:

- spans → complete ``"X"`` events (``ts``/``dur`` in microseconds) on their
  recording thread's track, so nesting is preserved exactly; pipeline stage
  spans (``engine.*`` with a ``pipeline`` label) instead get their own named
  track per pipeline, so multiple streams' dispatch cadences read side by side;
- instant events and warnings → ``"i"`` events;
- batch lineage (:mod:`~torchmetrics_tpu.obs.lineage`) → **flow events**
  (``"s"``/``"t"``/``"f"``, id = the batch's trace id): every span carrying a
  ``trace_id``/``trace_ids`` attr anchors the batch's flow, so one batch's
  ingest → dispatch → replay spans render as a visible arrow chain — across
  hosts when an aggregate is exported, because flow ids are global while each
  host keeps its own pid;
- counters and gauges → ``"C"`` counter tracks;
- the live host profiler (:mod:`~torchmetrics_tpu.obs.hostprof`), when one is
  installed → per-seam ``hostprof.samples{seam=...}`` counter tracks from its
  wall-stamped timeline ring, so host-Python attribution renders directly
  under the spans that were open while the time burned;
- **one pid per host**: a single-host export uses the local process index; a
  multi-host aggregate (``obs.aggregate.aggregate(include_events=True)``)
  renders every host as its own named process, aligned on the shared
  wall-clock anchor each recorder snapshots.

Writes are atomic (temp file + rename) like every telemetry writer.
"""

from __future__ import annotations

import json
from typing import Any, Dict, IO, List, Optional, Union

import torchmetrics_tpu.obs.trace as trace
from torchmetrics_tpu.utils.fileio import atomic_write_text

__all__ = ["chrome_trace", "write_trace"]

Source = Union[None, trace.TraceRecorder, Dict[str, Any], List[Dict[str, Any]]]


def _resolve_snapshots(source: Source) -> List[Dict[str, Any]]:
    """Normalize any accepted input to a list of host snapshots."""
    from torchmetrics_tpu.obs.aggregate import host_snapshot

    if source is None:
        return [host_snapshot(trace.get_recorder())]
    if isinstance(source, trace.TraceRecorder):
        return [host_snapshot(source)]
    if isinstance(source, list):
        return source
    if isinstance(source, dict):
        if "host_snapshots" in source:  # aggregate with events shipped
            return source["host_snapshots"]
        if source.get("aggregate"):
            raise ValueError(
                "This aggregate carries no per-host events — build it with"
                " aggregate(include_events=True) to export a cross-host trace."
            )
        return [source]  # a single host snapshot
    raise TypeError(f"Cannot build a chrome trace from {type(source).__name__}")


def _us(seconds: float) -> float:
    return round(seconds * 1e6, 3)


def chrome_trace(source: Source = None) -> Dict[str, Any]:
    """Render telemetry as a Chrome trace-event JSON object.

    ``source``: ``None`` (the live recorder), a :class:`TraceRecorder`, a host
    snapshot, a list of host snapshots, or an ``include_events=True``
    aggregate. Returns ``{"traceEvents": [...], "displayTimeUnit": "ms", ...}``
    — ``json.dump`` it (or use :func:`write_trace`) and load in Perfetto.
    """
    snaps = _resolve_snapshots(source)
    anchors = [s.get("wall_clock_anchor") for s in snaps if s.get("wall_clock_anchor") is not None]
    anchor0 = min(anchors) if anchors else 0.0

    events: List[Dict[str, Any]] = []
    # batch-lineage flow points: every span referencing a trace id (the
    # `trace_id`/`trace_ids` attrs obs/lineage.py threads through the engine)
    # contributes one point; after all hosts are rendered, each trace id's
    # points become a Chrome flow chain (s → t → f) binding that ONE batch's
    # spans into a visible arrow — across hosts, because flow ids are global
    # while pids are per host
    flow_points: Dict[str, List[Dict[str, Any]]] = {}
    for snap in sorted(snaps, key=lambda s: s.get("host", {}).get("process_index", 0)):
        meta = snap.get("host", {})
        pid = int(meta.get("process_index", 0))
        # hosts align on the shared wall-clock: each host's monotonic-relative
        # `ts` is offset by how far its session anchor sits past the earliest
        offset = (snap.get("wall_clock_anchor") or anchor0) - anchor0
        events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "ts": 0,
                "args": {"name": f"host {pid} ({meta.get('host_id', '?')})"},
            }
        )
        tids: Dict[Any, int] = {}

        def _tid(record: Dict[str, Any]) -> int:
            # pipeline stage spans (engine.dispatch etc., labeled by pipeline)
            # get their own NAMED track per pipeline label, so a trace with
            # several pipelines shows each stream's dispatch cadence separately
            # instead of interleaving them all on the recording thread's track
            attrs = record.get("attrs") or {}
            tenant = attrs.get("tenant")
            if (
                record.get("kind") == "span"
                and str(record.get("name", "")).startswith("engine.")
                and "pipeline" in attrs
            ):
                # keyed by (label, recording thread): two same-class pipelines
                # driven concurrently from different threads emit overlapping
                # spans, which on ONE track would render as garbled false
                # nesting — they get separate (identically named) tracks
                raw: Any = ("pipeline", str(attrs["pipeline"]), tenant, record.get("tid", 0))
                display = f"pipeline {attrs['pipeline']}"
                if tenant:
                    # tenant-scoped pipelines read as distinct sessions: two
                    # tenants driving the same metric class get separate tracks
                    display += f" (tenant {tenant})"
            elif record.get("kind") == "span" and tenant:
                # tenant-attributed metric spans group per (tenant, thread): a
                # serving trace reads per-session instead of one interleaved
                # wall of same-named update spans
                raw = ("tenant", str(tenant), record.get("tid", 0))
                display = f"tenant {tenant}"
            else:
                raw = record.get("tid", 0)
                display = None
            if raw not in tids:
                tids[raw] = len(tids)
                events.append(
                    {
                        "ph": "M",
                        "name": "thread_name",
                        "pid": pid,
                        "tid": tids[raw],
                        "ts": 0,
                        "args": {"name": display or f"thread {tids[raw]}"},
                    }
                )
            return tids[raw]

        for record in snap.get("events", ()):
            base = {
                "name": record["name"],
                "pid": pid,
                "tid": _tid(record),
                "ts": _us(offset + record["ts"]),
                "args": dict(record.get("attrs", {})),
            }
            if record["kind"] == "span":
                events.append({**base, "ph": "X", "cat": "span", "dur": _us(record["dur"])})
                attrs = record.get("attrs") or {}
                ids = [attrs["trace_id"]] if attrs.get("trace_id") else []
                for extra in str(attrs.get("trace_ids") or "").split(","):
                    if extra and extra not in ids:
                        ids.append(extra)
                for trace_id in ids:
                    flow_points.setdefault(trace_id, []).append(
                        {"pid": base["pid"], "tid": base["tid"], "ts": base["ts"]}
                    )
            elif record["kind"] == "warning":
                events.append({**base, "ph": "i", "cat": "warning", "s": "p"})
            else:
                events.append({**base, "ph": "i", "cat": record["kind"], "s": "t"})

        # counters/gauges have no per-sample timeline (they are cumulative /
        # last-write-wins) — render each as a counter track with one sample at
        # the capture end, so the track shows the final fleet-relevant value
        end_ts = _us(offset + float(snap.get("elapsed", 0.0)))
        for counter in snap.get("counters", ()):
            label = ",".join(f"{k}={v}" for k, v in sorted(counter["labels"].items()))
            name = counter["name"] + (f"{{{label}}}" if label else "")
            events.append(
                {
                    "ph": "C",
                    "name": name,
                    "pid": pid,
                    "tid": 0,
                    "ts": end_ts,
                    "args": {"value": counter["value"]},
                }
            )
        for gauge in snap.get("gauges", ()):
            label = ",".join(f"{k}={v}" for k, v in sorted(gauge["labels"].items()))
            name = gauge["name"] + (f"{{{label}}}" if label else "")
            events.append(
                {
                    "ph": "C",
                    "name": name,
                    "pid": pid,
                    "tid": 0,
                    "ts": end_ts,
                    "args": {"value": gauge["value"]},
                }
            )

    # the live host profiler's per-seam sample timeline renders as counter
    # tracks beside the spans: each bounded timeline bucket is wall-stamped,
    # so aligning against the earliest recorder anchor puts "which seam was
    # burning host time" directly under the span that was open while it
    # burned. Live sources only — a deserialized snapshot carries no profiler
    if source is None or isinstance(source, trace.TraceRecorder):
        try:
            from torchmetrics_tpu.obs import hostprof as _hostprof

            profiler = _hostprof.get_profiler()
        except Exception:
            profiler = None
        if profiler is not None and snaps:
            pid = int(snaps[0].get("host", {}).get("process_index", 0))
            for bucket in profiler.timeline():
                ts = _us(max(0.0, bucket["wall"] - anchor0)) if anchors else 0
                for seam, count in sorted(bucket["seams"].items()):
                    events.append(
                        {
                            "ph": "C",
                            "name": f"hostprof.samples{{seam={seam}}}",
                            "pid": pid,
                            "tid": 0,
                            "ts": ts,
                            "args": {"value": count},
                        }
                    )

    # one flow chain per trace id with at least two anchoring spans: the
    # first point starts the flow ("s"), intermediates step it ("t"), the
    # last ends it ("f") — Perfetto draws the arrow chain through every
    # anchored slice, stitching one batch's ingest → dispatch → replay story
    # across threads AND hosts (flow ids are the trace ids themselves)
    n_flows = 0
    for trace_id, points in sorted(flow_points.items()):
        if len(points) < 2:
            continue
        n_flows += 1
        points.sort(key=lambda p: p["ts"])
        for index, point in enumerate(points):
            ph = "s" if index == 0 else ("f" if index == len(points) - 1 else "t")
            flow = {
                "ph": ph,
                "cat": "lineage",
                "name": "batch",
                "id": trace_id,
                "pid": point["pid"],
                "tid": point["tid"],
                "ts": point["ts"],
            }
            if ph == "f":
                flow["bp"] = "e"  # bind the terminator to the enclosing slice
            events.append(flow)

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "torchmetrics_tpu.obs.perfetto",
            "schema_version": trace.SCHEMA_VERSION,
            "n_hosts": len(snaps),
            "n_flows": n_flows,
        },
    }


def write_trace(sink: Union[str, IO[str]], source: Source = None) -> int:
    """Write the Chrome trace JSON to ``sink``; returns the number of events.

    A string ``sink`` is written atomically (temp file + rename). Load the
    file in https://ui.perfetto.dev or ``chrome://tracing``.
    """
    doc = chrome_trace(source)
    text = json.dumps(doc)
    if isinstance(sink, str):
        atomic_write_text(sink, text)
    else:
        sink.write(text)
    return len(doc["traceEvents"])
