"""Low-overhead runtime telemetry: spans, events, counters, duration histograms.

The runtime instruments its hot seams — jit dispatch (``core/jit.py``), the
``Metric`` update/compute/forward/sync lifecycle (``core/metric.py``), and the
eager multihost collectives (``parallel/sync.py``) — through this module. The
design constraints, in order:

1. **Disabled is free.** A single module-level flag (:data:`ENABLED`); every
   instrumented call site is guarded by ``if trace.ENABLED:`` so the default
   path costs one attribute load and one branch. Nothing here imports jax or
   numpy — pure stdlib — so merely importing the runtime never pays for
   telemetry either.
2. **Enabled is bounded.** Events land in a ring buffer (``max_events``,
   default 4096, drop-oldest with a ``dropped_events`` counter); counters,
   gauges and histograms are small dicts. A week-long run cannot OOM the host
   through its own telemetry.
3. **Thread-safe.** The guarded eager collectives run in worker threads
   (``robust/degraded.py``) and user code may drive metrics from several
   threads; all recorder mutation is lock-protected, and span nesting depth is
   tracked per-thread.

Spans additionally feed a duration histogram (log-scale second buckets) keyed
by the span name plus its *string-valued* attributes — string attributes are
treated as bounded-cardinality labels (metric class, dispatch path), while
numeric attributes (payload sizes, cache sizes) stay event-only so an unbounded
value stream can never explode the histogram key space.

Egress lives in :mod:`torchmetrics_tpu.obs.export` (JSONL, Prometheus text,
summary table) and :mod:`torchmetrics_tpu.obs.profile` (``jax.profiler``
device-trace capture).
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

# batch lineage (pure stdlib): with lineage enabled, the ambient batch trace
# id (obs/lineage.py contextvar) rides duration observations as bounded
# per-bucket histogram EXEMPLARS — never as labels, so an unbounded id stream
# can never mint series; never-enabled cost is one branch per observation
import torchmetrics_tpu.obs.lineage as _lineage

# tenant/session attribution (pure stdlib, no package-internal imports): every
# recorder write passes its labels through scope.tag so an ambient
# `scope(tenant=...)` context stamps counters/gauges/histograms/spans/events
# with a bounded-cardinality `tenant` label; never-entered cost is one branch
import torchmetrics_tpu.obs.scope as _scope

__all__ = [
    "ENABLED",
    "SCHEMA_VERSION",
    "TraceRecorder",
    "annotate_current_span",
    "disable",
    "enable",
    "event",
    "get_recorder",
    "inc",
    "is_enabled",
    "observe",
    "observe_duration",
    "record_warning",
    "set_gauge",
    "span",
]

# THE enabled flag. Hot call sites guard with ``if trace.ENABLED:`` — the
# disabled path is one module-attribute load and one branch.
ENABLED = False

# Wire-format version of TraceRecorder.snapshot(). The cross-host aggregation
# (obs/aggregate.py) ships snapshots between processes that may run different
# builds; a host whose schema differs is excluded from the merge (and reported)
# instead of being mis-parsed. Bump on any structural snapshot change.
SCHEMA_VERSION = 1

_DEFAULT_MAX_EVENTS = 4096


def _host_meta() -> Dict[str, Any]:
    """Rank identity of this process: process index/count plus a stable host id.

    Snapshotting telemetry must never be the thing that *initializes* a jax
    backend (on a host with a wedged TPU tunnel, first-touch backend init
    hangs forever) — so jax is consulted only when something else has already
    imported it AND either ``jax.distributed`` is initialized (its global
    state is plain data) or a backend already exists; ``jax.process_index()``
    itself is only called in the latter, already-initialized case. Otherwise
    this is process 0 of 1.
    """
    index, count = 0, 1
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            from jax._src import distributed as _distributed  # plain state, no backend touch

            state = _distributed.global_state
            if getattr(state, "coordinator_address", None) is not None:
                index, count = int(state.process_id), int(state.num_processes)
            else:
                from jax._src import xla_bridge as _xla_bridge

                if getattr(_xla_bridge, "_backends", None):  # already initialized
                    index, count = int(jax_mod.process_index()), int(jax_mod.process_count())
        except Exception:  # private-API drift across jax versions: single-process view
            pass
    return {
        "process_index": index,
        "process_count": count,
        "host_id": f"{socket.gethostname()}:{os.getpid()}",
    }

LabelsKey = Tuple[Tuple[str, Any], ...]


def _labels_key(labels: Dict[str, Any]) -> LabelsKey:
    return tuple(sorted(labels.items()))


class _Histogram:
    """Fixed log-scale duration histogram (seconds), Prometheus-compatible.

    With batch lineage enabled (:mod:`~torchmetrics_tpu.obs.lineage`) each
    bucket additionally keeps the last :data:`EXEMPLAR_K` ``(trace_id, value,
    wall)`` **exemplars** — the OpenMetrics join from a latency bucket back to
    the concrete batch that landed in it. Exemplars are bounded per bucket,
    attach only to already-existing series (they can never mint a new label
    set), and cost nothing while lineage is off (the dict stays ``None``).
    """

    # non-cumulative per-bucket upper bounds; export computes cumulative counts
    BOUNDS: Tuple[float, ...] = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0, float("inf"))

    # exemplars kept per bucket (last-K wins: the freshest evidence is the
    # most actionable, and K bounds the memory per series)
    EXEMPLAR_K: int = 2

    __slots__ = ("counts", "sum", "count", "exemplars")

    def __init__(self) -> None:
        self.counts = [0] * len(self.BOUNDS)
        self.sum = 0.0
        self.count = 0
        self.exemplars: Optional[Dict[int, deque]] = None

    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        for i, bound in enumerate(self.BOUNDS):
            if value <= bound:
                self.counts[i] += 1
                if trace_id is not None:
                    if self.exemplars is None:
                        self.exemplars = {}
                    ring = self.exemplars.get(i)
                    if ring is None:
                        ring = self.exemplars[i] = deque(maxlen=self.EXEMPLAR_K)
                    ring.append((trace_id, value, time.time()))
                break
        self.sum += value
        self.count += 1

    def snapshot(self) -> Dict[str, Any]:
        snap = {
            "buckets": [[bound, count] for bound, count in zip(self.BOUNDS, self.counts)],
            "sum": self.sum,
            "count": self.count,
        }
        if self.exemplars:
            # additive key (absent without lineage): bucket index -> rows, so
            # pre-lineage consumers of the snapshot shape keep parsing
            snap["exemplars"] = {
                str(i): [[tid, val, wall] for tid, val, wall in ring]
                for i, ring in sorted(self.exemplars.items())
            }
        return snap


class TraceRecorder:
    """Bounded, thread-safe sink for spans/events/counters/gauges/histograms."""

    def __init__(self, max_events: int = _DEFAULT_MAX_EVENTS) -> None:
        self._lock = threading.Lock()
        self._local = threading.local()
        # cross-thread view of the per-thread span stacks: each thread's
        # thread-local stack LIST is also registered here by id, so a
        # sampling profiler (obs/hostprof.py) can join the ambient span
        # context of every thread. The lists mutate in place; a racy read
        # sees a momentarily stale but well-formed view, which is all
        # statistical sampling needs. Live context, not recorded data — it
        # survives clear().
        self._thread_stacks: Dict[int, List[Tuple[str, Dict[str, Any]]]] = {}
        self.max_events = int(max_events)
        self.clear()

    # ------------------------------------------------------------------ lifecycle

    def clear(self) -> None:
        """Drop all recorded data and restart the session clock."""
        with self._lock:
            self._events: deque = deque()
            self.dropped_events = 0
            self._counters: Dict[Tuple[str, LabelsKey], float] = {}
            self._gauges: Dict[Tuple[str, LabelsKey], float] = {}
            self._hists: Dict[Tuple[str, LabelsKey], _Histogram] = {}
            self._seen_warnings: set = set()
            self._t0 = time.monotonic()
            # wall-clock anchor paired with the monotonic session clock: lets
            # cross-host exports place hosts on one shared timeline (each
            # host's event `ts` is monotonic-relative; anchor + ts ≈ wall time)
            self._wall0 = time.time()

    def _span_stack(self) -> List[Tuple[str, Dict[str, Any]]]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
            with self._lock:
                self._thread_stacks[threading.get_ident()] = stack
        return stack

    def thread_spans(self) -> Dict[int, List[Tuple[str, Dict[str, Any]]]]:
        """Racy cross-thread snapshot of every thread's live span stack.

        ``{thread_id: [(span_name, attrs), ...]}`` innermost LAST, empty
        stacks omitted. The per-entry ``list(...)`` copy is taken without
        coordinating with the owning thread — the registry holds the live
        list objects — so a concurrent push/pop can surface a one-span-stale
        view; callers are statistical samplers where that is fine. Dead
        threads are pruned lazily once the registry grows past a bound.
        """
        with self._lock:
            if len(self._thread_stacks) > 256:
                alive = {t.ident for t in threading.enumerate()}
                for tid in [t for t in self._thread_stacks if t not in alive]:
                    del self._thread_stacks[tid]
            items = list(self._thread_stacks.items())
        out: Dict[int, List[Tuple[str, Dict[str, Any]]]] = {}
        for tid, stack in items:
            try:
                copy = list(stack)
            except Exception:
                continue
            if copy:
                out[tid] = copy
        return out

    def _append(self, record: Dict[str, Any]) -> None:
        # caller holds the lock; while (not if): the cap may have been lowered
        # below the current length via set_max_events on a live recorder
        while len(self._events) >= self.max_events:
            self._events.popleft()
            self.dropped_events += 1
        self._events.append(record)

    def set_max_events(self, max_events: int) -> None:
        """Rebound the ring buffer, evicting (and counting) the oldest events
        immediately when the new cap is below the current length."""
        if max_events <= 0:
            raise ValueError(f"Expected `max_events` to be positive, got {max_events}")
        with self._lock:
            self.max_events = int(max_events)
            while len(self._events) > self.max_events:
                self._events.popleft()
                self.dropped_events += 1

    def _restore_max_events(self, max_events: int) -> None:
        """Exit-path restore for ``observe``: reset the cap WITHOUT evicting.

        A scoped capture that raised the cap must stay exportable after the
        block ('recorded data is kept on exit'); ``_append``'s while-eviction
        re-establishes the bound at the next recording instead.
        """
        with self._lock:
            self.max_events = int(max_events)

    # ------------------------------------------------------------------ recording

    def add_event(self, name: str, kind: str = "event", **attrs: Any) -> None:
        attrs = _scope.tag(attrs)
        with self._lock:
            self._append(
                {
                    "kind": kind,
                    "name": name,
                    "ts": time.monotonic() - self._t0,
                    "tid": threading.get_ident(),
                    "attrs": attrs,
                }
            )

    def add_span(self, name: str, start: float, duration: float, depth: int, attrs: Dict[str, Any]) -> None:
        attrs = _scope.tag(attrs)
        with self._lock:
            self._append(
                {
                    "kind": "span",
                    "name": name,
                    "ts": start - self._t0,
                    "dur": duration,
                    "depth": depth,
                    "tid": threading.get_ident(),
                    "attrs": attrs,
                }
            )
            # trace ids are event-only data: an unbounded id stream must never
            # become a histogram label (series explosion) — they ride the span
            # attrs for /trace and Perfetto flows, and the histogram as a
            # bounded exemplar instead
            labels = {
                k: v
                for k, v in attrs.items()
                if isinstance(v, str) and not k.startswith("trace_id")
            }
            key = (name, _labels_key(labels))
            if not self._series_slot(self._hists, key):
                return
            hist = self._hists.get(key)
            if hist is None:
                hist = self._hists[key] = _Histogram()
            hist.observe(
                duration, _lineage.current_trace() if _lineage.ENABLED else None
            )

    def inc(self, name: str, value: float = 1.0, **labels: Any) -> None:
        key = (name, _labels_key(_scope.tag(labels)))
        with self._lock:
            if self._series_slot(self._counters, key):
                self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels: Any) -> None:
        key = (name, _labels_key(_scope.tag(labels)))
        with self._lock:
            if self._series_slot(self._gauges, key):
                self._gauges[key] = value

    def observe_duration(self, name: str, seconds: float, **labels: Any) -> None:
        key = (name, _labels_key(_scope.tag(labels)))
        with self._lock:
            if not self._series_slot(self._hists, key):
                return
            hist = self._hists.get(key)
            if hist is None:
                hist = self._hists[key] = _Histogram()
            hist.observe(
                seconds, _lineage.current_trace() if _lineage.ENABLED else None
            )

    # dedup tracks at most this many distinct warning messages: warnings with
    # per-occurrence dynamic text (embedded errors, attempt counts) would
    # otherwise grow the seen-set without bound on a long flaky run. Past the
    # cap, new messages still emit and land in the event log — they just stop
    # being dedup-tracked.
    max_tracked_warnings: int = 1024

    # cardinality cap across counter/gauge/histogram series: a long-lived
    # session that keeps constructing metric objects (fresh per-instance
    # labels) must not grow the recorder without bound. New series past the
    # cap are dropped and counted under `series.dropped`.
    max_series: int = 4096

    def _series_slot(self, table: Dict, key: Tuple[str, LabelsKey]) -> bool:
        """True when ``key`` exists or may be created; counts refused series.

        Caller holds the lock.
        """
        if key in table or len(table) < self.max_series:
            return True
        dropped = ("series.dropped", ())
        self._counters[dropped] = self._counters.get(dropped, 0.0) + 1.0
        return False

    def record_warning(self, message: str) -> bool:
        """Log a warning into the event stream; returns False for a duplicate.

        First occurrence of a message is recorded as a ``warning`` event (and
        should still be emitted through ``warnings.warn`` by the caller);
        repeats only bump the ``warnings.deduplicated`` counter.
        """
        with self._lock:
            if message in self._seen_warnings:
                key = ("warnings.deduplicated", ())
                self._counters[key] = self._counters.get(key, 0.0) + 1.0
                return False
            if len(self._seen_warnings) < self.max_tracked_warnings:
                self._seen_warnings.add(message)
            else:
                # past the dedup-tracking cap: the message still emits and
                # lands in the event log, but repeats of it can no longer be
                # deduplicated — count that loss instead of hiding it
                # (surfaced as `warnings_dropped` in summary/Prometheus)
                key = ("warnings.dropped", ())
                self._counters[key] = self._counters.get(key, 0.0) + 1.0
            key = ("warnings.emitted", ())
            self._counters[key] = self._counters.get(key, 0.0) + 1.0
            self._append(
                {
                    "kind": "warning",
                    "name": "warning",
                    "ts": time.monotonic() - self._t0,
                    "tid": threading.get_ident(),
                    "attrs": {"message": message},
                }
            )
            return True

    # ------------------------------------------------------------------ inspection

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def histogram_totals(self) -> List[Tuple[str, Dict[str, Any], float, int]]:
        """Per-histogram ``(name, labels, sum_seconds, count)`` rows.

        A cheap read for scrape-time derivations (the cost ledger's achieved-
        throughput gauges divide estimated flops by these measured span
        seconds) — ``snapshot()`` would copy the whole event ring for nothing.
        """
        with self._lock:
            return [
                (name, dict(labels), hist.sum, hist.count)
                for (name, labels), hist in self._hists.items()
            ]

    def histograms(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        """Labeled histogram snapshots, optionally filtered to one family.

        The public read behind per-family consumers (the obs server's
        ``request_stats``) — ``snapshot()`` would copy the whole event ring
        for nothing.
        """
        with self._lock:
            return [
                {"name": hist_name, "labels": dict(labels), **hist.snapshot()}
                for (hist_name, labels), hist in self._hists.items()
                if name is None or hist_name == name
            ]

    def series_counts_by_label(
        self, label: str, exclude_name_prefix: Optional[str] = None
    ) -> Dict[str, int]:
        """Distinct recorded series (counters + gauges + histograms) per value
        of ``label`` — the per-tenant cardinality read behind ``GET /tenants``
        and the ``tenant.series`` gauge family. ``exclude_name_prefix`` drops
        series families from the count (the tenant meta-gauges must not count
        themselves as tenant-owned cardinality)."""
        counts: Dict[str, int] = {}
        with self._lock:
            for table in (self._counters, self._gauges, self._hists):
                for name, labels in table:
                    if exclude_name_prefix is not None and name.startswith(exclude_name_prefix):
                        continue
                    for key, value in labels:
                        if key == label:
                            counts[str(value)] = counts.get(str(value), 0) + 1
                            break
        return counts

    def counter_value(self, name: str, **labels: Any) -> float:
        """Value of one counter (0.0 when never incremented). With no labels
        given, sums across every label set of ``name``."""
        with self._lock:
            if labels:
                return self._counters.get((name, _labels_key(labels)), 0.0)
            return sum(v for (n, _), v in self._counters.items() if n == name)

    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time copy of everything recorded, as plain python data.

        Rank-aware: carries the snapshot schema version, this process's rank
        identity (``host``), the wall-clock anchor of the session clock, and
        the elapsed session time — everything :mod:`~torchmetrics_tpu.obs.aggregate`
        needs to merge snapshots from many hosts onto one timeline.
        """
        host = _host_meta()  # resolved outside the lock: may consult jax
        with self._lock:
            return {
                "schema_version": SCHEMA_VERSION,
                "host": host,
                "wall_clock_anchor": self._wall0,
                "elapsed": time.monotonic() - self._t0,
                "events": list(self._events),
                "dropped_events": self.dropped_events,
                "counters": [
                    {"name": name, "labels": dict(labels), "value": value}
                    for (name, labels), value in sorted(self._counters.items())
                ],
                "gauges": [
                    {"name": name, "labels": dict(labels), "value": value}
                    for (name, labels), value in sorted(self._gauges.items())
                ],
                "histograms": [
                    {"name": name, "labels": dict(labels), **hist.snapshot()}
                    for (name, labels), hist in sorted(self._hists.items())
                ],
            }


_RECORDER = TraceRecorder()


def get_recorder() -> TraceRecorder:
    return _RECORDER


def is_enabled() -> bool:
    return ENABLED


def enable(max_events: Optional[int] = None, reset: bool = True) -> None:
    """Turn tracing on. ``reset`` (default) clears previously recorded data."""
    global ENABLED
    if max_events is not None:
        _RECORDER.set_max_events(max_events)
    if reset:
        _RECORDER.clear()
    ENABLED = True


def disable() -> None:
    global ENABLED
    ENABLED = False


@contextmanager
def observe(max_events: Optional[int] = None, reset: Optional[bool] = None) -> Iterator[TraceRecorder]:
    """Scoped tracing: enabled inside the block, prior state restored on exit
    (both the enabled flag and any ``max_events`` override).

    ``reset`` defaults to True when tracing was off (a fresh scoped capture)
    and False when tracing is already on — a nested ``observe`` inside a
    process-wide ``enable()`` session must not destroy the outer session's
    recorded data; for the same reason a nested observe IGNORES a
    ``max_events`` override (the ring buffer is shared, so lowering it would
    evict the outer session's events). Recorded data is *kept* on exit so the
    caller can export it::

        with obs.observe() as rec: run_epoch(...)
        print(obs.export.summary())
    """
    global ENABLED
    previous = ENABLED
    previous_max = _RECORDER.max_events
    if reset is None:
        reset = not previous
    if previous:
        max_events = None  # shared ring: never rebound under an outer session
    enable(max_events=max_events, reset=reset)
    try:
        yield _RECORDER
    finally:
        ENABLED = previous
        _RECORDER._restore_max_events(previous_max)


@contextmanager
def span(name: str, **attrs: Any) -> Iterator[None]:
    """Record a wall-clock span (monotonic clock) around the enclosed block.

    Hot call sites should guard entry with ``if trace.ENABLED:`` so the
    disabled path never pays the context-manager machinery; calling this with
    tracing off is still correct (it no-ops).
    """
    if not ENABLED:
        yield
        return
    rec = _RECORDER
    stack = rec._span_stack()
    depth = len(stack)
    stack.append((name, attrs))
    start = time.monotonic()
    try:
        yield
    finally:
        duration = time.monotonic() - start
        stack.pop()
        rec.add_span(name, start, duration, depth, attrs)


def annotate_current_span(**attrs: Any) -> None:
    """Amend the innermost open span's attributes (recorded at span exit).

    Lets a callee correct a label the caller could not know — e.g. the jit
    dispatcher rewriting ``path="jit"`` to ``path="eager_fallback"`` on the
    enclosing ``metric.update`` span when an unhashable static forces eager
    dispatch. No-op with tracing off or outside any span.
    """
    if not ENABLED:
        return
    stack = _RECORDER._span_stack()
    if stack:
        stack[-1][1].update(attrs)


def event(name: str, **attrs: Any) -> None:
    """Record an instant event (no duration)."""
    if ENABLED:
        _RECORDER.add_event(name, **attrs)


def inc(name: str, value: float = 1.0, **labels: Any) -> None:
    """Increment a counter."""
    if ENABLED:
        _RECORDER.inc(name, value, **labels)


def set_gauge(name: str, value: float, **labels: Any) -> None:
    """Set a gauge to its current value (last write wins)."""
    if ENABLED:
        _RECORDER.set_gauge(name, value, **labels)


def observe_duration(name: str, seconds: float, **labels: Any) -> None:
    """Feed one duration sample into a histogram."""
    if ENABLED:
        _RECORDER.observe_duration(name, seconds, **labels)


def record_warning(message: str) -> bool:
    """Route a warning through the event log; False means duplicate (suppress)."""
    if not ENABLED:
        return True
    return _RECORDER.record_warning(message)
