"""Telemetry egress: JSONL sink, Prometheus text exposition, summary table.

All three exporters read the same :class:`~torchmetrics_tpu.obs.trace.TraceRecorder`
snapshot and, when given live metric objects, also surface the PR-1 robustness
counters (``updates_ok`` / ``updates_skipped`` / ``updates_quarantined`` /
``quarantine_dropped`` / ``sync_degraded``) that previously had no export path.

Pure stdlib — importable (and usable for the robust counters) even where jax is
not initialised.
"""

from __future__ import annotations

import json
import math
import sys
from typing import Any, Dict, IO, Iterable, List, Optional, Tuple, Union

import torchmetrics_tpu.obs.trace as trace
from torchmetrics_tpu.utils.fileio import atomic_write_text

__all__ = [
    "OPENMETRICS_CONTENT_TYPE",
    "PROMETHEUS_CONTENT_TYPE",
    "build_info",
    "collect",
    "filter_tenant",
    "histogram_quantile",
    "openmetrics_text",
    "prometheus_text",
    "quantile_bucket",
    "summary",
    "write_jsonl",
]

# every exported series is namespaced; dots in internal names become underscores
_PROM_PREFIX = "tm_tpu_"

# the two negotiated exposition flavors the obs server serves on /metrics:
# classic text (the default — strict 0.0.4, byte-stable, exemplar-free) and
# OpenMetrics (opt-in via the Accept header — same series, plus histogram
# EXEMPLARS in `# {trace_id="..."}` syntax and a terminating `# EOF`)
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

_ROBUST_COUNTERS = ("updates_ok", "updates_skipped", "updates_quarantined", "quarantine_dropped")
_ROBUST_FLAGS = ("sync_degraded", "last_update_ok")


def _robust_snapshot(metrics: Iterable[Any]) -> List[Dict[str, Any]]:
    """Duck-typed robustness-counter rows for any objects exposing them.

    Each row carries an ``instance`` ordinal (the metric's position in the
    input iterable): two metrics of the same class (train/val accuracy) must
    not collapse into duplicate Prometheus series — a scraper rejects the
    whole page on a duplicate name+labelset.
    """
    rows = []
    for index, metric in enumerate(metrics):
        if not hasattr(metric, "updates_ok"):
            continue
        row: Dict[str, Any] = {"metric": type(metric).__name__, "instance": index}
        tenant = getattr(metric, "_obs_tenant", None)
        if tenant:
            row["tenant"] = str(tenant)
        for name in _ROBUST_COUNTERS:
            row[name] = int(getattr(metric, name, 0))
        for name in _ROBUST_FLAGS:
            row[name] = bool(getattr(metric, name, False))
        row["update_count"] = int(getattr(metric, "update_count", 0))
        rows.append(row)
    return rows


def build_info() -> Dict[str, str]:
    """Identity labels of this process's build — the ``tm_tpu_build_info`` gauge.

    Follows the node-exporter convention: a constant ``1`` gauge whose labels
    carry the versions, so dashboards can join fleet series against build
    identity. jax facts are probed lazily and safely: version only when jax is
    already imported, backend only when one is already initialized (exporting
    telemetry must never first-touch-initialize a wedged backend — the
    ``trace._host_meta`` contract).
    """
    try:
        from torchmetrics_tpu import __version__ as version
    except Exception:  # pragma: no cover - partial installs
        version = "unknown"
    jax_version = "not-imported"
    backend = "uninitialized"
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        jax_version = str(getattr(jax_mod, "__version__", "unknown"))
        try:
            from jax._src import xla_bridge as _xla_bridge

            if getattr(_xla_bridge, "_backends", None):  # already initialized
                backend = str(jax_mod.default_backend())
        except Exception:  # private-API drift: stay at "uninitialized"
            pass
    return {
        "version": str(version),
        "jax": jax_version,
        "backend": backend,
        "process_index": str(trace._host_meta()["process_index"]),
    }


def filter_tenant(snap: Dict[str, Any], tenant: str) -> Dict[str, Any]:
    """Project a snapshot onto one tenant's series, in place.

    Keeps only counters/gauges/histograms labeled ``tenant=<tenant>``, events
    whose attrs carry it, robust rows of metrics registered under it, and (when
    present) that tenant's registry row — the ``?tenant=`` scoped view. Meta
    fields (host identity, build info, dropped-event counts) stay: a scoped
    page is still a page about *this* process.
    """
    for kind in ("counters", "gauges", "histograms"):
        snap[kind] = [row for row in snap.get(kind, ()) if row["labels"].get("tenant") == tenant]
    snap["events"] = [
        ev for ev in snap.get("events", ()) if (ev.get("attrs") or {}).get("tenant") == tenant
    ]
    if "robust" in snap:
        snap["robust"] = [row for row in snap["robust"] if row.get("tenant") == tenant]
    if "tenants" in snap:
        snap["tenants"] = [row for row in snap["tenants"] if row.get("tenant") == tenant]
    if "alerts" in snap:
        snap["alerts"] = [row for row in snap["alerts"] if row.get("tenant") == tenant]
    snap["tenant_filter"] = tenant
    return snap


def collect(
    metrics: Iterable[Any] = (),
    recorder: Optional[trace.TraceRecorder] = None,
    tenant: Optional[str] = None,
) -> Dict[str, Any]:
    """One plain-data snapshot: recorder state + per-metric robust counters.

    ``tenant`` scopes the snapshot to one tenant's series (see
    :func:`filter_tenant`).
    """
    rec = recorder if recorder is not None else trace.get_recorder()
    snap = rec.snapshot()
    snap["robust"] = _robust_snapshot(metrics)
    snap["build_info"] = build_info()
    if tenant is not None:
        filter_tenant(snap, tenant)
    return snap


# ------------------------------------------------------------------------- JSONL


def write_jsonl(
    sink: Union[str, IO[str]],
    metrics: Iterable[Any] = (),
    recorder: Optional[trace.TraceRecorder] = None,
) -> int:
    """Write the full snapshot as JSON Lines; returns the number of lines.

    Line types (``"type"`` field): ``meta`` (one, first), then every ``span`` /
    ``event`` / ``warning`` in ring-buffer order, then ``counter`` / ``gauge`` /
    ``histogram`` series, then one ``robust`` line per metric. Writing to a
    path is atomic (temp file + rename): a crash mid-export never leaves a
    truncated JSONL masquerading as a complete one.
    """
    snap = collect(metrics, recorder)
    lines: List[str] = []

    def emit(obj: Dict[str, Any]) -> None:
        lines.append(json.dumps(obj, sort_keys=True, default=str))

    emit(
        {
            "type": "meta",
            "schema_version": snap["schema_version"],
            "process_index": snap["host"]["process_index"],
            "host_id": snap["host"]["host_id"],
            "wall_clock_anchor": snap["wall_clock_anchor"],
            "dropped_events": snap["dropped_events"],
            "events": len(snap["events"]),
            "build_info": snap["build_info"],
        }
    )
    for ev in snap["events"]:
        # attrs stay namespaced: event attrs are free-form user data and must
        # not clobber the structural type/name/ts/dur fields
        record = {"type": ev["kind"], "name": ev["name"], "ts": round(ev["ts"], 6), "attrs": ev["attrs"]}
        if "dur" in ev:
            record["dur"] = round(ev["dur"], 6)
            record["depth"] = ev["depth"]
        emit(record)
    for counter in snap["counters"]:
        emit({"type": "counter", **counter})
    for gauge in snap["gauges"]:
        emit({"type": "gauge", **gauge})
    for hist in snap["histograms"]:
        emit(
            {
                "type": "histogram",
                "name": hist["name"],
                "labels": hist["labels"],
                "buckets": [[("inf" if math.isinf(b) else b), c] for b, c in hist["buckets"]],
                "sum": round(hist["sum"], 6),
                "count": hist["count"],
            }
        )
    for row in snap["robust"]:
        emit({"type": "robust", **row})

    text = "\n".join(lines) + "\n"
    if isinstance(sink, str):
        atomic_write_text(sink, text)
    else:
        sink.write(text)
    return len(lines)


# -------------------------------------------------------------------- Prometheus


def _prom_name(name: str) -> str:
    return _PROM_PREFIX + "".join(c if (c.isalnum() or c == "_") else "_" for c in name)


def _prom_escape(value: Any) -> str:
    # text-format spec: backslash, double-quote and newline must be escaped in
    # label values; labels are public API so any string can arrive here
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    body = ",".join(f'{key}="{_prom_escape(value)}"' for key, value in sorted(labels.items()))
    return "{" + body + "}"


def _prom_value(value: float) -> str:
    value = float(value)
    if value.is_integer():
        return str(int(value))
    return repr(value)


def _prom_help_escape(text: str) -> str:
    # text-format spec: only backslash and newline are escaped in HELP text
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _prom_header(out: List[str], prom: str, kind: str, help_text: str) -> None:
    """Well-formed family header: one ``# HELP`` then one ``# TYPE`` line."""
    out.append(f"# HELP {prom} {_prom_help_escape(help_text)}")
    out.append(f"# TYPE {prom} {kind}")


# specific HELP text for the memory-accounting gauge families (obs/memory.py);
# everything else gets the generic last-recorded-value wording below
_GAUGE_HELP = {
    "memory.state_bytes": "Unique accumulated metric-state bytes (children included, aliased buffers deduped)",
    "memory.state_device_bytes": "Device-resident share of the unique metric-state bytes (incl. MaskedBuffer capacity)",
    "memory.state_host_bytes": "Host-resident share of the unique metric-state bytes (numpy states, defaults, quarantine)",
    "state.list_items": "Ragged list-state items currently held (grows unbounded without compute+reset)",
    "memory.device_bytes_in_use": "jax device.memory_stats() bytes_in_use (absent on backends without memory stats)",
    "memory.device_peak_bytes_in_use": "jax device.memory_stats() peak_bytes_in_use (absent on backends without memory stats)",
    "memory.snapshot_payload_bytes": "Bytes of the last cross-host telemetry snapshot shipped by this host",
    "engine.queue_depth": "Batches accumulated in the streaming engine's open fusion chunk",
    "engine.in_flight": "Dispatched-but-unawaited chunks in the streaming engine's async window",
    "engine.fused_chunk_size": "Batch count of the streaming engine's last fused scan dispatch",
    # XLA cost-ledger families (obs/cost.py): per-metric-class rollups of what
    # the compiled programs are estimated to cost vs what they measurably achieve
    "cost.compiled_variants": "AOT-compiled executables in the XLA cost ledger for this metric class",
    "cost.compile_seconds": "Summed XLA compile wall-seconds the metric class's variants cost",
    "cost.flops_per_dispatch": "Estimated flops per dispatch (dispatch-weighted mean over the class's compiled variants)",
    "cost.bytes_per_dispatch": "Estimated bytes accessed per dispatch (dispatch-weighted mean over the class's compiled variants)",
    "cost.estimated_flops": "Cumulative estimated flops dispatched (per-variant XLA cost_analysis x dispatch count)",
    "cost.estimated_bytes": "Cumulative estimated bytes accessed (per-variant XLA cost_analysis x dispatch count)",
    "cost.peak_memory_bytes": "Max argument+output+temp bytes any of the class's compiled variants holds live at once",
    "cost.achieved_flops_per_second": "Estimated flops divided by measured update/dispatch span seconds",
    "flight.records": "Per-batch lineage records currently held in the pipeline flight-recorder ring",
    # value-health + alerting families (obs/values.py, obs/alerts.py)
    "value.current": "Latest computed metric value per scalar leaf (the value-health timeline's head)",
    "alerts": "ALERTS-style series: 1 while the named alert is pending/firing, 0 on resolve",
    "alerts.firing": "Alerts currently in the firing state",
    "alerts.pending": "Alerts currently dwelling in the pending state (for_seconds not yet met)",
    "alerts.time_to_fire_seconds": "Latest episode's pending-to-firing wall delta for this (rule, series)",
    "alerts.time_to_resolve_seconds": "Latest episode's firing-to-resolved wall delta for this (rule, series)",
    # tenant/session attribution families (obs/scope.py): bounded-cardinality
    # per-tenant liveness, with the overflow bucket loud by design
    "tenant.updates": "Metric updates billed to this tenant (ambient scope or captured attribution)",
    "tenant.computes": "Fresh metric computes billed to this tenant",
    "tenant.active_pipelines": "Live MetricPipeline sessions currently registered under this tenant",
    "tenant.series": "Recorder series (counters+gauges+histograms) carrying this tenant's label",
    "tenant.last_activity_age_seconds": "Wall-clock seconds since this tenant's last recorded activity",
    "tenant.registered": "Tenants currently in the bounded tenant registry (cap: max_tenants)",
    "tenant.overflow_collapsed": "Distinct past-cap tenant names collapsed into the __overflow__ bucket",
    # cost-aware admission families (obs/scope.py AdmissionController): quota
    # pressure per tenant, with tenant.quota_exceeded the AlertRule-compatible
    # 0/1 signal (threshold series rules turn it into a firing alert)
    "tenant.quota_exceeded": "1 while the tenant's current window burn is at/over a quota limit, 0 otherwise",
    "tenant.quota_burn_ratio": "Max used/limit ratio across the tenant's metered quota dimensions this window",
    "tenant.quota_shed": "Lifetime update batches dropped for this tenant by over-quota shed decisions",
    "tenant.quota_deferred": "Lifetime update batches deprioritized for this tenant by over-quota defer decisions",
    "tenant.quota_window_updates": "Update batches admitted for this tenant in the current quota window",
    "tenant.quota_window_flops": "Estimated flops billed to this tenant in the current quota window (cost-ledger priced)",
    "tenant.quota_window_bytes": "Estimated bytes-accessed billed to this tenant in the current quota window",
    "tenant.quota_window_compile_seconds": "XLA compile wall-seconds billed to this tenant in the current quota window",
    "tenant.quota_priority": "Admission priority class of this tenant's quota (higher drains first from deferred backlogs)",
    # cross-tenant multiplexer families (engine/mux.py): one fused vmap
    # dispatch folds many tenants' same-signature updates
    "engine.mux_width": "Tenant count of the multiplexer's last fused dispatch (pre-padding)",
    "engine.mux_open_groups": "Same-signature tenant groups currently accumulating in the multiplexer",
    # continuous-checkpointing families (engine/migrate.py CheckpointPolicy):
    # crash-recovery liveness per tenant session, refreshed per scrape
    "checkpoint.last_success_age_seconds": "Wall-clock seconds since the tenant session's last successful periodic bundle",
    "checkpoint.write_seconds": "Wall seconds the last continuous-checkpoint bundle write took",
    "checkpoint.bundle_bytes": "Mean bundle bytes per checkpoint kind (full vs delta) for this tenant session",
    "checkpoint.bundles": "Continuous-checkpoint bundles written per kind (full vs delta)",
    "checkpoint.failures": "Continuous-checkpoint writes that failed (stream kept flowing; staleness grows)",
    # batch-lineage index families (obs/lineage.py): the bounded trace-id
    # index's cardinality, measured — eviction is visible, never silent
    "lineage.traces": "Live per-batch lineage records in the bounded trace-id index",
    "lineage.evicted": "Lineage records evicted from the bounded trace-id index (oldest-first)",
    "lineage.minted": "Trace ids minted by this process since the index was last reset",
    # hung-host fencing families (robust/fence.py + engine/migrate.py): session
    # leases, the fence ledger, and what recovery scans reject along the way
    "lease.seconds_to_expiry": "Seconds until this tenant session's lease expires (negative: expired, holder suspect)",
    "lease.active": "Unreleased session leases this process currently tracks",
    "lease.expired": "Leases past expiry that are neither released nor fenced (the watchdog's pending work)",
    "fence.fenced_epochs": "Session epochs fenced off as zombies (each one is a completed or pending failover)",
    "fence.bundles_rejected": "Post-fence zombie bundle writes rejected by recovery scans (counted, never restored)",
    "fence.bundles_swept": "Post-fence zombie bundles garbage-collected from disk by retention sweeps",
    "fence.failover_yielded": "Failovers this process stood down from after losing the durable claim-file election",
    "checkpoint.torn_bundles": "Torn/corrupt checkpoint bundles recovery scans skipped while selecting a restore point",
    # fleet telemetry plane families (obs/fleet.py): continuous cross-host
    # sampling, rate derivation from consecutive samples, and skew signals
    "fleet.hosts": "Hosts contributing to the newest merged fleet sample",
    "fleet.missing_hosts": "Hosts absent from the newest fleet sample (hung or unreachable; degraded, not stalled)",
    "fleet.degraded": "1 while the newest fleet sample is a degraded partial view, 0 when every host reported",
    "fleet.samples": "Fleet samples currently retained in the bounded drop-oldest ring",
    "fleet.degraded_samples": "Fleet samples taken degraded (partial gather) since the sampler was constructed",
    "fleet.sample_age_seconds": "Seconds since the fleet sampler last completed a sample (staleness of the view)",
    "fleet.imbalance": "Normalized fleet load-imbalance coefficient: 0 perfectly even, 1 all load on one host",
    "fleet.host_ratio": "Hottest-host load divided by coldest-host load (absent while the coldest host is idle)",
    "fleet.host_load_share": "This host's fraction of the fleet's update rate over the newest sample window",
    "fleet.host_updates_per_second": "Metric updates per second attributed to this host over the newest sample window",
    "fleet.updates_per_second": "Metric updates per second over the newest sample window (fleet total, or per tenant)",
    "fleet.computes_per_second": "Fresh metric computes per second over the newest sample window (fleet total, or per tenant)",
    "fleet.flop_burn_per_second": "Estimated cost-ledger flops per second burned fleet-wide over the newest sample window",
    "fleet.byte_burn_per_second": "Estimated cost-ledger bytes-accessed per second fleet-wide over the newest sample window",
    "fleet.checkpoint_bytes_per_second": "Checkpoint bundle bytes written per second over the newest sample window",
    # continuous host-profiler families (obs/hostprof.py): the Python-floor
    # attribution plane — all gauges (point-in-time sampler state), never _total
    "hostprof.samples": "Host stack samples taken and attributed (serving/scrape-thread samples excluded)",
    "hostprof.samples_serving": "Host stack samples landing in obs-server scrape threads (never billed to a tenant seam)",
    "hostprof.dropped_stacks": "Distinct collapsed stacks refused past the bounded stack-table cap",
    "hostprof.sample_errors": "Sampler iterations that raised and were swallowed (the sampler never kills the run)",
    "hostprof.rate_hz": "Configured host-profiler sampling rate in Hz",
    "hostprof.self_overhead_percent": "Measured sampler busy time as a percent of profiled wall time",
    "hostprof.attributed_percent": "Percent of attributable host samples landing in a named runtime seam (not 'other')",
    "hostprof.seam_seconds": "Sampled host seconds attributed to the labeled runtime seam",
    # conservation-audit families (obs/audit.py): the exactly-once accounting
    # plane — all gauges (point-in-time ledger state), never _total
    "audit.sessions": "Pipeline/mux sessions the conservation auditor is tracking (live + frozen)",
    "audit.approximate": "1 when the ledger is honest-approximate (lineage or fold-id eviction occurred), else 0",
    "audit.fed": "Batches fed to the labeled tenant across non-fenced epochs (arrival-counter ledger total)",
    "audit.processed": "Batches processed (folded minus quarantined/skipped) for the labeled tenant across non-fenced epochs",
    "audit.shed": "Batches shed by admission for the labeled tenant across non-fenced epochs",
    "audit.deferred_pending": "Deferred batches still awaiting replay for the labeled tenant",
    "audit.violations": "Conservation-audit violations (per labeled invariant, plus the unlabeled total the audit_violation preset watches)",
    # placement control-plane families (fleet/placement.py): the tenant→host
    # assignment table, rebalance moves and hysteresis-episode convergence —
    # all gauges (point-in-time controller state), never _total
    "placement.assignments": "Tenants currently assigned a host in the placement controller's table",
    "placement.host_tenants": "Tenants the placement table currently assigns to the labeled host",
    "placement.moves_in_flight": "Rebalance moves (drain->checkpoint->restore->replay) currently executing",
    "placement.moves_started": "Rebalance moves the controller has started since construction",
    "placement.moves_completed": "Rebalance moves completed successfully since construction",
    "placement.moves_failed": "Rebalance moves that failed (tenant left on its origin host) since construction",
    "placement.rebalancing": "1 while a hysteresis episode is open (imbalance above the high-water band), else 0",
    "placement.convergence_seconds": "Wall seconds the last closed hysteresis episode took to converge below the low-water band",
    "placement.decision_age_seconds": "Seconds since the placement controller last logged a decision",
}


def _gauge_help(name: str) -> str:
    specific = _GAUGE_HELP.get(name)
    if specific is not None:
        return f"{specific} (torchmetrics_tpu.obs)"
    return f"Last recorded value of `{name}` (torchmetrics_tpu.obs)"


# specific HELP text for histogram families; the default wording below covers
# span-derived duration histograms
_HIST_HELP = {
    "server.request": "Obs-server HTTP request duration by route — the self-instrumented scrape latency",
}


def _hist_help(name: str) -> str:
    specific = _HIST_HELP.get(name)
    if specific is not None:
        return f"{specific} (torchmetrics_tpu.obs)"
    return f"Duration distribution of `{name}` in seconds (torchmetrics_tpu.obs)"


def _render_exposition(snap: Dict[str, Any], openmetrics: bool) -> str:
    """One exposition walk, two flavors.

    ``openmetrics=False`` renders exactly the classic 0.0.4 page (byte-stable:
    the strict-parser goldens lock it) — histogram exemplars that may exist in
    the snapshot are **dropped**, because the classic text format has no
    exemplar syntax and a classic scraper must keep parsing unchanged.
    ``openmetrics=True`` renders the OpenMetrics flavor: counter family
    headers drop the ``_total`` suffix (samples keep it), histogram bucket
    lines carry their bucket's freshest exemplar as
    ``# {trace_id="..."} <value> <timestamp>``, and the page ends ``# EOF``.
    Exemplars reference already-existing series only — they can never mint a
    new label set.
    """
    out: List[str] = []

    def header(sample_name: str, family_name: str, kind: str, help_text: str) -> None:
        name = family_name if openmetrics else sample_name
        _prom_header(out, name, kind, help_text)

    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for counter in snap["counters"]:
        by_name.setdefault(counter["name"], []).append(counter)
    for name in sorted(by_name):
        prom = _prom_name(name) + "_total"
        header(prom, _prom_name(name), "counter", f"Cumulative count of `{name}` events (torchmetrics_tpu.obs)")
        for counter in by_name[name]:
            out.append(f"{prom}{_prom_labels(counter['labels'])} {_prom_value(counter['value'])}")

    by_name = {}
    for gauge in snap["gauges"]:
        by_name.setdefault(gauge["name"], []).append(gauge)
    for name in sorted(by_name):
        prom = _prom_name(name)
        header(prom, prom, "gauge", _gauge_help(name))
        for gauge in by_name[name]:
            out.append(f"{prom}{_prom_labels(gauge['labels'])} {_prom_value(gauge['value'])}")

    by_name = {}
    for hist in snap["histograms"]:
        by_name.setdefault(hist["name"], []).append(hist)
    for name in sorted(by_name):
        prom = _prom_name(name) + "_seconds"
        header(prom, prom, "histogram", _hist_help(name))
        for hist in by_name[name]:
            exemplars = hist.get("exemplars") or {}
            cumulative = 0
            for index, (bound, count) in enumerate(hist["buckets"]):
                cumulative += count
                le = "+Inf" if math.isinf(bound) else f"{bound:g}"
                labels = _prom_labels({**hist["labels"], "le": le})
                line = f"{prom}_bucket{labels} {cumulative}"
                if openmetrics:
                    rows = exemplars.get(str(index)) or exemplars.get(index)
                    if rows:
                        trace_id, value, wall = rows[-1]  # freshest exemplar wins
                        line += (
                            f' # {{trace_id="{_prom_escape(trace_id)}"}}'
                            f" {float(value):.9g} {float(wall):.3f}"
                        )
                out.append(line)
            out.append(f"{prom}_sum{_prom_labels(hist['labels'])} {_prom_value(hist['sum'])}")
            out.append(f"{prom}_count{_prom_labels(hist['labels'])} {hist['count']}")

    if snap["robust"]:

        def _robust_labels(row: Dict[str, Any]) -> Dict[str, Any]:
            labels = {"instance": str(row["instance"]), "metric": row["metric"]}
            if row.get("tenant"):
                labels["tenant"] = row["tenant"]
            return labels

        for name in _ROBUST_COUNTERS:
            prom = _prom_name("robust." + name) + "_total"
            header(
                prom,
                _prom_name("robust." + name),
                "counter",
                f"Per-metric robustness counter `{name}` (torchmetrics_tpu.robust)",
            )
            for row in snap["robust"]:
                out.append(f"{prom}{_prom_labels(_robust_labels(row))} {row[name]}")
        for name in _ROBUST_FLAGS:
            prom = _prom_name("robust." + name)
            header(prom, prom, "gauge", f"Per-metric robustness flag `{name}` (torchmetrics_tpu.robust)")
            for row in snap["robust"]:
                out.append(f"{prom}{_prom_labels(_robust_labels(row))} {int(row[name])}")

    prom = _prom_name("dropped_events") + "_total"
    header(
        prom,
        _prom_name("dropped_events"),
        "counter",
        "Events evicted from the telemetry ring buffer (torchmetrics_tpu.obs)",
    )
    out.append(f"{prom} {snap['dropped_events']}")

    # node-exporter-style identity gauge: constant 1, labels carry the build
    prom = _prom_name("build_info")
    header(
        prom, prom, "gauge",
        "Build identity of this process: package/jax versions, backend, process index (torchmetrics_tpu.obs)",
    )
    out.append(f"{prom}{_prom_labels(snap['build_info'])} 1")
    if openmetrics:
        out.append("# EOF")
    return "\n".join(out) + "\n"


def prometheus_text(
    metrics: Iterable[Any] = (),
    recorder: Optional[trace.TraceRecorder] = None,
    tenant: Optional[str] = None,
) -> str:
    """Prometheus text exposition (0.0.4) of counters, gauges, histograms and
    the per-metric robust counters. Every family gets a ``# HELP`` + ``# TYPE``
    header; histograms emit cumulative ``_bucket`` lines whose ``le`` labels
    end in ``+Inf`` plus ``_sum``/``_count``. ``tenant`` scopes the page to one
    tenant's series (``/metrics?tenant=``); meta families (build info, dropped
    events) stay on the scoped page. Deliberately **exemplar-free**: batch
    lineage never changes a byte of the classic page
    (:func:`openmetrics_text` is the exemplar-carrying flavor).
    """
    snap = collect(metrics, recorder, tenant=tenant)
    return _render_exposition(snap, openmetrics=False)


def openmetrics_text(
    metrics: Iterable[Any] = (),
    recorder: Optional[trace.TraceRecorder] = None,
    tenant: Optional[str] = None,
) -> str:
    """OpenMetrics exposition: the classic series plus histogram exemplars.

    Served by the obs server when a scraper's ``Accept`` header asks for
    ``application/openmetrics-text`` (:data:`OPENMETRICS_CONTENT_TYPE`).
    Histogram ``_bucket`` lines carry their bucket's freshest
    ``(trace_id, value, wall)`` exemplar (:mod:`~torchmetrics_tpu.obs.lineage`)
    in OpenMetrics exemplar syntax, so a dashboard can jump from a p99 latency
    bucket straight to ``GET /trace/<id>``; the page terminates with
    ``# EOF``.
    """
    snap = collect(metrics, recorder, tenant=tenant)
    return _render_exposition(snap, openmetrics=True)


# ------------------------------------------------------------------- quantiles


def quantile_bucket(buckets: List[List[float]], q: float) -> Optional[Tuple[float, float]]:
    """``(lower, upper)`` bounds of the bucket holding the ``q``-quantile.

    The single implementation of the cumulative bucket-selection walk —
    :func:`histogram_quantile` derives its midpoint estimate from this, and
    consumers that need the estimate's error bar (the chaos bench's
    scrape-latency spreads) read the same bucket, so the two can never
    disagree about which bucket the quantile landed in. The open-ended
    ``+Inf`` bucket reports ``(lower, lower)``. Returns ``None`` for an
    empty histogram.
    """
    if not 0.0 < q <= 1.0:
        raise ValueError(f"Expected quantile in (0, 1], got {q}")
    total = sum(count for _, count in buckets)
    if not total:
        return None
    target = q * total
    cumulative = 0.0
    lower = 0.0
    for bound, count in buckets:
        cumulative += count
        if cumulative >= target and count:
            if math.isinf(bound):
                return (lower, lower)
            return (lower, bound)
        if not math.isinf(bound):
            lower = bound
    return (lower, lower)  # pragma: no cover - cumulative always reaches target above


def histogram_quantile(buckets: List[List[float]], q: float) -> Optional[float]:
    """Estimate the ``q``-quantile of a bucketed duration histogram (seconds).

    ``buckets`` is the snapshot shape — ``[[upper_bound, count], ...]`` with
    *non-cumulative* per-bucket counts, bounds ascending and ending ``+Inf``.
    Estimation is **bucket-midpoint interpolation**: the quantile lands in the
    first bucket whose cumulative count reaches ``q * total``
    (:func:`quantile_bucket`) and is reported as that bucket's midpoint
    (``(lower + upper) / 2``); the open-ended ``+Inf`` bucket reports its
    lower bound (the only defensible point). With log-scale buckets this is a
    coarse-but-honest estimate — the error is bounded by the bucket width,
    which the summary tables document. Returns ``None`` for an empty
    histogram.
    """
    bucket = quantile_bucket(buckets, q)
    if bucket is None:
        return None
    lower, upper = bucket
    return (lower + upper) / 2.0


def _quantile_cols(hist: Dict[str, Any]) -> str:
    """`` p50=...us p95=...us`` columns for a summary-table histogram row."""
    p50 = histogram_quantile(hist["buckets"], 0.50)
    p95 = histogram_quantile(hist["buckets"], 0.95)
    if p50 is None or p95 is None:
        return ""
    return f" p50~{p50 * 1e6:9.1f}us p95~{p95 * 1e6:9.1f}us"


# ----------------------------------------------------------------- summary table


def summary(metrics: Iterable[Any] = (), recorder: Optional[trace.TraceRecorder] = None) -> str:
    """Human-readable summary of the recorded telemetry."""
    snap = collect(metrics, recorder)
    lines: List[str] = ["== torchmetrics_tpu obs summary =="]

    if snap["counters"]:
        lines.append("-- counters --")
        width = max(len(c["name"]) for c in snap["counters"])
        for counter in snap["counters"]:
            label = " ".join(f"{k}={v}" for k, v in sorted(counter["labels"].items()))
            lines.append(f"  {counter['name']:<{width}}  {_prom_value(counter['value']):>10}  {label}")

    if snap["gauges"]:
        lines.append("-- gauges --")
        width = max(len(g["name"]) for g in snap["gauges"])
        for gauge in snap["gauges"]:
            label = " ".join(f"{k}={v}" for k, v in sorted(gauge["labels"].items()))
            lines.append(f"  {gauge['name']:<{width}}  {_prom_value(gauge['value']):>10}  {label}")

    if snap["histograms"]:
        lines.append("-- durations --")
        width = max(len(h["name"]) for h in snap["histograms"])
        for hist in snap["histograms"]:
            label = " ".join(f"{k}={v}" for k, v in sorted(hist["labels"].items()))
            mean = hist["sum"] / hist["count"] if hist["count"] else 0.0
            lines.append(
                f"  {hist['name']:<{width}}  n={hist['count']:<6} total={hist['sum'] * 1e3:9.3f}ms"
                f" mean={mean * 1e6:9.1f}us{_quantile_cols(hist)}  {label}"
            )

    if snap["robust"]:
        lines.append("-- robust --")
        for row in snap["robust"]:
            flags = " ".join(f"{name}={int(row[name])}" for name in _ROBUST_FLAGS)
            counts = " ".join(f"{name.split('_', 1)[1]}={row[name]}" for name in _ROBUST_COUNTERS)
            lines.append(f"  {row['metric']}[{row['instance']}]: {counts} {flags}")

    counters = {
        (c["name"], tuple(sorted(c["labels"].items()))): c["value"] for c in snap["counters"]
    }
    emitted = counters.get(("warnings.emitted", ()), 0)
    deduped = counters.get(("warnings.deduplicated", ()), 0)
    dropped_tracking = counters.get(("warnings.dropped", ()), 0)
    if emitted or deduped or dropped_tracking:
        lines.append(
            f"-- warnings: {_prom_value(emitted)} emitted,"
            f" {_prom_value(deduped)} deduplicated,"
            f" {_prom_value(dropped_tracking)} past dedup cap (warnings_dropped) --"
        )
    lines.append(f"-- events: {len(snap['events'])} recorded, {snap['dropped_events']} dropped --")
    return "\n".join(lines) + "\n"
